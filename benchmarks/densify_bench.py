"""Measure the sparse-upload densify path against dense device_put.

The round-3 cold-path numbers (c5 first src-TopN 2378 ms vs 86-126 ms
repeat) are transfer-bound: candidate blocks ship as dense words at the
~1.1 GB/s tunnel rate. The sparse path ships set words bucketed by
128-lane group ([T, 256, G] lane/value slots — ops.packed.bucket_rows)
and densifies on device with G vectorized one-hot OR passes
(ops.pallas_kernels.densify_pallas). This harness measures, at c5-scale
block shapes:

- dense leg:   pack host → device_put [T, 32768] u32      (status quo)
- sparse leg:  device_put lane/val [T, 256, G] + densify  (new path)

plus the kernel-only dispatch time and first-call compile cost, and
writes benchmarks/DENSIFY.json. Run on the real chip.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "DENSIFY.json")


def main() -> None:
    import jax

    from pilosa_tpu.ops import packed
    from pilosa_tpu.ops.pallas_kernels import densify_pallas

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(5)
    W = packed.WORDS_PER_SLICE  # 32768
    subs = W // 128

    out = {"platform": platform, "cases": []}
    # (tiles, set bits per row): c5-ish 256 slices x 64 candidates at
    # ~2000 and ~30 bits/row, and a denser 16K-bit variant for the
    # crossover. Every row reuses one synthetic sparse pattern.
    for t_rows, bits_per_row in ((256 * 64, 2000), (256 * 64, 30),
                                 (2048, 16000)):
        pos = np.sort(
            rng.choice(W * 32, size=bits_per_row, replace=False))
        widx = (pos >> 5).astype(np.int64)
        bitv = (np.uint32(1) << (pos & 31).astype(np.uint32))
        starts = np.concatenate(([0], np.flatnonzero(np.diff(widx)) + 1))
        uidx = widx[starts]
        uval = np.bitwise_or.reduceat(bitv, starts)
        # bucket one row, then broadcast to T rows
        groups = uidx >> 7
        counts = np.bincount(groups, minlength=subs)
        g_pad = 1 << (max(1, int(counts.max())) - 1).bit_length()
        st = np.zeros(subs + 1, np.int64)
        np.cumsum(counts, out=st[1:])
        rank = np.arange(len(uidx)) - st[groups]
        lane1 = np.zeros((subs, g_pad), np.uint32)
        val1 = np.zeros((subs, g_pad), np.uint32)
        lane1[groups, rank] = (uidx & 127).astype(np.uint32)
        val1[groups, rank] = uval
        lanes = np.broadcast_to(lane1, (t_rows, subs, g_pad)).copy()
        vals = np.broadcast_to(val1, (t_rows, subs, g_pad)).copy()

        dense = np.zeros((t_rows, W), np.uint32)
        dense[:, uidx] = uval

        jax.device_put(dense[:64]).block_until_ready()  # warm path
        t0 = time.perf_counter()
        d = jax.device_put(dense)
        d.block_until_ready()
        dense_s = time.perf_counter() - t0
        del d

        t0 = time.perf_counter()
        dl, dv = jax.device_put(lanes), jax.device_put(vals)
        jax.block_until_ready((dl, dv))
        upload_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        got = densify_pallas(dl, dv, W)
        got.block_until_ready()
        first_kernel_s = time.perf_counter() - t0  # includes compile
        ok = bool((np.asarray(got[:2]) == dense[:2]).all())
        t0 = time.perf_counter()
        for _ in range(8):
            got = densify_pallas(dl, dv, W)
        got.block_until_ready()
        kernel_ms = (time.perf_counter() - t0) / 8 * 1e3
        del dl, dv, got

        case = {
            "tiles": t_rows, "bits_per_row": bits_per_row,
            "g_slots": int(g_pad),
            "dense_mb": round(dense.nbytes / 1e6, 1),
            "sparse_mb": round((lanes.nbytes + vals.nbytes) / 1e6, 1),
            "dense_put_s": round(dense_s, 3),
            "sparse_put_s": round(upload_s, 3),
            "densify_first_s": round(first_kernel_s, 3),
            "densify_ms": round(kernel_ms, 2),
            "sparse_total_s": round(upload_s + kernel_ms / 1e3, 3),
            "speedup": round(dense_s / (upload_s + kernel_ms / 1e3), 2),
            "verified": ok,
        }
        print(json.dumps(case), flush=True)
        out["cases"].append(case)

    with open(OUT, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"wrote": OUT}))


if __name__ == "__main__":
    main()
