"""Measure the sparse-upload densify path against dense device_put.

The round-3 cold-path numbers (c5 first src-TopN 2378 ms vs 86-126 ms
repeat) are transfer-bound: candidate blocks ship as dense words at the
~1.1 GB/s tunnel rate. The sparse path ships (word idx, word value)
pairs and densifies on device (ops.pallas_kernels.densify_pallas).
This harness measures, at a c5-scale block shape:

- dense leg:   pack host → device_put [T, 32768] u32      (the status quo)
- sparse leg:  device_put idx/val [T, P] + densify kernel (the new path)

plus the kernel-only dispatch time and first-call compile cost, and
writes benchmarks/DENSIFY.json. Run on the real chip.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "DENSIFY.json")


def main() -> None:
    import jax

    from pilosa_tpu.ops import packed
    from pilosa_tpu.ops.pallas_kernels import densify_pallas

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(5)
    W = packed.WORDS_PER_SLICE  # 32768

    out = {"platform": platform, "cases": []}
    # (tiles, set bits per row) — c5-ish: 256 slices x 64 candidates,
    # ~2000 bits/row (the suite's ranked-frame density), and a denser
    # variant to find the crossover.
    for t_rows, bits_per_row in ((256 * 64, 2000), (256 * 64, 30),
                                 (2048, 16000)):
        # synth sparse rows: bits_per_row distinct positions per row
        pos = np.sort(
            rng.choice(W * 32, size=bits_per_row, replace=False))
        widx = (pos >> 5).astype(np.int32)
        vals = (np.uint32(1) << (pos & 31).astype(np.uint32))
        starts = np.concatenate(([0], np.flatnonzero(np.diff(widx)) + 1))
        uidx = widx[starts]
        uval = np.bitwise_or.reduceat(vals, starts)
        p_pad = -(-len(uidx) // 512) * 512
        idx = np.zeros((t_rows, p_pad), np.int32)
        val = np.zeros((t_rows, p_pad), np.uint32)
        idx[:, :len(uidx)] = uidx
        val[:, :len(uval)] = uval

        dense = np.zeros((t_rows, W), np.uint32)
        dense[:, uidx] = uval

        # dense leg: transfer the packed words
        jax.device_put(dense[:64]).block_until_ready()  # warm path
        t0 = time.perf_counter()
        d = jax.device_put(dense)
        d.block_until_ready()
        dense_s = time.perf_counter() - t0
        del d

        # sparse leg: transfer pairs + densify
        t0 = time.perf_counter()
        di, dv = jax.device_put(idx), jax.device_put(val)
        jax.block_until_ready((di, dv))
        upload_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        got = densify_pallas(di, dv, W)
        got.block_until_ready()
        first_kernel_s = time.perf_counter() - t0  # includes compile
        ok = bool((np.asarray(got[:2]) == dense[:2]).all())
        # kernel-only, chained
        t0 = time.perf_counter()
        for _ in range(8):
            got = densify_pallas(di, dv, W)
        got.block_until_ready()
        kernel_ms = (time.perf_counter() - t0) / 8 * 1e3
        del di, dv, got

        case = {
            "tiles": t_rows, "bits_per_row": bits_per_row,
            "pairs_per_row": int(len(uidx)), "p_padded": int(p_pad),
            "dense_mb": round(dense.nbytes / 1e6, 1),
            "sparse_mb": round((idx.nbytes + val.nbytes) / 1e6, 1),
            "dense_put_s": round(dense_s, 3),
            "sparse_put_s": round(upload_s, 3),
            "densify_first_s": round(first_kernel_s, 3),
            "densify_ms": round(kernel_ms, 2),
            "sparse_total_s": round(upload_s + kernel_ms / 1e3, 3),
            "speedup": round(dense_s / (upload_s + kernel_ms / 1e3), 2),
            "verified": ok,
        }
        print(json.dumps(case), flush=True)
        out["cases"].append(case)

    with open(OUT, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"wrote": OUT}))


if __name__ == "__main__":
    main()
