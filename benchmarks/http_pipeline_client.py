"""Pipelined single-connection HTTP SetBit client (suite leg
config_http_pipelined_setbit drives this as a subprocess so the
server-side measurement is GIL-clean).

Responses are parsed with proper Content-Length framing (a substring
count would miscount across recv boundaries); any non-200 response or
early close aborts with rc=1 so the suite records an error instead of
an inflated number.

Usage: http_pipeline_client.py <host> <port> <n_ops>
"""

import select
import socket
import sys
import time

host, port, N = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])


def req(path: str, body: bytes) -> bytes:
    return (f"POST {path} HTTP/1.1\r\nHost: x\r\n"
            f"Content-Length: {len(body)}\r\n\r\n").encode() + body


def drain_responses(buf: bytearray) -> tuple[int, bool]:
    """(complete responses consumed from buf, saw_error)."""
    n = 0
    while True:
        end = buf.find(b"\r\n\r\n")
        if end < 0:
            return n, False
        head = bytes(buf[:end]).decode("latin-1")
        status = head.split(" ", 2)[1]
        length = 0
        for ln in head.split("\r\n")[1:]:
            k, _, v = ln.partition(":")
            if k.lower() == "content-length":
                length = int(v)
        total = end + 4 + length
        if len(buf) < total:
            return n, False
        if status != "200":
            sys.stderr.write(f"non-200 response: {status}\n")
            return n, True
        del buf[:total]
        n += 1


def read_n(s: socket.socket, buf: bytearray, n: int) -> None:
    """Consume exactly n framed responses (setup handshake — a bare
    recv could leave a split response's tail to be miscounted later)."""
    got = 0
    while got < n:
        inc, bad = drain_responses(buf)
        got += inc
        if bad:
            sys.exit(1)
        if got < n:
            data = s.recv(65536)
            if not data:
                sys.stderr.write("closed during setup\n")
                sys.exit(1)
            buf += data


def main() -> int:
    s = socket.create_connection((host, port))
    setup_buf = bytearray()
    s.sendall(req("/index/i", b"{}") + req("/index/i/frame/f", b"{}"))
    read_n(s, setup_buf, 2)
    if setup_buf:
        sys.stderr.write("unexpected bytes after setup\n")
        return 1

    blob = b"".join(
        req("/index/i/query",
            f'SetBit(frame="f", rowID={i % 50},'
            f' columnID={i * 13 % (1 << 20)})'.encode())
        for i in range(N))
    s.setblocking(False)
    sent = 0
    done = 0
    buf = bytearray()
    view = memoryview(blob)
    t0 = time.perf_counter()
    deadline = t0 + 180
    while done < N:
        if time.perf_counter() > deadline:
            sys.stderr.write(f"timed out at {done}/{N}\n")
            return 1
        r, w, _ = select.select([s], [s] if sent < len(blob) else [],
                                [], 5)
        if w:
            sent += s.send(view[sent:sent + (1 << 20)])
        if r:
            data = s.recv(1 << 20)
            if not data:
                sys.stderr.write(f"early close at {done}/{N}\n")
                return 1
            buf += data
            got, bad = drain_responses(buf)
            done += got
            if bad:
                return 1
    el = time.perf_counter() - t0
    print(f"RESULT {done / el:.0f} op/s responses={done}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
