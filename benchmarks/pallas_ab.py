"""Kernel-level Pallas-vs-XLA A/B at the BASELINE target shapes.

Round-3 verdict: the Pallas kernels are the TPU serving default, yet no
recorded measurement shows them beating XLA fusion anywhere — the
default was faith, not data. This harness settles it: each serving-path
kernel pair runs both legs at the literal benchmark shapes —

- ``op_count``           at bench.py's 1 B-bit chained-dispatch shape
                         (16 rows x 2^25 u32 words),
- ``expr_count_rows``    at the c4/c5 mesh Count shape (2-leaf
                         intersect over 256 slices) and the c3 shape
                         (10 slices),
- ``topn_block_count``   at the c3 exact-count shape (10 slices x 1000
                         candidates) and a c5-scale block (256 slices),

and persists both legs + the winner to ``benchmarks/PALLAS_AB.json``,
which bench.py stamps into the round artifact. The serving default
(ops.pallas_kernels.pallas_mode) is then chosen from this record — the
analogue of the reference dispatching to asm only when CPUID proves it
pays (roaring/assembly_asm.go:15,40-80).

Methodology (matches bench.py): the tunnel's ~65 ms sync floor would
swamp per-call timing, so each measurement chains N asynchronous
dispatches and syncs once; reported ms is per dispatch. XLA legs run
before Pallas legs (device-queue contamination drains forward), and
both legs verify against numpy before timing.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "PALLAS_AB.json")


def _chain_ms(fn, n_iters: int, *args) -> float:
    """Per-dispatch ms over n_iters chained async dispatches, 1 sync."""
    import jax
    out = fn(*args)
    jax.block_until_ready(out)  # warmup/compile outside the window
    t0 = time.perf_counter()
    for _ in range(n_iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n_iters * 1e3


def _median(vals):
    return sorted(vals)[len(vals) // 2]


def _ab(name, xla_fn, pallas_fn, args, n_iters, trials=3, meta=None):
    import jax
    want = np.asarray(jax.block_until_ready(xla_fn(*args)))
    got = np.asarray(jax.block_until_ready(pallas_fn(*args)))
    assert (want == got).all(), f"{name}: leg mismatch"
    xla_ms = _median([_chain_ms(xla_fn, n_iters, *args)
                      for _ in range(trials)])
    pal_ms = _median([_chain_ms(pallas_fn, n_iters, *args)
                      for _ in range(trials)])
    row = {"kernel": name, "xla_ms": round(xla_ms, 3),
           "pallas_ms": round(pal_ms, 3),
           "pallas_over_xla": round(pal_ms / xla_ms, 3),
           "winner": "pallas" if pal_ms < xla_ms else "xla",
           "n_iters": n_iters, **(meta or {})}
    print(json.dumps(row), flush=True)
    return row


def main() -> None:
    import jax

    from pilosa_tpu.ops import pallas_kernels as pk
    from pilosa_tpu.ops.kernels import op_count_rows

    platform = jax.devices()[0].platform
    if platform != "tpu":
        print(json.dumps({"skipped": f"platform={platform}"}))
        return
    rng = np.random.default_rng(11)
    rows_out = []

    # --- op_count at the metric-of-record shape: 16 x 1 B-bit rows.
    n_words = 1 << 25
    a = jax.device_put(rng.integers(0, 2**32, (16, n_words), np.uint32))
    b = jax.device_put(rng.integers(0, 2**32, (16, n_words), np.uint32))
    rows_out.append(_ab(
        "op_count_1Gbit_rows",
        lambda x, y: op_count_rows("and", x, y),
        lambda x, y: pk.op_count_rows_pallas("and", x, y),
        (a, b), n_iters=64, meta={"shape": [16, n_words]}))
    # single long row (the fold-into-8 path) — 1 x 1 B bits
    a1, b1 = a[0], b[0]
    rows_out.append(_ab(
        "op_count_single_1Gbit_row",
        lambda x, y: op_count_rows("and", x, y),
        lambda x, y: pk.op_count_rows_pallas("and", x, y),
        (a1, b1), n_iters=64, meta={"shape": [1, n_words]}))
    del a, b, a1, b1

    # --- expr_count_rows: Count(Intersect(a,b)) per slice-row.
    expr = ("and", ("leaf", 0), ("leaf", 1))
    w = (1 << 20) // 32
    for n_slices, tag in ((256, "c5_256slices"), (10, "c3_10slices")):
        leaves = jax.device_put(
            rng.integers(0, 2**32, (2, n_slices, w), np.uint32))
        rows_out.append(_ab(
            f"expr_count_rows_{tag}",
            lambda lv: _xla_expr_count(expr, lv),
            lambda lv: pk.expr_count_rows_pallas(expr, lv),
            (leaves,), n_iters=128, meta={"shape": [2, n_slices, w]}))
        del leaves

    # --- topn_block_count: popcount(row & src) per (slice, candidate).
    for n_slices, n_cand, tag in ((10, 1000, "c3_10x1000"),
                                  (256, 64, "c5_256x64")):
        blk = jax.device_put(
            rng.integers(0, 2**32, (n_slices, n_cand, w), np.uint32))
        src = jax.device_put(
            rng.integers(0, 2**32, (1, n_slices, w), np.uint32))
        sexpr = ("leaf", 0)
        rows_out.append(_ab(
            f"topn_block_count_{tag}",
            lambda r, s: _xla_topn_block(sexpr, r, s),
            lambda r, s: pk.topn_block_count_pallas(sexpr, r, s),
            (blk, src), n_iters=32,
            meta={"shape": [n_slices, n_cand, w]}))
        del blk, src

    summary = {
        "platform": platform,
        "results": rows_out,
        "pallas_wins": sum(r["winner"] == "pallas" for r in rows_out),
        "total": len(rows_out),
    }
    with open(OUT_PATH, "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps({"wrote": OUT_PATH,
                      "pallas_wins": summary["pallas_wins"],
                      "total": summary["total"]}))


def _make_xla_legs():
    """Module-level jitted XLA legs (a fresh jit wrapper per call would
    recompile per dispatch and time the compiler, not the kernel —
    exactly the bug the first run of this harness had)."""
    import functools

    import jax
    import jax.numpy as jnp

    from pilosa_tpu.ops.kernels import _BITWISE

    def ev(node, lv):
        if node[0] == "leaf":
            return lv[node[1]]
        return _BITWISE[node[0]](ev(node[1], lv), ev(node[2], lv))

    @functools.partial(jax.jit, static_argnums=0)
    def expr_count(e, lv):
        pc = jax.lax.population_count(ev(e, lv)).astype(jnp.int32)
        return jnp.sum(pc, axis=-1)

    @functools.partial(jax.jit, static_argnums=0)
    def topn_block(e, r, lv):
        words = jnp.bitwise_and(r, ev(e, lv)[:, None, :])
        pc = jax.lax.population_count(words).astype(jnp.int32)
        return jnp.sum(pc, axis=-1)

    return expr_count, topn_block


_XLA_EXPR_COUNT = None
_XLA_TOPN_BLOCK = None


def _xla_expr_count(expr, leaves):
    global _XLA_EXPR_COUNT, _XLA_TOPN_BLOCK
    if _XLA_EXPR_COUNT is None:
        _XLA_EXPR_COUNT, _XLA_TOPN_BLOCK = _make_xla_legs()
    return _XLA_EXPR_COUNT(expr, leaves)


def _xla_topn_block(expr, rows, leaves):
    global _XLA_EXPR_COUNT, _XLA_TOPN_BLOCK
    if _XLA_TOPN_BLOCK is None:
        _XLA_EXPR_COUNT, _XLA_TOPN_BLOCK = _make_xla_legs()
    return _XLA_TOPN_BLOCK(expr, rows, leaves)


if __name__ == "__main__":
    main()
