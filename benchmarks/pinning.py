"""Shared best-ever pinning for benchmarks/HOST_BASELINE.json.

Both bench.py (read denominator, best = LOWEST seconds) and the suite's
write denominator (best = HIGHEST ops/s) persist per-machine best-ever
host-native measurements here; one writer keeps the record schema and
error handling in one place. Keys carry the hostname so a faster rig's
measurement never poisons another rig's ratio.
"""

from __future__ import annotations

import json
import os
import time

PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "HOST_BASELINE.json")


def pin(key: str, field: str, value: float, better) -> float:
    """Update HOST_BASELINE.json[key][field] with ``value`` when
    ``better(value, recorded)`` says it improves; returns the pinned
    (monotone best-ever) value either way. ``better`` is e.g.
    ``lambda new, old: new < old`` for seconds."""
    record = {}
    try:
        with open(PATH) as f:
            record = json.load(f)
    except (OSError, ValueError):
        pass
    best = record.get(key, {}).get(field)
    if best is None or better(value, best):
        record[key] = {field: value,
                       "updated": time.strftime("%Y-%m-%d")}
        try:
            with open(PATH, "w") as f:
                json.dump(record, f, indent=1, sort_keys=True)
        except OSError:
            pass
        return value
    return best
