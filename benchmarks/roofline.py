"""Roofline accounting for the metric of record + untunneled v5e-8
projections for BASELINE configs 4-5 (VERDICT r4 item 4).

Two kinds of numbers, explicitly labeled:

- MEASUREMENT: arithmetic over recorded single-chip numbers (bench.py's
  ops/s, PALLAS_AB kernel times) — no modeling.
- PROJECTION: what the same kernels would do on a v5e-8 with no
  benchmark tunnel, from measured kernel times scaled by the sharding
  factor plus stated overhead assumptions. Device legs on this rig pay
  a ~63-65 ms host<->device sync floor per dispatch through the axon
  tunnel (memory: every dispatch is a round trip), which is why
  config-4/5 device legs lose to host HERE while the kernels win by
  5-11x — the projection is the evidence that the loss is a harness
  artifact, not a design property.

Writes benchmarks/ROOFLINE.json and prints it.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

# Public v5e per-chip specs (cloud.google.com/tpu/docs/v5e): 819 GB/s
# HBM bandwidth, 16 GB HBM. Used only as the denominator for the
# "fraction of peak" measurement and for sanity-checking projections.
V5E_HBM_GBPS = 819.0

# Projection assumptions (stated, conservative):
# - per-dispatch overhead without the tunnel: 0.3 ms (jit dispatch +
#   host sync on a local PCIe/ICI-attached chip; the tunnel's 63 ms
#   floor replaced by a local sync).
DISPATCH_S = 0.3e-3
# - one small-payload ICI collective (psum of K counts / gather of a
#   <1 MB pair table) on a v5e-8 ring: 50 us is the conservative end of
#   public all-reduce latency for tiny payloads.
ICI_SMALL_COLLECTIVE_S = 50e-6


def _kernel_ms(ab: dict, name: str) -> float:
    for r in ab["results"]:
        if r["kernel"] == name:
            return min(r["xla_ms"], r["pallas_ms"])
    raise KeyError(name)


def compute(metric_ops_s: float | None = None) -> dict:
    with open(os.path.join(HERE, "PALLAS_AB.json")) as f:
        ab = json.load(f)

    out: dict = {"v5e_hbm_peak_gbps": V5E_HBM_GBPS}

    # ---- MEASUREMENT: effective HBM bandwidth of the metric of record.
    # One Intersect+Count op on 2^30-bit rows streams both operands
    # from HBM once: 2 * 2^30/8 bytes = 256 MiB.
    if metric_ops_s is None:
        # Latest recorded bench line (BENCH_r{N}.json wraps the line of
        # record in a "tail" string).
        try:
            import re
            bench_files = sorted(
                (f for f in os.listdir(os.path.join(HERE, ".."))
                 if re.match(r"BENCH_r\d+\.json$", f)),
                key=lambda f: int(re.search(r"\d+", f).group()))
            with open(os.path.join(HERE, "..", bench_files[-1])) as f:
                rec = json.load(f)
            line = json.loads(rec["tail"]) if "tail" in rec else rec
            # Only the canonical 2^30-bit shape matches the hardcoded
            # bytes/op below; older lines without a "bits" field are
            # all canonical (the field arrived with the guard).
            if line.get("bits", 1 << 30) != (1 << 30):
                metric_ops_s = None
            else:
                metric_ops_s = line["value"]
        except (OSError, ValueError, KeyError, IndexError):
            metric_ops_s = None
    if metric_ops_s:
        bytes_per_op = 2 * (1 << 30) // 8
        eff = metric_ops_s * bytes_per_op / 1e9
        out["metric_of_record"] = {
            "kind": "measurement",
            "note": "computed from the quoted run's ops/s; shared-VM "
                    "slots swing ops/s (and thus GB/s) ~±10% run to "
                    "run — compare same-run canaries, not absolutes",
            "ops_per_s": metric_ops_s,
            "bytes_per_op": bytes_per_op,
            "arithmetic": f"{metric_ops_s:.0f} ops/s x {bytes_per_op}"
                          f" B = {eff:.0f} GB/s",
            "effective_hbm_gbps": round(eff, 1),
            "fraction_of_v5e_peak": round(eff / V5E_HBM_GBPS, 3),
        }

    # ---- PROJECTION: config 4 — Count(Intersect) over 256 slices on
    # a v5e-8. Measured single-chip kernel: expr_count_rows over
    # [2 leaves, 256 slices, 32768 words]. Sharded 32 slices/chip the
    # per-chip kernel runs on 1/8 the data; add dispatch + one psum.
    k4_ms = _kernel_ms(ab, "expr_count_rows_c5_256slices")
    proj4_s = k4_ms / 1e3 / 8 + DISPATCH_S + ICI_SMALL_COLLECTIVE_S
    out["config4_count_256slices_v5e8"] = {
        "kind": "projection",
        "single_chip_kernel_ms_measured": k4_ms,
        "arithmetic": (f"{k4_ms:.3f} ms / 8 chips + {DISPATCH_S * 1e3:.1f}"
                       f" ms dispatch + {ICI_SMALL_COLLECTIVE_S * 1e6:.0f}"
                       f" us psum = {proj4_s * 1e3:.3f} ms"),
        "projected_latency_ms": round(proj4_s * 1e3, 3),
        "projected_ops_per_s": round(1.0 / proj4_s, 1),
        "assumptions": {"dispatch_ms": DISPATCH_S * 1e3,
                        "ici_collective_us":
                            ICI_SMALL_COLLECTIVE_S * 1e6},
    }

    # ---- PROJECTION: config 5 — cluster TopN on 1 B columns (1024
    # slices), exact phase over ~64 candidates. Measured single-chip
    # kernel: topn_block_count over [256 slices, 64 rows, 32768 words];
    # 1024 slices = 4x the data, sharded over 8 chips = x4/8 per chip.
    # The pair-table gather (<1 MB) rides one ICI collective.
    k5_ms = _kernel_ms(ab, "topn_block_count_c5_256x64")
    proj5_s = (k5_ms * 4 / 8) / 1e3 + DISPATCH_S + ICI_SMALL_COLLECTIVE_S
    out["config5_topn_1024slices_v5e8"] = {
        "kind": "projection",
        "single_chip_kernel_ms_measured_256slices": k5_ms,
        "arithmetic": (f"{k5_ms:.3f} ms x 4 (1024/256 slices) / 8 chips"
                       f" + {DISPATCH_S * 1e3:.1f} ms dispatch +"
                       f" {ICI_SMALL_COLLECTIVE_S * 1e6:.0f} us gather"
                       f" = {proj5_s * 1e3:.3f} ms"),
        "projected_exact_phase_ms": round(proj5_s * 1e3, 3),
        "assumptions": {"dispatch_ms": DISPATCH_S * 1e3,
                        "ici_collective_us":
                            ICI_SMALL_COLLECTIVE_S * 1e6},
    }
    return out


# -- measured projection constants (VERDICT r5 weak #7) -----------------------
# The 0.3 ms dispatch / 50 us collective numbers above were ASSUMED.
# measure_constants() times them on this rig: a null-kernel dispatch
# (jit'd identity on a tiny operand, per-call with a sync) and an
# 8-device virtual-mesh psum of a tiny payload. On a TPU-tunnel rig
# the dispatch number IS the tunnel sync floor; on CPU it is the local
# jit dispatch + sync the projection assumes — either way the value is
# recorded NEXT TO the assumption with its platform, so the projection
# is no longer built on unmeasured constants.

_MEASURE_MARK = "MEASURED_CONSTANTS:"


def _measure_worker() -> None:
    """Runs in a subprocess with an 8-device virtual CPU mesh (or the
    real backend when one is attached); prints one marked JSON line."""
    import time

    import numpy as np

    import jax
    import jax.numpy as jnp

    def per_call_s(fn, arg, n=50):
        fn(arg).block_until_ready()  # compile
        best = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                out = fn(arg)
            out.block_until_ready()
            best.append((time.perf_counter() - t0) / n)
        return sorted(best)[1]

    # Null-kernel dispatch: the fixed per-dispatch cost with no real
    # compute or transfer behind it.
    tiny = jax.device_put(np.zeros(8, np.float32))
    null_s = per_call_s(jax.jit(lambda x: x + 1), tiny)

    # 8-device mesh psum of a tiny payload: the small-collective cost.
    sys.path.insert(0, os.path.dirname(HERE))
    from pilosa_tpu.parallel import mesh as mesh_mod
    mesh = mesh_mod.make_mesh()
    n_dev = int(mesh.shape[mesh_mod.AXIS_SLICES])
    fn = jax.jit(mesh_mod._shard_map(
        lambda x: jax.lax.psum(jnp.sum(x), mesh_mod.AXIS_SLICES),
        mesh=mesh,
        in_specs=(mesh_mod.P(mesh_mod.AXIS_SLICES),),
        out_specs=mesh_mod.P()))
    shard = mesh_mod.shard_slices(mesh,
                                  np.zeros((n_dev, 16), np.float32))
    psum_s = per_call_s(fn, shard)

    print(_MEASURE_MARK + json.dumps({
        "dispatch_ms": round(null_s * 1e3, 4),
        "psum_ms": round(psum_s * 1e3, 4),
        # The collective alone ~= the psum dispatch minus the null
        # dispatch (both pay the same fixed cost), floored at 0.
        "ici_collective_us": round(max(0.0, psum_s - null_s) * 1e6, 2),
        "devices": n_dev,
        "platform": jax.devices()[0].platform,
    }), flush=True)


def _measure_once(env: dict, timeout_s: float) -> dict | None:
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--measure-worker"],
            timeout=timeout_s, capture_output=True, text=True,
            env=env)
    except subprocess.TimeoutExpired:
        return None
    for line in proc.stdout.splitlines():
        if line.startswith(_MEASURE_MARK):
            return json.loads(line[len(_MEASURE_MARK):])
    return None


def measure_constants(timeout_s: float = 180.0) -> dict | None:
    """Measure the projection constants in a bounded subprocess. The
    first attempt keeps whatever backend the rig attaches (a real TPU
    measures the actual tunnel dispatch floor — the number the
    assumption stands in for); only if that fails does a CPU-forced
    retry run, so a broken tunnel still yields a labeled CPU-platform
    number instead of nothing. The virtual-device XLA flag only
    affects the host platform, so it is safe to set either way."""
    env = dict(os.environ)
    env.setdefault("XLA_FLAGS",
                   "--xla_force_host_platform_device_count=8")
    out = _measure_once(env, timeout_s)
    if out is None and env.get("JAX_PLATFORMS") != "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        out = _measure_once(env, timeout_s)
    if out is not None:
        out["method"] = ("null-kernel dispatch (jit identity,"
                         " per-call sync) and a mesh psum of a tiny"
                         " payload on this rig's backend (platform/"
                         "devices recorded); collective = psum - null"
                         " dispatch")
    return out


def _stamp_measured(out: dict, measured: dict | None) -> None:
    """Record measured: values NEXT TO the assumed constants."""
    if not measured:
        return
    out["measured_constants"] = measured
    for key in ("config4_count_256slices_v5e8",
                "config5_topn_1024slices_v5e8"):
        assumptions = out.get(key, {}).get("assumptions")
        if assumptions is not None:
            assumptions["dispatch_ms_measured"] = measured["dispatch_ms"]
            assumptions["ici_collective_us_measured"] = \
                measured["ici_collective_us"]
            assumptions["measured_platform"] = measured["platform"]


def main() -> None:
    # Preserve the fields bench.py owns (recent-run median headline,
    # best_observed) — a roofline re-run must not reset the metric
    # history, and the headline recomputes from that history.
    path = os.path.join(HERE, "ROOFLINE.json")
    try:
        with open(path) as f:
            prior = json.load(f)
    except (OSError, ValueError):
        prior = {}
    recent = prior.get("recent_runs") or []
    metric_ops_s = None
    if recent:
        import statistics
        metric_ops_s = float(statistics.median(recent[-5:]))
    out = compute(metric_ops_s=metric_ops_s)
    if recent:
        out["metric_of_record"]["kind"] = \
            "measurement (median of recent runs)"
        latest = prior.get("metric_of_record", {}) \
            .get("latest_run_ops_per_s")
        if latest is not None:
            out["metric_of_record"]["latest_run_ops_per_s"] = latest
        out["recent_runs"] = recent
    if "best_observed" in prior:
        out["best_observed"] = prior["best_observed"]
    # A failed/timed-out measurement must not erase the last good one
    # (same carry-forward contract as recent_runs/best_observed).
    _stamp_measured(out, measure_constants()
                    or prior.get("measured_constants"))
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    if "--measure-worker" in sys.argv[1:]:
        _measure_worker()
    else:
        main()
