"""Benchmark suite for the BASELINE.md target configurations.

Prints one JSON line per config. `bench.py` at the repo root remains the
single-metric benchmark of record; this suite covers the remaining
BASELINE.json configs for documentation and regression tracking:

1. Single-fragment Intersect+Count on two 1M-column rows (config 1) —
   through the Fragment/query layer, host path vs device kernel.
2. Union/Difference over 1K rows in one slice, mixed container kinds
   (config 2) — device row-block fold vs the C++/numpy host kernel.
3. TopN(n) over a rows×columns frame with a source bitmap (config 3) —
   p50 latency of the executor's exact-count phase, host vs mesh.
4. Count(Intersect) across N slices on the device mesh (config 4) —
   mesh.count_expr, the mapReduce replacement.
5. Cluster-style TopN across N slices (config 5, single-host form) —
   mesh.topn_exact; the multi-host leg adds HTTP remote legs on top.

Timing through the TPU tunnel: per-call sync costs ~65 ms regardless of
payload, so each measurement chains dispatches and syncs once
(see bench.py's methodology note), except the latency configs (3) where
the sync IS part of the reported p50.

Env: PILOSA_BENCH_SCALE (default 1.0) scales row/slice counts down for
smoke runs; PILOSA_BENCH_DEVICE=0 skips device measurements.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

SCALE = float(os.environ.get("PILOSA_BENCH_SCALE", "1.0"))
USE_DEVICE = os.environ.get("PILOSA_BENCH_DEVICE", "1") != "0"

# One persistent XLA compile cache for the whole pass (and its
# subprocesses): suite-spawned servers live in temp dirs that are
# deleted mid-run, so without this the first such server would arm the
# process-global cache at a doomed path; with it, repeated passes also
# reuse each other's compilations (the restart-latency story the
# compile_stability config measures).
if "PILOSA_TPU_COMPILE_CACHE" not in os.environ:
    from pilosa_tpu.utils import cache_dir as _cache_dir
    os.environ["PILOSA_TPU_COMPILE_CACHE"] = _cache_dir("xla-suite")


# Every emit of this pass, in order — main() folds them into
# benchmarks/MANIFEST.json so "which run wrote this artifact" is
# answerable (VERDICT r5 weak #7).
_EMITTED: list[dict] = []


def emit(metric: str, value: float, unit: str, **extra) -> None:
    line = {"metric": metric, "value": round(value, 4),
            "unit": unit, **extra}
    _EMITTED.append(line)
    print(json.dumps(line), flush=True)


# Canonical artifact file per metric family: the one JSON a consumer
# should read for that number (everything else is a historical or
# intermediate record). bench.py owns ROOFLINE.json; this suite owns
# the rest.
_CANONICAL_ARTIFACTS = {
    "intersect_count": "ROOFLINE.json",
    "write_path": "WRITEPATH.json",
    "distributed_topn": "DISTRIBUTED.json",
    "resize": "RESIZE.json",
    "topn1000": "TOPN1000.json",
    "pallas_ab": "PALLAS_AB.json",
    "densify": "DENSIFY.json",
    "host_baselines": "HOST_BASELINE.json",
    "latency_under_load": "LATENCY.json",
    "tenant_isolation": "TENANTS.json",
    "tiered": "TIERED.json",
    "planner": "PLANNER.json",
    "replay": "REPLAY.json",
}


def write_manifest(partial: bool = False) -> None:
    """benchmarks/MANIFEST.json: THE index of benchmark truth — which
    artifact file is canonical per metric family, plus this pass's
    metrics with their same-pass canary (the measured tunnel sync
    floor) and canary-normalized ratios. Cross-round comparisons
    should compare vs_canary, not absolute values: the shared VM slot
    swings absolutes ~±10%, and "whichever run last wrote
    WRITEPATH.json" is no longer the provenance story — the manifest
    records the writing pass and its canary alongside."""
    floor_ms = _SYNC_FLOOR_MS
    metrics = {}
    first_vs_warm = {}
    for line in _EMITTED:
        entry = dict(line)
        entry.pop("metric", None)
        if floor_ms > 0 and line.get("unit") == "ms":
            # Device latencies scale with the slot's sync floor; the
            # ratio transfers across passes (and to direct-attached
            # hardware) where the absolute ms does not.
            entry["vs_canary_sync_floor"] = round(
                line["value"] / floor_ms, 3)
        metrics[line["metric"]] = entry
        if "first_ms" in line and line.get("unit") == "ms":
            # Cold-vs-warm per config (VERDICT r5 weak #2 as a tracked
            # regression metric): first query pays compile + upload,
            # the warm p50 must not.
            first_vs_warm[line["metric"]] = {
                "first_ms": line["first_ms"],
                "warm_p50_ms": line["value"],
                "first_over_warm": round(
                    line["first_ms"] / max(line["value"], 1e-9), 2),
            }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "MANIFEST.json")
    # The latency_* entries are owned by latency_under_load.py (its
    # _fold_into_manifest); a suite pass must carry them forward, not
    # clobber them. One read serves every carry-forward below.
    try:
        with open(path) as f:
            prior_doc = json.load(f)
    except (OSError, ValueError):
        prior_doc = {}
    prior = prior_doc.get("metrics", {})
    for k, v in prior.items():
        # A partial pass (argv-selected configs) re-measures only its
        # own families; everything else carries forward so the
        # manifest stays the full index. Full passes carry only the
        # latency_* entries (owned by latency_under_load.py).
        # Error rows never carry forward: a failed config's row is
        # keyed by FUNCTION name while its successful rerun emits
        # metric names, so a stale error would otherwise contradict
        # the fresh section forever.
        if (k not in metrics and (partial or k.startswith("latency_"))
                and (not isinstance(v, dict)
                     or v.get("unit") != "error")):
            metrics[k] = v
    out = {
        "written_by": "benchmarks/suite.py",
        "scale": SCALE,
        "device": USE_DEVICE,
        "canary": {"sync_floor_ms": round(floor_ms, 3) or None},
        "canonical_artifacts": _CANONICAL_ARTIFACTS,
        "metrics": metrics,
        "first_vs_warm": first_vs_warm,
        "compile_cache": _compile_cache_snapshot(),
    }
    if partial:
        # A subset pass that measured no sync floor / warm tables /
        # compile stats keeps the full pass's values on record — and
        # must not relabel the retained sections' environment: the
        # top-level device flag and the compile-cache block describe
        # the FULL pass the carried-forward numbers came from, so a
        # CPU-only partial rerun of one config keeps both (its own
        # device flag rides its section's entry).
        if floor_ms <= 0:
            out["canary"] = prior_doc.get("canary", out["canary"])
        if not first_vs_warm:
            out["first_vs_warm"] = prior_doc.get("first_vs_warm", {})
        if "device" in prior_doc:
            out["device"] = prior_doc["device"]
        if prior_doc.get("compile_cache"):
            out["compile_cache"] = prior_doc["compile_cache"]
    # Per-config cost ledgers (config_query_cost) and the measured
    # roofline constants (benchmarks/roofline.py) ride the manifest;
    # a pass that skipped either carries the prior values forward.
    out["query_cost"] = _QUERY_COST or prior_doc.get("query_cost", {})
    # Run-container mix on the run-heavy workload
    # (config_container_mix): run-op share, resident bytes vs the
    # two-kind baseline, p50 — ROADMAP item 4's acceptance artifact.
    out["container_mix"] = (_CONTAINER_MIX
                            or prior_doc.get("container_mix", {}))
    # Fresh-process first-vs-warm + compile counts per slice config
    # (config_compile_stability): the restart-latency acceptance table.
    out["compile_stability"] = (_COMPILE_STABILITY
                                or prior_doc.get("compile_stability",
                                                 {}))
    # Write-path A/B (config_write_path): per-op SetBit, executor
    # per-op, wire import, fsync amortization — ISSUE 8's acceptance
    # table, one-crossing+group-commit vs the pre-extension path.
    out["write_path"] = _WRITE_PATH or prior_doc.get("write_path", {})
    # Distributed fast paths (config_distributed_topn): 2-node TopN
    # pushdown vs fan-out A/B + the generation-validated resident
    # chain — ROADMAP item 3's acceptance table.
    out["distributed_topn"] = (_DISTRIBUTED_TOPN
                               or prior_doc.get("distributed_topn",
                                                {}))
    # Always-on observability overhead (config_obs_overhead): tail
    # sampling + blackbox cadence vs all-off, interleaved — ISSUE 11's
    # ≤2% acceptance artifact.
    out["obs_overhead"] = (_OBS_OVERHEAD
                           or prior_doc.get("obs_overhead", {}))
    # Metric-history sampler + regression sentinel overhead
    # (config_obs_history): whole-registry sampling, disk ticks, and
    # rule evaluation vs all-off, interleaved — ISSUE 13's ≤2%
    # acceptance artifact.
    out["obs_history"] = (_OBS_HISTORY
                          or prior_doc.get("obs_history", {}))
    # Background storage-scrub overhead (config_scrub_overhead): the
    # bench-leg p50 with the scrubber re-verifying checksums at an
    # elevated cadence vs off, interleaved — ISSUE 15's ≤2%
    # acceptance artifact.
    out["scrub_overhead"] = (_SCRUB_OVERHEAD
                             or prior_doc.get("scrub_overhead", {}))
    # Elastic resize under load (config_resize): duration, streamed
    # volume, and query p99 inflation during the migration — ROADMAP
    # item 5's acceptance table.
    out["resize"] = _RESIZE or prior_doc.get("resize", {})
    # Multi-tenant isolation (config_tenant_isolation): quiet-tenant
    # p99 under an aggressor at ≥3× its cap vs solo, per-tenant
    # shed/kill counts, and the quiet burn rate — ISSUE 14's
    # acceptance table.
    out["tenant_isolation"] = (_TENANT_ISOLATION
                               or prior_doc.get("tenant_isolation",
                                                {}))
    # Tiered storage (config_tiered): hot-working-set p99 with the
    # index 10× over the resident budget (bulk in the blob tier) vs
    # all-resident, zero wrong answers — ISSUE 16's acceptance table.
    out["tiered"] = _TIERED or prior_doc.get("tiered", {})
    # Cost-based planner A/B (config_planner): skewed multi-operand
    # speedup legs + the planner+plan-recording overhead guard +
    # the costmodel-constants fold-back — ISSUE 18's acceptance table.
    out["planner"] = _PLANNER or prior_doc.get("planner", {})
    # Recorded-traffic replay (config_replay -> benchmarks/replay.py):
    # the open-loop sustained-QPS artifact re-driven from a captured
    # stream, the self-shadow/seeded-fault proof, and the capture
    # on/off overhead guard — ISSUE 19's acceptance table.
    out["replay"] = _REPLAY or prior_doc.get("replay", {})
    out["capture_overhead"] = (_CAPTURE_OVERHEAD
                               or prior_doc.get("capture_overhead",
                                                {}))
    # Disaster recovery (config_backup): the backup-while-serving
    # overhead guard (continuous coordinator passes vs off,
    # interleaved; ≤5% target on the bench-leg p50) plus the restore
    # wall time into a fresh node — ISSUE 20's acceptance table.
    out["backup"] = _BACKUP or prior_doc.get("backup", {})
    measured = _roofline_measured() or prior_doc.get(
        "roofline_measured_constants")
    if measured:
        out["roofline_measured_constants"] = measured
    with open(path, "w") as f:
        json.dump(out, f, indent=1)


# Per-config cost ledgers captured by config_query_cost() — folded
# into MANIFEST.json's query_cost section.
_QUERY_COST: dict = {}

# Run-container mix measurements captured by config_container_mix() —
# folded into MANIFEST.json's container_mix section (ROADMAP item 4's
# done-when artifact).
_CONTAINER_MIX: dict = {}

# Per-slice-config restart latency + compile counts captured by
# config_compile_stability() — folded into MANIFEST.json.
_COMPILE_STABILITY: dict = {}

# Write-path A/B acceptance table captured by config_write_path() —
# folded into MANIFEST.json's write_path section and merged into
# WRITEPATH.json for bench.py's line of record (ISSUE 8).
_WRITE_PATH: dict = {}

# Distributed-fast-path acceptance table captured by
# config_distributed_topn() — folded into MANIFEST.json's
# distributed_topn section and written to DISTRIBUTED.json
# (ROADMAP item 3 / ISSUE 9).
_DISTRIBUTED_TOPN: dict = {}

# Always-on observability overhead A/B captured by
# config_obs_overhead() — folded into MANIFEST.json's obs_overhead
# section (ISSUE 11's ≤2% acceptance bound on the bench-leg p50).
_OBS_OVERHEAD: dict = {}

# Metric-history + sentinel overhead A/B captured by
# config_obs_history() — folded into MANIFEST.json's obs_history
# section (ISSUE 13's ≤2% acceptance bound on the bench-leg p50).
_OBS_HISTORY: dict = {}

# Background-scrub overhead A/B captured by config_scrub_overhead()
# — folded into MANIFEST.json's scrub_overhead section (ISSUE 15's
# ≤2% acceptance bound on the bench-leg p50 with the scrubber at
# elevated cadence).
_SCRUB_OVERHEAD: dict = {}

# Elastic-resize acceptance table captured by config_resize() —
# folded into MANIFEST.json's resize section and written to
# RESIZE.json (ROADMAP item 5 / ISSUE 12): resize duration + query
# p99 inflation under live load during the migration.
_RESIZE: dict = {}

# Multi-tenant isolation A/B captured by config_tenant_isolation() —
# folded into MANIFEST.json's tenant_isolation section and written to
# TENANTS.json (ROADMAP item 5's multi-tenant half / ISSUE 14): the
# quiet tenant's p99 with an aggressor at ≥3× its admission cap vs its
# solo baseline, interleaved, with the aggressor's shed/kill counts.
_TENANT_ISOLATION: dict = {}

# Tiered-storage acceptance table captured by config_tiered() —
# folded into MANIFEST.json's tiered section and written to
# TIERED.json (ISSUE 16: hot-working-set p99 ≤ 1.2× all-resident
# while the index is ≥ 10× the resident budget, zero wrong answers).
_TIERED: dict = {}

# Cost-based planner A/B captured by config_planner() — folded into
# MANIFEST.json's planner section and written to PLANNER.json
# (ISSUE 18): planned-vs-unplanned p50 on the skewed multi-operand
# workload (short-circuit, reorder, cross-query CSE legs; ≥3× target)
# plus the planner+plan-recording overhead guard on the production
# default workload (≤1.02 target), and the costmodel-constants
# fold-back record.
_PLANNER: dict = {}

# Recorded-traffic replay summary captured by config_replay() (which
# shells out to benchmarks/replay.py) — folded into MANIFEST.json's
# replay + capture_overhead sections and written to REPLAY.json
# (ISSUE 19): offered/achieved QPS with per-lane p99s + shed rates,
# the self-shadow zero-mismatch proof, the seeded-fault detection,
# and the capture on/off p50 ratio (≤1.02 target).
_REPLAY: dict = {}
_CAPTURE_OVERHEAD: dict = {}

# Disaster-recovery acceptance table captured by config_backup() —
# folded into MANIFEST.json's backup section (ISSUE 20): the
# backup-while-serving p50 overhead (coordinator running continuous
# full passes vs off, interleaved; ≤1.05 target) and the wall time
# of a digest-verified restore into a fresh empty node.
_BACKUP: dict = {}


# Fresh-process measurement: each slice config restarts python, arms
# the SHARED persistent compile cache, and times the FIRST device
# query end-to-end (backend init + mesh + program acquisition +
# dispatch) then the warm p50 — the real "first device query after
# restart" number (VERDICT weak #2), not an in-process proxy.
_STABILITY_CHILD = r"""
import json, os, sys, tempfile, time
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PILOSA_TPU_COST_MODEL"] = "0"
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from pilosa_tpu import SLICE_WIDTH
from pilosa_tpu.executor import Executor
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.parallel import mesh as mesh_mod, programs

armed = mesh_mod.arm_compile_cache(None)  # env carries the shared dir
n_slices = %(n_slices)d
rng = np.random.default_rng(17)
with tempfile.TemporaryDirectory() as d:
    holder = Holder(d)
    holder.open()
    try:
        frame = holder.create_index_if_not_exists("cs") \
            .create_frame_if_not_exists("f")
        for row in (0, 1):
            cols = (rng.integers(0, SLICE_WIDTH, size=50 * n_slices)
                    + np.repeat(np.arange(n_slices), 50) * SLICE_WIDTH)
            frame.import_bits(np.full(len(cols), row, dtype=np.uint64),
                              cols.astype(np.uint64))
        ex = Executor(holder, host="local", mesh_min_slices=1)
        # The server's boot sequence: warmup compiles the catalogue at
        # the holder's actual bucket (reading the persistent cache),
        # THEN queries arrive. first_ms is the first real device query
        # a restarted server serves; warmup_s is the startup cost it
        # paid in the background to get there.
        q = ("Count(Intersect(Bitmap(frame=f, rowID=0),"
             " Bitmap(frame=f, rowID=1)))")
        from pilosa_tpu.sched.warmup import Warmup
        w = Warmup(ex)
        t0 = time.perf_counter()
        w._run()
        warmup_s = time.perf_counter() - t0
        assert w.state == "done", (w.state, w.error)
        t0 = time.perf_counter()
        first = ex.execute("cs", q)[0]
        first_s = time.perf_counter() - t0
        lat = []
        for _ in range(5):
            t0 = time.perf_counter()
            again = ex.execute("cs", q)[0]
            lat.append(time.perf_counter() - t0)
        assert again == first
        assert ex.device_fallbacks == 0, "fell back to host"
        stats = mesh_mod.compile_stats()
        print("RESULT " + json.dumps({
            "first_ms": round(first_s * 1e3, 1),
            "warm_p50_ms": round(sorted(lat)[2] * 1e3, 2),
            "warmup_s": round(warmup_s, 2),
            "compile_count": stats["firstCalls"],
            "persistent_hits": stats["persistentHits"],
            "persistent_misses": stats["persistentMisses"],
            "bucket": programs.slice_bucket(n_slices, 8),
            "cache_dir": armed}))
    finally:
        holder.close()
"""


def config_compile_stability() -> None:
    """First-vs-warm device query latency AND compile counts per
    slice-count config, each in a FRESH process sharing one on-disk
    XLA cache — records (a) whether the compile count stays constant
    (bucket-bound) as slice count grows 8→32, and (b) what the first
    device query after a restart actually costs once the persistent
    cache is warm. The tier-1 regression twin lives in
    tests/test_programs.py; this is the measured artifact."""
    import subprocess
    import tempfile

    from pilosa_tpu.utils import cache_dir

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    share = os.environ.get("PILOSA_TPU_COMPILE_CACHE")
    if share == "0":
        # The operator explicitly disabled the persistent cache; a
        # forced-warm measurement would be the number they asked NOT
        # to produce. Record the skip instead of overriding.
        emit("compile_stability", -1, "error",
             error="skipped: PILOSA_TPU_COMPILE_CACHE=0")
        return
    if not share:
        share = cache_dir("xla-suite")
    env = dict(os.environ)
    env["PILOSA_TPU_COMPILE_CACHE"] = share
    for n_slices in (8, 16, 24, 32):
        code = _STABILITY_CHILD % {"repo": repo, "n_slices": n_slices}
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, env=env,
                             timeout=600)
        line = [ln for ln in out.stdout.splitlines()
                if ln.startswith("RESULT ")]
        if out.returncode != 0 or not line:
            emit(f"compile_stability_s{n_slices}", -1, "error",
                 error=(out.stderr or out.stdout)[-200:])
            continue
        rec = json.loads(line[0][len("RESULT "):])
        _COMPILE_STABILITY[f"s{n_slices}"] = rec
        emit(f"compile_stability_s{n_slices}", rec["warm_p50_ms"],
             "ms", first_ms=rec["first_ms"],
             compile_count=rec["compile_count"],
             persistent_hits=rec["persistent_hits"],
             bucket=rec["bucket"], slices=n_slices)


def _roofline_measured() -> dict | None:
    """The measured projection constants benchmarks/roofline.py
    records (dispatch/collective next to the 0.3 ms / 50 us
    assumptions) — carried into MANIFEST.json so both artifacts agree."""
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(
                __file__)), "ROOFLINE.json")) as f:
            return json.load(f).get("measured_constants")
    except (OSError, ValueError):
        return None


def config_query_cost() -> None:
    """Per-config query-cost ledgers (obs.accounting): the bench query
    shapes through the executor with a cost-attached QueryContext, so
    MANIFEST.json records WHAT each config's query costs (container-op
    mix by operand kinds, device programs/bytes, compile ms) next to
    how long it took — the attribution layer's numbers as committed
    artifacts."""
    import tempfile

    from pilosa_tpu import SLICE_WIDTH
    from pilosa_tpu.executor import ExecOptions, Executor
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.obs import accounting
    from pilosa_tpu.sched import QueryContext

    rng = np.random.default_rng(21)
    n_slices = max(2, int(8 * SCALE))
    with tempfile.TemporaryDirectory() as d:
        holder = Holder(d)
        holder.open()
        try:
            frame = holder.create_index_if_not_exists("qc") \
                .create_frame_if_not_exists("f")
            for row in range(8):
                cols = (rng.integers(0, SLICE_WIDTH,
                                     size=400 * n_slices)
                        + np.repeat(np.arange(n_slices), 400)
                        * SLICE_WIDTH)
                frame.import_bits(
                    np.full(len(cols), row, dtype=np.uint64),
                    cols.astype(np.uint64))
            # Narrow materializing shapes run the roaring container
            # algebra (the wide-union shape routes to the vectorized
            # word fold, which by design does no container ops); the
            # Count shape exercises the fused count path, whose cost
            # shows up as device programs/bytes on the device leg.
            shapes = {
                "c1_intersect_materialize":
                    "Intersect(Bitmap(frame=f, rowID=0),"
                    " Bitmap(frame=f, rowID=1))",
                "c2_union_materialize":
                    "Union(Bitmap(rowID=0, frame=f),"
                    " Bitmap(rowID=1, frame=f),"
                    " Bitmap(rowID=2, frame=f))",
                "c4_count_intersect":
                    "Count(Intersect(Bitmap(frame=f, rowID=0),"
                    " Bitmap(frame=f, rowID=1)))",
            }
            legs = [("host", False)]
            if USE_DEVICE:
                legs.append(("device", True))
            for leg, use_mesh in legs:
                ex = Executor(holder, host="local", use_mesh=use_mesh,
                              mesh_min_slices=1)
                if use_mesh:
                    ex._cost_model_enabled = False
                for name, q in shapes.items():
                    ex.execute("qc", q)  # warm (compile outside ledger)
                    # The ledger run must do the real work: drop the
                    # materialized-result cache the warm run seeded.
                    ex._bitmap_results.clear()
                    ctx = QueryContext(pql=q)
                    accounting.attach(ctx)
                    # ctx travels via ExecOptions: the executor binds
                    # it into every worker leg, where the container
                    # algebra actually runs.
                    ex.execute("qc", q, opt=ExecOptions(ctx=ctx))
                    cost = ctx.cost.to_tree()
                    cost.pop("node", None)
                    _QUERY_COST[f"{name}_{leg}"] = cost
                    emit(f"query_cost_{name}_{leg}",
                         float(sum(cost["containerOps"].values())),
                         "container_ops",
                         device_bytes=cost["deviceBytes"],
                         device_programs=cost["devicePrograms"],
                         compile_ms=cost["compileMs"],
                         words_scanned=cost["wordsScanned"])
                ex.close()
        finally:
            holder.close()


def config_container_mix() -> None:
    """Run containers on a run-heavy (timestamp/BSI-shaped) workload:
    the same import + query mix with the cardinality-adaptive
    optimize() pass ON vs OFF (PILOSA_TPU_RUN_CONTAINERS semantics),
    recording (1) resident container bytes, (2) the container-op mix
    by operand kind from the PR 4 cost ledger — the "mix shifts to
    run ops" claim as numbers — and (3) host-path query p50. The
    MANIFEST container_mix section is ROADMAP item 4's done-when
    artifact: run-op share > 0 on the run leg, strictly reduced
    resident bytes, equal-or-better p50."""
    import tempfile

    from pilosa_tpu import SLICE_WIDTH
    from pilosa_tpu.executor import ExecOptions, Executor
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.obs import accounting
    from pilosa_tpu.sched import QueryContext
    from pilosa_tpu.storage import fragment as fragment_mod

    n_slices = max(2, int(4 * SCALE))
    n_rows = 6
    span_len = int(120_000 * SCALE)
    queries = [
        "Count(Intersect(Bitmap(rowID=0, frame=f),"
        " Bitmap(rowID=1, frame=f)))",
        "Count(Union(Bitmap(rowID=1, frame=f),"
        " Bitmap(rowID=2, frame=f)))",
        "Count(Difference(Bitmap(rowID=2, frame=f),"
        " Bitmap(rowID=3, frame=f)))",
        "TopN(frame=f, n=3)",
    ]

    def build(d: str, optimize_on: bool):
        prior = fragment_mod._RUN_OPTIMIZE
        fragment_mod._RUN_OPTIMIZE = optimize_on
        try:
            holder = Holder(d)
            holder.open()
            frame = holder.create_index_if_not_exists("cm") \
                .create_frame_if_not_exists("f")
            # Timestamp-view shape: each row holds long dense column
            # spans (sequential ids), overlapping so intersections are
            # non-trivial.
            for row in range(n_rows):
                start = row * span_len // 2
                cols = np.arange(start, start + span_len,
                                 dtype=np.uint64) \
                    % (n_slices * SLICE_WIDTH)
                frame.import_bits(
                    np.full(len(cols), row, dtype=np.uint64),
                    np.sort(cols))
        finally:
            fragment_mod._RUN_OPTIMIZE = prior
        stats = {"array": 0, "bitmap": 0, "run": 0}
        bytes_ = dict(stats)
        for s in range(n_slices):
            frag = holder.fragment("cm", "f", "standard", s)
            if frag is None:
                continue
            cs = frag.container_stats()
            for k in stats:
                stats[k] += cs["counts"][k]
                bytes_[k] += cs["bytes"][k]
        ex = Executor(holder, host="local", use_mesh=False)
        for q in queries:
            ex.execute("cm", q)  # warm
        meas = {"containers": stats,
                "resident_bytes": sum(bytes_.values()),
                "bytes_by_kind": bytes_, "container_ops": {},
                "lat_ms": []}
        return holder, ex, meas

    def round_of(ex, meas) -> None:
        for q in queries:
            ex._bitmap_results.clear()
            ctx = QueryContext(pql=q)
            accounting.attach(ctx)
            t0 = time.perf_counter()
            ex.execute("cm", q, opt=ExecOptions(ctx=ctx))
            meas["lat_ms"].append((time.perf_counter() - t0) * 1e3)
            ops = meas["container_ops"]
            for key, cnt in ctx.cost.to_tree()[
                    "containerOps"].items():
                ops[key] = ops.get(key, 0) + cnt

    def finish(meas) -> dict:
        ops = meas.pop("container_ops")
        total_ops = sum(ops.values()) or 1
        run_ops = sum(cnt for key, cnt in ops.items()
                      if "run" in key.split(":")[-1])
        meas["container_ops"] = ops
        meas["run_op_share"] = round(run_ops / total_ops, 4)
        meas["p50_ms"] = round(float(np.median(meas.pop("lat_ms"))), 3)
        return meas

    # INTERLEAVED A/B rounds: the shared VM slot swings absolute
    # latencies ±10%+ between back-to-back passes, so the two legs
    # alternate round by round and the p50s compare like for like
    # (same pattern as the accounting overhead guard).
    with tempfile.TemporaryDirectory() as d1, \
            tempfile.TemporaryDirectory() as d2:
        h1, ex1, m_runs = build(d1, True)
        h2, ex2, m_base = build(d2, False)
        try:
            for _ in range(int(max(8, 24 * SCALE))):
                round_of(ex1, m_runs)
                round_of(ex2, m_base)
        finally:
            ex1.close()
            ex2.close()
            h1.close()
            h2.close()
    runs_leg = finish(m_runs)
    baseline = finish(m_base)
    _CONTAINER_MIX.update({
        "workload": {"slices": n_slices, "rows": n_rows,
                     "span_len": span_len, "queries": len(queries)},
        "runs": runs_leg,
        "baseline_array_bitmap": baseline,
        "resident_bytes_ratio": round(
            runs_leg["resident_bytes"]
            / max(baseline["resident_bytes"], 1), 4),
        "p50_ratio": round(runs_leg["p50_ms"]
                           / max(baseline["p50_ms"], 1e-9), 3),
    })
    emit("container_mix_runs", runs_leg["p50_ms"], "ms",
         run_op_share=runs_leg["run_op_share"],
         resident_bytes=runs_leg["resident_bytes"],
         containers=runs_leg["containers"])
    emit("container_mix_baseline", baseline["p50_ms"], "ms",
         run_op_share=baseline["run_op_share"],
         resident_bytes=baseline["resident_bytes"],
         containers=baseline["containers"])


def config_obs_overhead() -> None:
    """Always-on observability overhead guard (ISSUE 11): the
    bench-leg query p50 with the production default (tail sampling on
    every query + the blackbox recorder at its default cadence) vs
    everything off, interleaved in small alternating groups so shared
    CI noise lands on both modes equally (the PR-3 accounting-guard
    pattern). Acceptance: on/off p50 ratio ≤ 1.02."""
    import io
    import tempfile

    import numpy as np

    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.obs import metrics as obs_metrics
    from pilosa_tpu.obs.blackbox import Blackbox
    from pilosa_tpu.obs.diskring import SegmentRing
    from pilosa_tpu.obs.sampler import TailSampler
    from pilosa_tpu.obs.trace import Tracer
    from pilosa_tpu.server.handler import Handler
    from pilosa_tpu.storage import wal as storage_wal

    def call(app, method, path, body=b""):
        environ = {"REQUEST_METHOD": method, "PATH_INFO": path,
                   "QUERY_STRING": "",
                   "CONTENT_LENGTH": str(len(body)),
                   "wsgi.input": io.BytesIO(body)}
        out = {}

        def start_response(status, hs):
            out["status"] = int(status.split()[0])

        list(app(environ, start_response))
        return out["status"]

    with tempfile.TemporaryDirectory() as d:
        holder = Holder(os.path.join(d, "data"))
        holder.open()
        frame = holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        rng = np.random.default_rng(11)
        n_rows = max(8, int(24 * SCALE))
        for row in range(n_rows):
            cols = rng.choice(1 << 16, size=2000, replace=False)
            frame.import_bits(np.full(2000, row, np.uint64),
                              cols.astype(np.uint64))
        from pilosa_tpu.utils.profiling import thread_dump

        ex = Executor(holder, host="local")
        handler = Handler(holder, ex, host="local",
                          tracer=Tracer(enabled=False))
        sampler = TailSampler(
            disk=SegmentRing(os.path.join(d, "traces")))

        def state_fn():
            # Production-shaped snapshot weight (Server._blackbox_state
            # without the server wiring): WAL health, thread dump,
            # query-state reads.
            return {"wal": storage_wal.flusher_health(),
                    "threads": thread_dump()[:20000],
                    "queries": {"active": handler.registry.active(),
                                "slow": handler.registry
                                .slow_queries()[-8:]},
                    "metrics": {"queries": obs_metrics.QUERIES_TOTAL
                                .labels("Union", "read", "200").value}}

        # 0.25 s cadence (40× the 10 s production default) so real
        # snapshots actually land INSIDE the measured on-windows —
        # at the default cadence a ~0.4 s group would never see one
        # and the A/B would measure tail sampling alone. Conservative:
        # the recorded ratio over-counts snapshot load per query.
        blackbox = Blackbox(os.path.join(d, "bb"), state_fn=state_fn,
                            interval_s=0.25, node="bench")
        children = ", ".join(f"Bitmap(rowID={r}, frame=f)"
                             for r in range(n_rows))
        q = f"Union({children})".encode()

        def run_group(samples, n=40):
            for _ in range(n):
                # The materialized-result cache would collapse repeats
                # to a dict hit and measure nothing; clear per query
                # (both modes identically).
                ex._bitmap_results.clear()
                t0 = time.perf_counter()
                status = call(handler, "POST", "/index/i/query", q)
                samples.append(time.perf_counter() - t0)
                assert status == 200, status

        warm: list = []
        run_group(warm, 40)
        on_samples: list = []
        off_samples: list = []
        # Alternating ~0.4 s groups: long enough for the 0.25 s
        # blackbox cadence to land snapshots inside on-windows, short
        # enough that shared-VM scheduler noise spreads over both
        # modes (the per-query sampling cost itself is microseconds
        # against a ~10 ms query, so the measurement is noise-bound).
        rounds = max(6, int(15 * SCALE))
        for _ in range(rounds):
            handler.sampler = None
            run_group(off_samples)
            handler.sampler = sampler
            blackbox.start()
            try:
                run_group(on_samples)
            finally:
                blackbox.stop()
        on_p50 = sorted(on_samples)[len(on_samples) // 2]
        off_p50 = sorted(off_samples)[len(off_samples) // 2]
        ratio = on_p50 / off_p50
        _OBS_OVERHEAD.update({
            "on_p50_ms": round(on_p50 * 1e3, 4),
            "off_p50_ms": round(off_p50 * 1e3, 4),
            "ratio": round(ratio, 4),
            "samples_per_mode": len(on_samples),
            "rounds": rounds,
            "query": f"Union over {n_rows} rows",
            "tail_default": {"head_n": sampler.head_n,
                             "slow_floor_s": sampler.slow_floor_s},
            "blackbox_interval_s": blackbox.interval_s,
            "blackbox_interval_note":
                "40x the 10s production cadence, so snapshots land"
                " inside the measured windows (conservative)",
            "blackbox_snapshots_during_on": blackbox.ring.written,
            "device": USE_DEVICE,
            "target_ratio": 1.02,
        })
        emit("obs_overhead_on_p50", on_p50 * 1e3, "ms")
        emit("obs_overhead_off_p50", off_p50 * 1e3, "ms")
        emit("obs_overhead_ratio", ratio, "x_on_vs_off",
             target=1.02)
        sampler.disk.close()
        ex.close()
        holder.close()


def config_replay() -> None:
    """Recorded-traffic replay artifact (ISSUE 19): shells out to
    benchmarks/replay.py in a fresh interpreter (its multi-process
    open-loop driver forks; a clean process keeps that away from this
    pass's jax state) and folds REPLAY.json into the manifest's
    line of record."""
    import subprocess

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "replay.py")
    proc = subprocess.run([sys.executable, script],
                          capture_output=True, text=True,
                          timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(
            f"replay.py failed rc={proc.returncode}:"
            f" {proc.stderr[-400:]}")
    with open(os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "REPLAY.json")) as f:
        doc = json.load(f)
    _REPLAY.update(doc["replay"])
    _REPLAY["shadow"] = doc["shadow"]
    _CAPTURE_OVERHEAD.update(doc["capture_overhead"])
    emit("replay_offered_qps", doc["replay"]["offered_qps"], "qps",
         target=20000)
    emit("replay_achieved_qps", doc["replay"]["achieved_qps"], "qps")
    emit("replay_shadow_mismatches",
         doc["shadow"]["self"]["mismatches"], "count", target=0)
    emit("capture_overhead_ratio", doc["capture_overhead"]["ratio"],
         "x_on_vs_off", target=1.02)


def config_planner() -> None:
    """Cost-based planner A/B (ISSUE 18), interleaved alternating
    groups on ONE holder (shared fragment caches keep the comparison
    fair — the PR-3 guard pattern):

    - the SKEWED MULTI-OPERAND workload the planner exists for —
      short-circuit (a 3-operand intersect containing an empty row:
      unplanned pays the huge∩huge intermediate, planned proves 0
      without touching a fragment), reorder (tiny operand folded
      first vs the written huge-first order), and cross-query CSE
      (a repeated interior union under a varying outer leaf, served
      from the generation-token-keyed subresult cache) —
      acceptance: unplanned/planned p50 ≥ 3×;
    - the production-default workload the planner can only lose on
      (single-row counts through the full handler path, plan
      recording + the fingerprint store live) —
      acceptance: on/off p50 ratio ≤ 1.02;
    - the costmodel fold-back record: the committed defaults before
      and after PR 18, plus this rig's persisted calibration.
    """
    import io
    import tempfile

    import numpy as np

    from pilosa_tpu import SLICE_WIDTH
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.parallel import costmodel
    from pilosa_tpu.server.handler import Handler

    def call(app, method, path, body=b""):
        environ = {"REQUEST_METHOD": method, "PATH_INFO": path,
                   "QUERY_STRING": "",
                   "CONTENT_LENGTH": str(len(body)),
                   "wsgi.input": io.BytesIO(body)}
        out = {}

        def start_response(status, hs):
            out["status"] = int(status.split()[0])

        list(app(environ, start_response))
        return out["status"]

    def p50(samples):
        return sorted(samples)[len(samples) // 2]

    with tempfile.TemporaryDirectory() as d:
        holder = Holder(os.path.join(d, "data"))
        holder.open()
        frame = holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        rng = np.random.default_rng(18)
        n_cols = 4 * SLICE_WIDTH
        # The skew the planner exploits: two huge rows (0, 1), a band
        # of medium rows for the shared union, tiny rows, and row 40
        # empty — rank caches make all of this estimable.
        huge = max(60_000, int(150_000 * SCALE))
        for row in (0, 1):
            cols = rng.choice(n_cols, size=huge, replace=False)
            frame.import_bits(np.full(huge, row, np.uint64),
                              cols.astype(np.uint64))
        for row in range(2, 32):
            cols = rng.choice(n_cols, size=2_000, replace=False)
            frame.import_bits(np.full(2_000, row, np.uint64),
                              cols.astype(np.uint64))
        for row in range(32, 36):
            cols = rng.choice(n_cols, size=50, replace=False)
            frame.import_bits(np.full(50, row, np.uint64),
                              cols.astype(np.uint64))

        planned = Executor(holder, host="local")
        unplanned = Executor(holder, host="local")
        unplanned.planner_enabled = False

        union = ", ".join(f"Bitmap(rowID={r}, frame=f)"
                          for r in range(2, 32))
        legs = {
            # Written worst-first: empty row LAST, huge rows first.
            "short_circuit":
                lambda i: ("Count(Intersect(Bitmap(rowID=0, frame=f),"
                           " Bitmap(rowID=1, frame=f),"
                           " Bitmap(rowID=40, frame=f)))"),
            "reorder":
                lambda i: (f"Count(Intersect(Bitmap(rowID=0, frame=f),"
                           f" Bitmap(rowID=1, frame=f),"
                           f" Bitmap(rowID={32 + i % 4}, frame=f)))"),
            "cse":
                lambda i: (f"Count(Intersect(Union({union}),"
                           f" Bitmap(rowID={2 + i % 30}, frame=f)))"),
        }

        def run_group(ex, leg_fn, samples, n, base):
            for i in range(n):
                # Both modes clear the whole-result cache identically:
                # it would collapse repeats for both sides and measure
                # nothing (the subresult cache under test is interior-
                # node, token-keyed — it survives this clear).
                ex._bitmap_results.clear()
                q = leg_fn(base + i)
                t0 = time.perf_counter()
                ex.execute("i", q)
                samples.append(time.perf_counter() - t0)

        rounds = max(4, int(8 * SCALE))
        group_n = 6
        leg_results: dict = {}
        workload_planned: list = []
        workload_unplanned: list = []
        for leg, leg_fn in legs.items():
            a: list = []
            b: list = []
            # Warm both paths once (fragment row caches, rank caches,
            # and the CSE second-sighting threshold) outside the
            # measured groups.
            run_group(planned, leg_fn, [], 3, 0)
            run_group(unplanned, leg_fn, [], 3, 0)
            for r in range(rounds):
                run_group(unplanned, leg_fn, b, group_n, r * group_n)
                run_group(planned, leg_fn, a, group_n, r * group_n)
            leg_results[leg] = {
                "planned_p50_ms": round(p50(a) * 1e3, 4),
                "unplanned_p50_ms": round(p50(b) * 1e3, 4),
                "speedup": round(p50(b) / max(p50(a), 1e-9), 2),
            }
            workload_planned.extend(a)
            workload_unplanned.extend(b)
            emit(f"planner_{leg}_speedup",
                 leg_results[leg]["speedup"], "x_unplanned_vs_planned",
                 planned_p50_ms=leg_results[leg]["planned_p50_ms"],
                 unplanned_p50_ms=leg_results[leg]["unplanned_p50_ms"])
        skew_speedup = (p50(workload_unplanned)
                        / max(p50(workload_planned), 1e-9))
        emit("planner_skewed_workload_speedup", skew_speedup,
             "x_unplanned_vs_planned", target=3.0)

        # Overhead guard: the handler path (plan recording, the
        # fingerprint store, ctx stitching all live) on single-row
        # counts the planner cannot improve.
        handler = Handler(holder, planned, host="local")
        simple = [f"Count(Bitmap(rowID={r}, frame=f))".encode()
                  for r in range(2, 32)]

        def run_simple(samples, n=40):
            for i in range(n):
                planned._bitmap_results.clear()
                t0 = time.perf_counter()
                status = call(handler, "POST", "/index/i/query",
                              simple[i % len(simple)])
                samples.append(time.perf_counter() - t0)
                assert status == 200, status

        run_simple([], 20)  # warm
        on_s: list = []
        off_s: list = []
        for _ in range(rounds):
            planned.planner_enabled = False
            run_simple(off_s)
            planned.planner_enabled = True
            run_simple(on_s)
        overhead = p50(on_s) / max(p50(off_s), 1e-9)
        emit("planner_overhead_ratio", overhead, "x_on_vs_off",
             target=1.02, on_p50_ms=round(p50(on_s) * 1e3, 4),
             off_p50_ms=round(p50(off_s) * 1e3, 4))

        snap = planned.planner.snapshot()
        cal = costmodel.default_calibration()
        table = {
            "legs": leg_results,
            "skewed_workload_speedup": round(skew_speedup, 2),
            "target_speedup": 3.0,
            "overhead": {
                "on_p50_ms": round(p50(on_s) * 1e3, 4),
                "off_p50_ms": round(p50(off_s) * 1e3, 4),
                "ratio": round(overhead, 4),
                "target_ratio": 1.02,
                "samples_per_mode": len(on_s),
            },
            "planner_snapshot": snap,
            "constants": {
                # PR 18 folded measured medians back into the
                # committed Calibration defaults (the old hand-picked
                # upload/pack numbers over-estimated pack rate ~16x).
                "before": {"upload_bps": 1.0e9, "pack_bps": 2.0e9},
                "after": {
                    "sync_s": costmodel.DEFAULT_SYNC_S,
                    "host_bps": costmodel.DEFAULT_HOST_BPS,
                    "upload_bps": costmodel.DEFAULT_UPLOAD_BPS,
                    "pack_bps": costmodel.DEFAULT_PACK_BPS,
                },
                "this_rig": {
                    "sync_s": cal.sync_s, "host_bps": cal.host_bps,
                    "upload_bps": cal.upload_bps,
                    "pack_bps": cal.pack_bps,
                },
            },
            "rounds": rounds, "group_n": group_n,
            "device": USE_DEVICE,
        }
        _PLANNER.update(table)
        with open(os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "PLANNER.json"),
                "w") as f:
            json.dump(table, f, indent=1)
        planned.close()
        unplanned.close()
        holder.close()


def config_scrub_overhead() -> None:
    """Background storage-scrub overhead guard (ISSUE 15): the
    bench-leg query p50 with the scrubber re-reading + re-crc'ing
    every fragment file at an ELEVATED cadence (continuous
    back-to-back passes — production runs one pass per [scrub]
    interval, default 10 min) vs scrubber off, interleaved in small
    alternating groups (the config_obs_overhead pattern).
    Acceptance: on/off p50 ratio ≤ 1.02."""
    import io
    import tempfile

    import numpy as np

    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.server.handler import Handler
    from pilosa_tpu.storage.scrub import Scrubber
    from pilosa_tpu.obs.trace import Tracer

    def call(app, method, path, body=b""):
        environ = {"REQUEST_METHOD": method, "PATH_INFO": path,
                   "QUERY_STRING": "",
                   "CONTENT_LENGTH": str(len(body)),
                   "wsgi.input": io.BytesIO(body)}
        out = {}

        def start_response(status, hs):
            out["status"] = int(status.split()[0])

        list(app(environ, start_response))
        return out["status"]

    with tempfile.TemporaryDirectory() as d:
        holder = Holder(os.path.join(d, "data"))
        holder.open()
        frame = holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        rng = np.random.default_rng(13)
        n_rows = max(8, int(24 * SCALE))
        for row in range(n_rows):
            cols = rng.choice(1 << 18, size=4000, replace=False)
            frame.import_bits(np.full(4000, row, np.uint64),
                              cols.astype(np.uint64))
        # Real footered on-disk snapshots: the scrub pass must be
        # re-crc'ing actual container blocks, not empty stubs.
        blocks_on_disk = 0
        for frag in holder.iter_fragments():
            frag.snapshot(sync=True)
            blocks_on_disk += frag.verify_on_disk()["blocks"]
        assert blocks_on_disk > 0

        ex = Executor(holder, host="local")
        handler = Handler(holder, ex, host="local",
                          tracer=Tracer(enabled=False))
        children = ", ".join(f"Bitmap(rowID={r}, frame=f)"
                             for r in range(n_rows))
        q = f"Union({children})".encode()

        def run_group(samples, n=40):
            for _ in range(n):
                ex._bitmap_results.clear()
                t0 = time.perf_counter()
                status = call(handler, "POST", "/index/i/query", q)
                samples.append(time.perf_counter() - t0)
                assert status == 200, status

        warm: list = []
        run_group(warm, 40)
        on_samples: list = []
        off_samples: list = []
        passes = 0
        rounds = max(6, int(15 * SCALE))
        for _ in range(rounds):
            run_group(off_samples)
            # Elevated cadence: a fresh scrubber per on-window
            # starting a pass every 50 ms (vs one per 10 MINUTES in
            # production — >10000x elevated), with the default
            # inter-fragment pacing the shipped scrubber uses (pacing
            # IS the discipline that keeps scrub IO out of serving's
            # way; measuring an unpaced spin-loop would benchmark a
            # configuration that never runs).
            scrubber = Scrubber(holder, interval_s=0.05, pace_s=0.01)
            scrubber.start()
            try:
                run_group(on_samples)
            finally:
                scrubber.stop()
            passes += scrubber.state()["passes"]
        on_p50 = sorted(on_samples)[len(on_samples) // 2]
        off_p50 = sorted(off_samples)[len(off_samples) // 2]
        ratio = on_p50 / off_p50
        _SCRUB_OVERHEAD.update({
            "on_p50_ms": round(on_p50 * 1e3, 4),
            "off_p50_ms": round(off_p50 * 1e3, 4),
            "ratio": round(ratio, 4),
            "samples_per_mode": len(on_samples),
            "rounds": rounds,
            "scrub_passes_during_on": passes,
            "blocks_on_disk": blocks_on_disk,
            "query": f"Union over {n_rows} rows",
            "cadence_note":
                "a pass every 50ms with the default 10ms fragment"
                " pacing (production default is one pass per 10 min"
                " — >10000x elevated)",
            "device": USE_DEVICE,
            "target_ratio": 1.02,
        })
        emit("scrub_overhead_on_p50", on_p50 * 1e3, "ms")
        emit("scrub_overhead_off_p50", off_p50 * 1e3, "ms")
        emit("scrub_overhead_ratio", ratio, "x_on_vs_off",
             target=1.02)
        ex.close()
        holder.close()


def config_obs_history() -> None:
    """Metric-history + sentinel overhead guard (ISSUE 13): the
    bench-leg query p50 with the history sampler ticking AND the
    regression sentinel evaluating vs both off, interleaved in small
    alternating groups (the config_obs_overhead pattern). The sampler
    runs at 0.25 s — 40× the 10 s production cadence — so whole-
    registry sampling passes + disk tick records actually land inside
    the measured on-windows (conservative: the recorded ratio
    over-counts sampling load per query). Acceptance: on/off p50
    ratio ≤ 1.02."""
    import io
    import tempfile
    import threading

    import numpy as np

    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.obs.history import MetricHistory
    from pilosa_tpu.obs.sentinel import Sentinel
    from pilosa_tpu.obs.trace import Tracer
    from pilosa_tpu.server.handler import Handler

    def call(app, method, path, body=b""):
        environ = {"REQUEST_METHOD": method, "PATH_INFO": path,
                   "QUERY_STRING": "",
                   "CONTENT_LENGTH": str(len(body)),
                   "wsgi.input": io.BytesIO(body)}
        out = {}

        def start_response(status, hs):
            out["status"] = int(status.split()[0])

        list(app(environ, start_response))
        return out["status"]

    with tempfile.TemporaryDirectory() as d:
        holder = Holder(os.path.join(d, "data"))
        holder.open()
        frame = holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        rng = np.random.default_rng(13)
        n_rows = max(8, int(24 * SCALE))
        for row in range(n_rows):
            cols = rng.choice(1 << 16, size=2000, replace=False)
            frame.import_bits(np.full(2000, row, np.uint64),
                              cols.astype(np.uint64))
        ex = Executor(holder, host="local")
        handler = Handler(holder, ex, host="local",
                          tracer=Tracer(enabled=False))
        history = MetricHistory(
            os.path.join(d, "hist"),
            resolutions=((0.25, 400), (1.0, 200), (5.0, 100)))
        sentinel = Sentinel(history, interval_s=3600, window_s=5,
                            baseline_s=60, min_points=3)

        # The ticker thread IS the production runtime-collector +
        # sentinel cadence, accelerated: one whole-registry sampling
        # pass (and a disk tick) every 0.25 s, a full rule evaluation
        # every other tick.
        stop = threading.Event()

        def ticker():
            while not stop.wait(0.25):
                try:
                    history.sample()
                    sentinel.check()
                except Exception:  # noqa: BLE001 - bench must finish
                    pass

        children = ", ".join(f"Bitmap(rowID={r}, frame=f)"
                             for r in range(n_rows))
        q = f"Union({children})".encode()

        def run_group(samples, n=40):
            for _ in range(n):
                ex._bitmap_results.clear()
                t0 = time.perf_counter()
                status = call(handler, "POST", "/index/i/query", q)
                samples.append(time.perf_counter() - t0)
                assert status == 200, status

        warm: list = []
        run_group(warm, 40)
        on_samples: list = []
        off_samples: list = []
        rounds = max(6, int(15 * SCALE))
        for _ in range(rounds):
            run_group(off_samples)
            stop.clear()
            t = threading.Thread(target=ticker, daemon=True)
            t.start()
            try:
                run_group(on_samples)
            finally:
                stop.set()
                t.join(timeout=5)
        on_p50 = sorted(on_samples)[len(on_samples) // 2]
        off_p50 = sorted(off_samples)[len(off_samples) // 2]
        ratio = on_p50 / off_p50
        _OBS_HISTORY.update({
            "on_p50_ms": round(on_p50 * 1e3, 4),
            "off_p50_ms": round(off_p50 * 1e3, 4),
            "ratio": round(ratio, 4),
            "samples_per_mode": len(on_samples),
            "rounds": rounds,
            "query": f"Union over {n_rows} rows",
            "history": history.stats(),
            "sentinel_checks": sentinel.checks,
            "sample_interval_s": 0.25,
            "cadence_note":
                "0.25s sampling + sentinel evaluation per tick —"
                " 40-120x the 10s/30s production cadence, so passes"
                " land inside the measured windows (conservative)",
            "device": USE_DEVICE,
            "target_ratio": 1.02,
        })
        emit("obs_history_on_p50", on_p50 * 1e3, "ms")
        emit("obs_history_off_p50", off_p50 * 1e3, "ms")
        emit("obs_history_ratio", ratio, "x_on_vs_off", target=1.02)
        history.close()
        ex.close()
        holder.close()


def _compile_cache_snapshot() -> dict:
    """The XLA program-cache counters for THIS pass
    (parallel.mesh.compile_stats): hit/miss ratio + compile seconds —
    the 5.4 s cold-query question (VERDICT r5 weak #2) as numbers a
    regression check can hold onto."""
    try:
        from pilosa_tpu.parallel import mesh as mesh_mod
        return mesh_mod.compile_stats()
    except Exception as e:  # noqa: BLE001 - manifest must still write
        return {"error": str(e)[:120]}


def emit_compile_cache() -> None:
    """Emit the compile-cache counters as a suite metric so they ride
    the normal manifest metrics table too."""
    s = _compile_cache_snapshot()
    if "error" in s:
        emit("compile_cache", -1, "error", **s)
        return
    emit("compile_cache", float(s["misses"]), "programs", **s)


def _timed_chain(fn, iters: int) -> float:
    """Median-of-3 per-call seconds, chained dispatch + single sync."""
    np.asarray(fn())  # warmup/compile
    best = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn()
        np.asarray(out)
        best.append((time.perf_counter() - t0) / iters)
    return sorted(best)[1]


def config1_fragment_intersect_count() -> None:
    from pilosa_tpu.ops import kernels
    from pilosa_tpu.storage import native
    import jax

    n_words = (1 << 20) // 32
    rng = np.random.default_rng(1)
    a = rng.integers(0, 2**32, size=n_words, dtype=np.uint32)
    b = rng.integers(0, 2**32, size=n_words, dtype=np.uint32)

    native.popcnt_and(a.view(np.uint64), b.view(np.uint64))
    t0 = time.perf_counter()
    iters = 50
    for _ in range(iters):
        native.popcnt_and(a.view(np.uint64), b.view(np.uint64))
    host_s = (time.perf_counter() - t0) / iters
    extra = {}
    if native.available():
        # Only a real C++ run may pin the *_native denominator — the
        # numpy fallback rate must never masquerade as it.
        extra["native_pinned_ops"] = round(
            pin_best("c1_intersect_1M_native", 1.0 / host_s), 1)
    emit("c1_intersect_count_1M_host", 1.0 / host_s, "ops/sec", **extra)

    if USE_DEVICE:
        da, db = jax.device_put(a), jax.device_put(b)
        dev_s = _timed_chain(
            lambda: kernels.op_count_rows("and", da, db), 64)
        emit("c1_intersect_count_1M_device", 1.0 / dev_s, "ops/sec",
             vs_host=round(host_s / dev_s, 3))


def config2_union_difference_1k_rows() -> None:
    from pilosa_tpu.ops import kernels
    import jax

    n_rows = max(8, int(1000 * SCALE))
    n_words = (1 << 20) // 32
    rng = np.random.default_rng(2)
    # mixed "containers": half dense rows, half sparse (array-like)
    rows = rng.integers(0, 2**32, size=(n_rows, n_words), dtype=np.uint32)
    rows[n_rows // 2:] &= rng.integers(0, 2, size=(n_rows - n_rows // 2,
                                                   n_words),
                                       dtype=np.uint32)  # sparsify
    other = rng.integers(0, 2**32, size=n_words, dtype=np.uint32)

    np.bitwise_count(np.bitwise_or(rows, other[None, :]))  # warmup
    lat = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.bitwise_count(np.bitwise_or(rows, other[None, :])).sum(axis=-1)
        lat.append(time.perf_counter() - t0)
    host_s = sorted(lat)[1]
    emit("c2_union_1k_rows_host", 1.0 / host_s, "ops/sec")

    # Host-NATIVE leg: the same per-row union counts through the C++
    # kernel (one popcnt_or per row) — the pinned reference-equivalent
    # denominator (round-3 verdict: c1-c3 compared device against
    # numpy, not native).
    from pilosa_tpu.storage import native as native_mod
    if native_mod.available():
        o64 = other.view(np.uint64)
        r64 = rows.view(np.uint64)
        native_mod.popcnt_or(r64[0], o64)  # warmup
        lat = []
        for _ in range(3):
            t0 = time.perf_counter()
            for i in range(n_rows):
                native_mod.popcnt_or(r64[i], o64)
            lat.append(time.perf_counter() - t0)
        nat_s = sorted(lat)[1]
        pinned = pin_best(f"c2_union_native,rows={n_rows}",
                          1.0 / nat_s)
        emit("c2_union_1k_rows_native", 1.0 / nat_s, "ops/sec",
             native_pinned_ops=round(pinned, 2))

    if USE_DEVICE:
        dr, do = jax.device_put(rows), jax.device_put(other)
        dev_s = _timed_chain(
            lambda: kernels.row_block_op_count("or", dr, do), 16)
        emit("c2_union_1k_rows_device", 1.0 / dev_s, "ops/sec",
             vs_host=round(host_s / dev_s, 3))
        dev_s = _timed_chain(
            lambda: kernels.row_block_op_count("andnot", dr, do), 16)
        emit("c2_difference_1k_rows_device", 1.0 / dev_s, "ops/sec")


def config3_topn_latency() -> None:
    """TopN exact-count phase p50 latency, host loop vs one mesh call."""
    from pilosa_tpu.parallel import mesh as mesh_mod
    import jax

    n_rows = max(64, int(1000 * SCALE))
    n_slices = max(2, int(10 * SCALE))
    n_words = (1 << 20) // 32
    rng = np.random.default_rng(3)
    rows = rng.integers(0, 2**32, size=(n_slices, n_rows, n_words),
                        dtype=np.uint32)
    src = rng.integers(0, 2**32, size=(1, n_slices, n_words),
                       dtype=np.uint32)

    lat = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.bitwise_count(rows & src[0][:, None, :]).sum(axis=(0, 2))
        lat.append(time.perf_counter() - t0)
    emit("c3_topn_exact_host_p50", sorted(lat)[2] * 1e3, "ms",
         rows=n_rows, slices=n_slices)

    # Host-NATIVE leg: the same exact-count phase through the C++
    # kernel — one popcnt_and per (slice, candidate) pair, matching
    # the reference's per-row IntersectionCount loop shape
    # (fragment.go:560-614). Pinned as the c3 denominator.
    from pilosa_tpu.storage import native as native_mod
    if native_mod.available():
        r64 = rows.view(np.uint64)
        s64 = src[0].view(np.uint64)
        native_mod.popcnt_and(r64[0, 0], s64[0])  # warmup
        lat = []
        for _ in range(3):
            t0 = time.perf_counter()
            for si in range(n_slices):
                srow = s64[si]
                for ri in range(n_rows):
                    native_mod.popcnt_and(r64[si, ri], srow)
            lat.append(time.perf_counter() - t0)
        nat_ms = sorted(lat)[1] * 1e3
        pinned = pin_best(
            f"c3_exact_native,rows={n_rows},slices={n_slices}",
            1e3 / nat_ms)  # phases/sec so "best" = highest
        emit("c3_topn_exact_native_p50", nat_ms, "ms",
             rows=n_rows, slices=n_slices,
             native_pinned_ms=round(1e3 / pinned, 2))

    if USE_DEVICE:
        # Device-resident form — what the executor's residency cache
        # serves on repeat queries (first-query upload is measured by
        # config_residency_repeat_latency's first_ms).
        mesh = mesh_mod.make_mesh()
        expr = ("leaf", 0)
        n_dev = mesh.shape[mesh_mod.AXIS_SLICES]
        rows_p = mesh_mod.pad_to_multiple(rows, n_dev)
        d_rows = mesh_mod.shard_slices(mesh, rows_p)
        d_leaves = [mesh_mod.shard_slices(
            mesh, mesh_mod.pad_to_multiple(src[0], n_dev))]
        mesh_mod.topn_exact_sharded(mesh, expr, d_rows, d_leaves)
        lat = []
        for _ in range(5):
            t0 = time.perf_counter()
            mesh_mod.topn_exact_sharded(mesh, expr, d_rows, d_leaves)
            lat.append(time.perf_counter() - t0)
        emit_latency("c3_topn_exact_mesh_p50", sorted(lat)[2] * 1e3,
                     rows=n_rows, slices=n_slices)


def _kernel_ab_modes() -> list[tuple[str, str]]:
    """(label, PILOSA_TPU_PALLAS value) pairs to A/B on this backend.

    On TPU both serving-path kernel variants are measured — the Pallas
    fused kernels vs XLA fusion — so the winner is chosen from data,
    per the round-2 mandate. Off-TPU only XLA runs (interpret-mode
    Pallas is a correctness tool, not a performance candidate).
    """
    import jax
    if jax.devices()[0].platform == "tpu":
        return [("xla", "0"), ("pallas", "1")]
    return [("xla", "0")]


@contextlib.contextmanager
def _pallas_mode_env(mode: str):
    """Force PILOSA_TPU_PALLAS for one measurement, restoring the
    caller's value even when the measured leg throws (main() continues
    fail-soft past per-config errors)."""
    prior = os.environ.get("PILOSA_TPU_PALLAS")
    os.environ["PILOSA_TPU_PALLAS"] = mode
    try:
        yield
    finally:
        if prior is None:
            os.environ.pop("PILOSA_TPU_PALLAS", None)
        else:
            os.environ["PILOSA_TPU_PALLAS"] = prior


def config4_mesh_count_over_slices() -> None:
    from pilosa_tpu.parallel import mesh as mesh_mod
    import jax

    n_slices = max(8, int(256 * SCALE))
    n_words = (1 << 20) // 32
    rng = np.random.default_rng(4)
    leaves = rng.integers(0, 2**32, size=(2, n_slices, n_words),
                          dtype=np.uint32)

    t0 = time.perf_counter()
    int(np.bitwise_count(leaves[0] & leaves[1]).sum())
    host_s = time.perf_counter() - t0
    emit("c4_count_intersect_host", 1.0 / host_s, "ops/sec",
         slices=n_slices)

    if USE_DEVICE:
        # Device-resident leaf slabs (the executor residency form).
        mesh = mesh_mod.make_mesh()
        expr = ("and", ("leaf", 0), ("leaf", 1))
        n_dev = mesh.shape[mesh_mod.AXIS_SLICES]
        arrs = [mesh_mod.shard_slices(
            mesh, mesh_mod.pad_to_multiple(leaves[i], n_dev))
            for i in range(2)]
        for label, mode in _kernel_ab_modes():
            with _pallas_mode_env(mode):
                mesh_mod.count_expr_sharded(mesh, expr, arrs)  # compile
                lat = []
                for _ in range(5):
                    t0 = time.perf_counter()
                    mesh_mod.count_expr_sharded(mesh, expr, arrs)
                    lat.append(time.perf_counter() - t0)
            dev_s = sorted(lat)[2]
            emit(f"c4_count_intersect_mesh_{label}", 1.0 / dev_s,
                 "ops/sec", slices=n_slices, devices=len(jax.devices()),
                 vs_host=round(host_s / dev_s, 3))


def config5_cluster_topn() -> None:
    from pilosa_tpu.parallel import mesh as mesh_mod
    import jax

    n_slices = max(8, int(256 * SCALE))
    n_rows = max(16, int(100 * SCALE))
    n_words = (1 << 20) // 32
    rng = np.random.default_rng(5)
    rows = rng.integers(0, 2**32, size=(n_slices, n_rows, n_words),
                        dtype=np.uint32)
    src = rng.integers(0, 2**32, size=(1, n_slices, n_words),
                       dtype=np.uint32)

    if USE_DEVICE:
        mesh = mesh_mod.make_mesh()
        n_dev = mesh.shape[mesh_mod.AXIS_SLICES]
        d_rows = mesh_mod.shard_slices(
            mesh, mesh_mod.pad_to_multiple(rows, n_dev))
        d_leaves = [mesh_mod.shard_slices(
            mesh, mesh_mod.pad_to_multiple(src[0], n_dev))]
        for label, mode in _kernel_ab_modes():
            with _pallas_mode_env(mode):
                mesh_mod.topn_exact_sharded(mesh, ("leaf", 0), d_rows,
                                            d_leaves)  # compile
                lat = []
                for _ in range(5):
                    t0 = time.perf_counter()
                    mesh_mod.topn_exact_sharded(mesh, ("leaf", 0),
                                                d_rows, d_leaves)
                    lat.append(time.perf_counter() - t0)
            emit_latency(f"c5_cluster_topn_mesh_p50_{label}",
                         sorted(lat)[2] * 1e3, slices=n_slices,
                         rows=n_rows, devices=len(jax.devices()))


def config2_executor_wide_union() -> None:
    """Config 2 through the EXECUTOR: materializing Union/Difference
    over many rows — device fold + repack vs per-slice roaring merges."""
    import tempfile
    import numpy as np
    from pilosa_tpu import SLICE_WIDTH
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder

    n_rows = max(16, int(1000 * SCALE))
    rng = np.random.default_rng(8)
    with tempfile.TemporaryDirectory() as d:
        holder = Holder(d)
        holder.open()
        frame = holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        for row in range(n_rows):
            cols = rng.choice(SLICE_WIDTH, size=500, replace=False)
            frame.import_bits([row] * len(cols), cols.tolist())
        children = ", ".join(f"Bitmap(rowID={r}, frame=f)"
                             for r in range(n_rows))
        for name in ("Union", "Difference"):
            q = f"{name}({children})"
            want = None
            for label, use_mesh in (("host", False),) + (
                    (("device", True),) if USE_DEVICE else ()):
                ex = Executor(holder, host="local", use_mesh=use_mesh,
                              mesh_min_slices=1)
                got = ex.execute("i", q)[0].count()  # warmup/compile
                if want is None:
                    want = got
                assert got == want, (name, label, got, want)
                # COLD leg: the fold + repack itself, result cache
                # cleared per iteration (the residency row below
                # measures the cache).
                lat = []
                for _ in range(3):
                    ex._bitmap_results.clear()
                    t0 = time.perf_counter()
                    ex.execute("i", q)
                    lat.append(time.perf_counter() - t0)
                if use_mesh:  # the device label must measure the device
                    assert ex.device_fallbacks == 0, "device path fell back"
                emit(f"c2_executor_{name.lower()}_{n_rows}rows_{label}",
                     sorted(lat)[1] * 1e3, "ms", bits=int(want))
                # RESIDENT repeat: the materialized-result cache serves
                # the identical chain with zero re-fold and zero repack
                # (VERDICT r4 item 5).
                lat = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    ex.execute("i", q)
                    lat.append(time.perf_counter() - t0)
                emit(f"c2_executor_{name.lower()}_{n_rows}rows_"
                     f"{label}_resident", sorted(lat)[1] * 1e3, "ms")
                ex.close()
        holder.close()


def config_residency_repeat_latency() -> None:
    """Configs 3-4 through the EXECUTOR with the budgeted HBM residency
    cache: first query packs + uploads leaf/candidate blocks, repeats
    hit device-resident slabs — repeat p50 must sit well below first."""
    if not USE_DEVICE:
        return
    import tempfile
    import numpy as np
    from pilosa_tpu import SLICE_WIDTH
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder

    # Sized so the TopN candidate block (slices × cand × 128 KB) stays
    # under mesh.TOPN_BLOCK_BYTES — above it the executor streams
    # instead of using the residency cache this config measures.
    n_slices = max(8, int(32 * SCALE))
    n_cand = max(8, int(50 * SCALE))
    rng = np.random.default_rng(7)
    with tempfile.TemporaryDirectory() as d:
        holder = Holder(d)
        holder.open()
        frame = holder.create_index_if_not_exists("i") \
            .create_frame_if_not_exists("f")
        for row in range(n_cand):
            cols = (rng.integers(0, SLICE_WIDTH, size=n_slices)
                    + np.arange(n_slices) * SLICE_WIDTH)
            frame.import_bits([row] * n_slices, cols.tolist())
        ex = Executor(holder, host="local", mesh_min_slices=1)
        # This config MEASURES the device residency path; the routing
        # veto (which may rightly prefer host at this size on tunnel
        # rigs — config4_executor_routing measures that choice) would
        # make it measure the wrong leg.
        ex._cost_model_enabled = False

        def timed(q, label):
            t0 = time.perf_counter()
            first = ex.execute("i", q)
            first_s = time.perf_counter() - t0
            lat = []
            for _ in range(5):
                t0 = time.perf_counter()
                again = ex.execute("i", q)
                lat.append(time.perf_counter() - t0)
            assert again == first
            emit_latency(label, sorted(lat)[2] * 1e3,
                         first_ms=round(first_s * 1e3, 4),
                         slices=n_slices,
                         speedup_vs_first=round(first_s / sorted(lat)[2],
                                                2))

        timed("Count(Intersect(Bitmap(frame=f, rowID=0),"
              " Bitmap(frame=f, rowID=1)))", "c4_executor_count_repeat_p50")
        ids = ",".join(str(r) for r in range(n_cand))
        timed(f"TopN(Bitmap(frame=f, rowID=0), frame=f, ids=[{ids}])",
              "c3_executor_topn_repeat_p50")
        assert ex.device_fallbacks == 0, "device path fell back"
        holder.close()


def config_host_write_and_import() -> None:
    """Host write-side throughput (the device only serves reads): bulk
    CSV parse, server-side bulk apply, and per-op SetBit through the
    executor — the round-2 host-path optimizations, reproducible."""
    import io
    import random
    import tempfile

    from pilosa_tpu.cli.commands import _parse_csv_arrays
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder

    n = int(1_000_000 * SCALE)
    random.seed(0)
    buf = io.StringIO()
    for _ in range(n):
        buf.write(f"{random.randrange(100)},{random.randrange(1 << 22)}\n")
    buf.seek(0)
    t0 = time.perf_counter()
    chunks = list(_parse_csv_arrays(buf, sys.stderr, 10_000_000))
    emit("host_csv_parse", n / (time.perf_counter() - t0), "bits/sec")

    with tempfile.TemporaryDirectory() as d:
        holder = Holder(d)
        holder.open()
        try:
            frame = holder.create_index("bench").create_frame("f")
            t0 = time.perf_counter()
            for rows, cols, ts in chunks:
                frame.import_bits(rows, cols, ts)
            emit("host_import_apply", n / (time.perf_counter() - t0),
                 "bits/sec")

            ex = Executor(holder, host="local", use_mesh=False)
            k = int(5000 * SCALE)
            ex.execute("bench", 'SetBit(frame="f", rowID=0, columnID=0)')
            t0 = time.perf_counter()
            for i in range(k):
                ex.execute("bench",
                           f'SetBit(frame="f", rowID={i % 50},'
                           f' columnID={i * 13 % (1 << 20)})')
            setbit_exec = k / (time.perf_counter() - t0)
            emit("host_setbit_inprocess", setbit_exec, "ops/sec")
            # Batched bodies (1000 SetBits per query): the executor's
            # mutate-batch run + fast-path parse (round 5).
            kb = max(1000, int(100_000 * SCALE))
            queries = ["\n".join(
                f'SetBit(frame="f", rowID={i % 50},'
                f' columnID={i * 13 % (1 << 20)})'
                for i in range(s, min(s + 1000, kb)))
                for s in range(0, kb, 1000)]
            t0 = time.perf_counter()
            for q in queries:
                ex.execute("bench", q)
            emit("host_setbit_inprocess_batched",
                 kb / (time.perf_counter() - t0), "ops/sec")
            ex.close()
        finally:
            holder.close()

    _write_denominator(setbit_exec)


def _write_denominator(setbit_exec: float) -> None:
    """The write path's measured host-native denominator (round-3
    verdict: writes were the one surface with no reference-equivalent
    number). Runs the same workload through (a) the C++ write
    micro-engine (native.bench_setbit: container mutate + 13-byte WAL
    append per op + snapshot/fsync/rename every MAX_OP_N — the faithful
    stand-in for fragment.go:369-459 with no Go toolchain here) and
    (b) Fragment.set_bit in-process; pins the native best in
    HOST_BASELINE.json and leaves both in benchmarks/WRITEPATH.json for
    bench.py to stamp into the round artifact."""
    import tempfile

    from pilosa_tpu.storage import native
    from pilosa_tpu.storage.fragment import MAX_OP_N, Fragment

    rng = np.random.default_rng(9)
    n = max(1, int(100_000 * SCALE))
    rows = rng.integers(0, 1000, n).astype(np.uint64)
    cols = rng.integers(0, 1 << 20, n).astype(np.uint64)
    pos = (rows << np.uint64(20)) + cols

    native_ops = None
    if native.available():
        with tempfile.TemporaryDirectory() as d:
            t0 = time.perf_counter()
            native.bench_setbit(os.path.join(d, "frag"), pos, MAX_OP_N)
            native_ops = n / (time.perf_counter() - t0)
        emit("host_setbit_native", native_ops, "ops/sec")

    # Same op count as the native leg: amortized snapshot cost grows
    # with bits set so far, so different run lengths would bias the
    # published ratio (review finding, round 4).
    with tempfile.TemporaryDirectory() as d:
        frag = Fragment(os.path.join(d, "frag"), "bench", "f",
                        "standard", 0)
        frag.open()
        try:
            lat = np.empty(n)
            t0 = time.perf_counter()
            for i, (r, c) in enumerate(zip(rows.tolist(),
                                           cols.tolist())):
                t1 = time.perf_counter()
                frag.set_bit(r, c)
                lat[i] = time.perf_counter() - t1
            frag._join_snapshot()
            frag_ops = n / (time.perf_counter() - t0)
            lat.sort()
            p999_ms = float(lat[int(n * 0.999)]) * 1e3
            max_ms = float(lat[-1]) * 1e3
        finally:
            frag.close()
    emit("host_setbit_fragment", frag_ops, "ops/sec",
         p999_ms=round(p999_ms, 2), max_ms=round(max_ms, 1))

    # The batched serving path (round-5: one native crossing + one WAL
    # group-commit per batch — how query fan-outs and pipelined bodies
    # actually hit the fragment). Same workload, same durability.
    batch_ops = {}
    for B in (1000, 4000):
        with tempfile.TemporaryDirectory() as d:
            frag = Fragment(os.path.join(d, "frag"), "bench", "f",
                            "standard", 0)
            frag.open()
            try:
                t0 = time.perf_counter()
                for s in range(0, n, B):
                    frag.set_bits(rows[s:s + B], cols[s:s + B])
                frag._join_snapshot()
                batch_ops[B] = n / (time.perf_counter() - t0)
            finally:
                frag.close()
        emit(f"host_setbit_fragment_batched_b{B}", batch_ops[B],
             "ops/sec")

    # Key carries the op count: snapshot amortization scales with run
    # length, so a short smoke run must not pin the canonical shape.
    pinned = (pin_best(f"setbit_native,n={n}", native_ops)
              if native_ops else None)
    art = {"setbit_native_ops": round(native_ops, 1) if native_ops else None,
           "setbit_native_pinned_ops": round(pinned, 1) if pinned else None,
           "setbit_fragment_ops": round(frag_ops, 1),
           "setbit_fragment_batched_b1000_ops": round(batch_ops[1000], 1),
           "setbit_fragment_batched_b4000_ops": round(batch_ops[4000], 1),
           "setbit_fragment_p999_ms": round(p999_ms, 2),
           "setbit_executor_ops": round(setbit_exec, 1),
           "fragment_vs_native_pinned": (
               round(pinned / frag_ops, 2) if pinned else None),
           "batched_vs_native_pinned": (
               round(pinned / batch_ops[4000], 2) if pinned else None)}
    emit("write_denominator", art["fragment_vs_native_pinned"] or 0.0,
         "x_native_over_fragment", **art)
    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "WRITEPATH.json"), "w") as f:
        json.dump(art, f, indent=1)


def pin_best(name: str, ops_s: float) -> float:
    """Persist the best-ever (highest ops/s) host-native measurement for
    ``name`` on this machine; returns the pinned best (monotone, like
    bench.py's read denominator — one shared writer, benchmarks.pinning)."""
    import platform

    from benchmarks.pinning import pin
    return pin(f"{name},host={platform.node()}", "best_ops_s", ops_s,
               lambda new, old: new > old)


def _build_topn_frame(holder, n_rows: int, n_slices: int):
    """BASELINE config 3's frame: ranked rows with a long tail, columns
    spread over n_slices × 2^20. Bulk-built in slice-grouped batches."""
    from pilosa_tpu import SLICE_WIDTH

    rng = np.random.default_rng(33)
    frame = holder.create_index_if_not_exists("t3") \
        .create_frame_if_not_exists("f")
    # Head: 2000 rows with counts 1000→21 (descending, distinct ranks);
    # tail: the rest at 4 bits each. Totals ~1.4 M bits at full scale.
    head = min(2000, n_rows)
    counts = np.concatenate([
        np.maximum(21, 1000 - np.arange(head)).astype(np.int64),
        np.full(n_rows - head, 4, dtype=np.int64)])
    rows = np.repeat(np.arange(n_rows, dtype=np.uint64), counts)
    cols = rng.integers(0, n_slices * SLICE_WIDTH, size=len(rows),
                        dtype=np.uint64)
    order = np.argsort(cols // np.uint64(SLICE_WIDTH), kind="stable")
    rows, cols = rows[order], cols[order]
    step = max(1, len(rows) // 20)
    for i in range(0, len(rows), step):
        frame.import_bits(rows[i:i + step], cols[i:i + step])
    return frame, int(counts.sum())


def config3_topn1000_end_to_end() -> None:
    """The second clause of the metric of record: TopN(n=1000) p50 on a
    100 K-row × 10 M-column frame (BASELINE config 3, Fragment.Top
    fragment.go:490-625 + rank cache cache.go:126-275), END TO END
    through the executor — candidate phase over the rank caches plus
    the exact merge — first query and residency-warm, device vs host."""
    import tempfile

    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder

    n_rows = max(1000, int(100_000 * SCALE))
    n_slices = max(2, int(10 * SCALE))
    with tempfile.TemporaryDirectory() as d:
        holder = Holder(d)
        holder.open()
        t0 = time.perf_counter()
        _build_topn_frame(holder, n_rows, n_slices)
        build_s = time.perf_counter() - t0

        q = "TopN(frame=f, n=1000)"
        want = None
        legs = (("host", False),)
        if USE_DEVICE:
            # routed before the forced-device leg: the forced leg's
            # drain contaminates whatever follows on this shared core.
            legs += (("routed", True), ("device", True))
        for label, use_mesh in legs:
            ex = Executor(holder, host="local", use_mesh=use_mesh,
                          mesh_min_slices=1)
            if label == "device":
                ex._cost_model_enabled = False
            t0 = time.perf_counter()
            got = ex.execute("t3", q)[0]
            first_s = time.perf_counter() - t0
            if want is None:
                want = got
            assert got == want, (label, len(got), len(want))
            lat = []
            for _ in range(5):
                t0 = time.perf_counter()
                ex.execute("t3", q)
                lat.append(time.perf_counter() - t0)
            lat.sort()
            emit_latency(f"c3_topn1000_e2e_{label}_p50", lat[2] * 1e3,
                         device=(label != "host"),
                         rows=n_rows, slices=n_slices, n=len(want),
                         first_ms=round(first_s * 1e3, 1),
                         p95_ms=round(lat[-1] * 1e3, 1),
                         build_s=round(build_s, 1))
            if label == "device" and SCALE >= 1.0:
                # Refresh the metric-of-record artifact bench.py stamps
                # into its JSON line (full-scale runs only).
                _write_topn1000_artifact(
                    p50_ms=lat[2] * 1e3, p95_ms=lat[-1] * 1e3,
                    first_ms=first_s * 1e3, rows=n_rows,
                    slices=n_slices)
            ex.close()
        holder.close()


def _write_topn1000_artifact(p50_ms, p95_ms, first_ms, rows, slices):
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "TOPN1000.json")
    rec = {
        "config": f"BASELINE config 3: TopN(n=1000), {rows} rows x "
                  f"{slices} slices, end-to-end through the executor",
        "date": time.strftime("%Y-%m-%d"),
        "device_p50_ms": round(p50_ms, 1),
        "device_p95_ms": round(p95_ms, 1),
        "device_first_ms": round(first_ms, 1),
        # None when this run skipped the floor probe — never report a
        # fake 0 (review finding: a p50 below the tunnel floor needs
        # the note below to be interpretable).
        "sync_floor_ms": (round(_SYNC_FLOOR_MS, 1)
                          if _SYNC_FLOOR_MS > 0 else None),
        "note": "plain TopN's candidate walk reads host rank caches on"
                " every leg (no device dispatch exists for the"
                " sourceless form); 'device' = the device-enabled"
                " executor, whose router correctly keeps this query"
                " host-side — that is why the p50 can sit below the"
                " ~65 ms tunnel sync floor",
    }
    try:
        with open(path, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
    except OSError:
        pass


def config4_executor_routing() -> None:
    """Task: the chosen path must never be slower than the better of
    the two. Config-4 shape through the EXECUTOR three ways: host
    (use_mesh=0), forced device (cost model off), and the default
    calibrated routing — emitting all three so the routing quality is
    a measured fact, not an assumption."""
    import tempfile

    from pilosa_tpu import SLICE_WIDTH
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder

    n_slices = max(8, int(128 * SCALE))
    rng = np.random.default_rng(44)
    with tempfile.TemporaryDirectory() as d:
        holder = Holder(d)
        holder.open()
        frame = holder.create_index_if_not_exists("r4") \
            .create_frame_if_not_exists("f")
        for row in (0, 1):
            cols = (rng.integers(0, SLICE_WIDTH, size=200 * n_slices)
                    + np.repeat(np.arange(n_slices), 200) * SLICE_WIDTH)
            frame.import_bits(np.full(len(cols), row, dtype=np.uint64),
                              cols.astype(np.uint64))
        q = ("Count(Intersect(Bitmap(frame=f, rowID=0),"
             " Bitmap(frame=f, rowID=1)))")

        def measure(label, **kw):
            ex = Executor(holder, host="local", mesh_min_slices=1, **kw)
            if label == "device_forced":
                ex._cost_model_enabled = False
            want = ex.execute("r4", q)  # warm (compile/residency/pools)
            lat = []
            for _ in range(7):
                t0 = time.perf_counter()
                got = ex.execute("r4", q)
                lat.append(time.perf_counter() - t0)
            assert got == want
            p50 = sorted(lat)[len(lat) // 2]
            # 8 routed executions (1 warm + 7 timed): all vetoed = the
            # host path, none = the device path, anything in between =
            # mixed per-query decisions (report it, don't guess).
            if label == "routed":
                chose = {0: "device", 8: "host"}.get(ex.cost_vetoes,
                                                     "mixed")
            else:
                chose = "device" if label == "device_forced" else "host"
            emit_latency(f"c4_executor_{label}_p50", p50 * 1e3,
                         device=(chose != "host"),
                         slices=n_slices, vetoes=ex.cost_vetoes)
            ex.close()
            return p50, chose

        # routed before device_forced: the forced leg leaves queued
        # device work draining, which contaminates whatever follows on
        # this shared-core rig.
        host, _ = measure("host", use_mesh=False)
        if USE_DEVICE:
            routed, chose = measure("routed")
            forced, _ = measure("device_forced")
            best = min(host, forced)
            emit("c4_routing_overhead", routed / best, "x_vs_best",
                 host_ms=round(host * 1e3, 2),
                 device_ms=round(forced * 1e3, 2),
                 routed_ms=round(routed * 1e3, 2),
                 chose=chose)
        holder.close()


def config5_executor_cluster_topn() -> None:
    """BASELINE config 5's single-host form through the EXECUTOR: TopN
    over a 256-slice (268 M-column) ranked frame, end to end — the
    candidate phase walks 256 rank caches, the exact phase merges
    cluster-wide, and the calibrated router picks the serving path.
    (The multi-host form of the same program is exercised by the pod
    tests and the driver's dryrun_multichip.)"""
    import tempfile

    from pilosa_tpu import SLICE_WIDTH
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder

    n_slices = max(8, int(256 * SCALE))
    n_rows = max(100, int(1000 * SCALE))
    rng = np.random.default_rng(55)
    with tempfile.TemporaryDirectory() as d:
        holder = Holder(d)
        holder.open()
        frame = holder.create_index_if_not_exists("t5") \
            .create_frame_if_not_exists("f")
        head = min(500, n_rows)
        counts = np.concatenate([
            np.maximum(40, 2000 - 4 * np.arange(head)).astype(np.int64),
            np.full(n_rows - head, 8, dtype=np.int64)])
        rows = np.repeat(np.arange(n_rows, dtype=np.uint64), counts)
        cols = rng.integers(0, n_slices * SLICE_WIDTH, size=len(rows),
                            dtype=np.uint64)
        order = np.argsort(cols // np.uint64(SLICE_WIDTH), kind="stable")
        rows, cols = rows[order], cols[order]
        t0 = time.perf_counter()
        step = max(1, len(rows) // 16)
        for i in range(0, len(rows), step):
            frame.import_bits(rows[i:i + step], cols[i:i + step])
        build_s = time.perf_counter() - t0

        legs = (("host", False),)
        if USE_DEVICE:
            legs += (("routed", True),)
        want: dict = {}
        for label, use_mesh in legs:
            ex = Executor(holder, host="local", use_mesh=use_mesh,
                          mesh_min_slices=1)
            for q, tag in (("TopN(frame=f, n=10)", "plain"),
                           ("TopN(Bitmap(frame=f, rowID=0), frame=f,"
                            " n=10)", "src")):
                t0 = time.perf_counter()
                got = ex.execute("t5", q)[0]
                first_s = time.perf_counter() - t0
                assert want.setdefault(tag, got) == got, (label, tag)
                lat = []
                for _ in range(5):
                    t0 = time.perf_counter()
                    again = ex.execute("t5", q)[0]
                    lat.append(time.perf_counter() - t0)
                assert again == got
                lat.sort()
                # The routed leg only crossed the device when nothing
                # was vetoed (mirrors config4_executor_routing).
                crossed = (label != "host" and ex.cost_vetoes == 0)
                emit_latency(f"c5_executor_topn_{tag}_{label}_p50",
                             lat[2] * 1e3, device=crossed,
                             slices=n_slices, rows=n_rows,
                             first_ms=round(first_s * 1e3, 1),
                             vetoes=ex.cost_vetoes,
                             build_s=round(build_s, 1))
            ex.close()
        holder.close()


_SYNC_FLOOR_MS: float = 0.0


def emit_latency(metric: str, ms: float, device: bool = True,
                 **extra) -> None:
    """Latency emit with the tunnel-floor-subtracted column on DEVICE
    legs, so device-vs-host conclusions transfer to direct-attached
    hardware (where the sync floor is ~1 ms, not ~65-130 ms). Host legs
    never cross the tunnel, so the column would be meaningless there."""
    if device and _SYNC_FLOOR_MS > 0:
        extra["minus_floor_ms"] = round(max(0.0, ms - _SYNC_FLOOR_MS), 3)
    emit(metric, ms, "ms", **extra)


def _measure_sync_floor() -> None:
    global _SYNC_FLOOR_MS
    if not USE_DEVICE:
        return
    from pilosa_tpu.parallel import costmodel, mesh as mesh_mod
    model = costmodel.get_model(mesh_mod.make_mesh())
    _SYNC_FLOOR_MS = model.cal.sync_s * 1e3
    emit("sync_floor", _SYNC_FLOOR_MS, "ms",
         host_gbps=round(model.cal.host_bps / 1e9, 2))


def config_topn1000_1024slices() -> None:
    """Plain TopN(1000) p50 at 1024 slices (the 1 B-column shape) —
    round-3 verdict item 7: the candidate/refetch curve past 256
    slices was uncharacterized; the vectorized rank-array host leg
    (executor._topn_local_host_fn + fragment.present_rows) replaced a
    ~2.4 s per-Pair walk with a ~0.3 s merge. Host path (the rank
    caches ARE the candidate source; no device leg exists for the
    sourceless form)."""
    import tempfile

    from pilosa_tpu import SLICE_WIDTH
    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder

    n_slices = max(16, int(1024 * SCALE))
    n_rows = max(100, int(2000 * SCALE))
    rng = np.random.default_rng(13)
    with tempfile.TemporaryDirectory() as d:
        holder = Holder(d)
        holder.open()
        try:
            frame = holder.create_index_if_not_exists("t1024") \
                .create_frame_if_not_exists("f")
            counts = np.maximum(
                20, 3000 - 2 * np.arange(n_rows)).astype(np.int64)
            rows = np.repeat(np.arange(n_rows, dtype=np.uint64), counts)
            cols = rng.integers(0, n_slices * SLICE_WIDTH,
                                size=len(rows), dtype=np.uint64)
            order = np.argsort(cols // np.uint64(SLICE_WIDTH),
                               kind="stable")
            rows, cols = rows[order], cols[order]
            step = max(1, len(rows) // 32)
            for i in range(0, len(rows), step):
                frame.import_bits(rows[i:i + step], cols[i:i + step])
            ex = Executor(holder, host="local", use_mesh=False)
            q = "TopN(frame=f, n=1000)"
            t0 = time.perf_counter()
            ex.execute("t1024", q)
            first_ms = (time.perf_counter() - t0) * 1e3
            lat = []
            for _ in range(5):
                t0 = time.perf_counter()
                ex.execute("t1024", q)
                lat.append(time.perf_counter() - t0)
            emit("topn1000_1024slices_p50", sorted(lat)[2] * 1e3, "ms",
                 slices=n_slices, rows=n_rows,
                 first_ms=round(first_ms, 1))
            ex.close()
        finally:
            holder.close()


def config_http_pipelined_setbit() -> None:
    """Over-the-wire SetBit through the real HTTP front door: one
    pipelined keep-alive connection driven by a SUBPROCESS client (the
    in-process GIL would contaminate the measurement). The round-4
    wsgiref server measured ~970 op/s here; the round-5 server's
    pipelining + batch lane is the fix (VERDICT r4 item 2)."""
    import subprocess
    import tempfile

    from pilosa_tpu.server.server import Server

    n = max(2000, int(30000 * SCALE))
    with tempfile.TemporaryDirectory() as d:
        srv = Server(d, host="127.0.0.1:0", anti_entropy_interval=0,
                     polling_interval=0)
        srv.open()
        try:
            hostname, port = srv.host.split(":")
            out = subprocess.run(
                [sys.executable,
                 os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "http_pipeline_client.py"),
                 hostname, port, str(n)],
                capture_output=True, text=True, timeout=240)
            for line in out.stdout.splitlines():
                if line.startswith("RESULT"):
                    emit("http_pipelined_setbit",
                         float(line.split()[1]), "ops/sec", n=n)
                    break
            else:
                emit("http_pipelined_setbit", -1, "error",
                     error=out.stderr[-200:])
        finally:
            srv.close()


def config_wire_import() -> None:
    """Bulk import over the real wire: client-side protobuf encode +
    concurrent per-slice POSTs + server-side decode and apply (the
    round-5 packed-sort lanes). Complements host_import_apply, which
    measures only the in-process apply."""
    import tempfile

    from pilosa_tpu.cluster.client import Client
    from pilosa_tpu.server.server import Server

    n = int(1_000_000 * SCALE)
    rng = np.random.default_rng(0)
    rows = rng.integers(0, 300, n).astype(np.uint64)
    cols = rng.integers(0, 1 << 22, n).astype(np.uint64)
    with tempfile.TemporaryDirectory() as d:
        srv = Server(d, host="127.0.0.1:0", anti_entropy_interval=0,
                     polling_interval=0)
        srv.open()
        try:
            client = Client(srv.host)
            client.create_index("wi")
            client.create_frame("wi", "f")
            t0 = time.perf_counter()
            client.import_arrays("wi", "f", rows, cols)
            emit("wire_import", n / (time.perf_counter() - t0),
                 "bits/sec", n=n)
        finally:
            srv.close()


@contextlib.contextmanager
def _write_path_leg(ext: bool, group: bool, fsync: str = "none"):
    """Select one write-path configuration for the A/B legs below:
    the one-crossing extension on/off (roaring reads native_ext.EXT
    per op, so toggling the module attribute is the real switch) and
    the WAL mode env vars, which fragments read at open()."""
    from pilosa_tpu.storage import native_ext

    # Load BEFORE snapshotting: the extension loads lazily at the
    # first Fragment.open() — snapshotting the pre-load None and
    # restoring it on exit would clobber the loaded module for every
    # later leg (load() latches, so it never comes back): round-1 A
    # measures the extension, every round after silently measures
    # pure Python.
    native_ext.load()
    saved_ext = native_ext.EXT
    saved_env = {k: os.environ.get(k)
                 for k in ("PILOSA_TPU_WAL_GROUP", "PILOSA_TPU_WAL_FSYNC")}
    if not ext:
        native_ext.EXT = None
    os.environ["PILOSA_TPU_WAL_GROUP"] = "1" if group else "0"
    os.environ["PILOSA_TPU_WAL_FSYNC"] = fsync
    try:
        yield
    finally:
        native_ext.EXT = saved_ext
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def config_write_path() -> None:
    """ISSUE 8 acceptance table: the write path A/B, interleaved.

    Leg A is the production write path — one compiled crossing per op
    (native/fastmutate.c: container mutate + marshaled WAL record in
    one call) feeding the group-committed WAL. Leg B is the
    pre-ISSUE-8 path: pure-Python mutate through the per-call layers,
    write-through op-log. Rounds interleave A and B so shared-slot
    drift cancels; best-of-rounds is reported (steady state — the
    slot's scheduling stalls are not the write path's cost). Four
    measurements: per-op Fragment.set_bit, per-op through the
    executor (parse + route + mutate), bulk import over the real
    wire, and fsyncs-per-1k-ops from 8 concurrent durable writers
    (group commit coalescing barriers vs one fsync per op). Folds
    into MANIFEST.json `write_path` and merges into WRITEPATH.json
    for bench.py's line of record."""
    import tempfile
    import threading

    from pilosa_tpu.storage.fragment import Fragment

    rounds = 3

    def setbit_leg(n: int) -> float:
        # Steady-state serving shape: 50 rows over the slice (the
        # executor-leg workload) keeps ops landing in EXISTING
        # containers — the production per-op shape. A warmup fifth
        # populates the container set so the measured span isn't
        # dominated by one-time container creation (which bails to
        # the Python path by design).
        with tempfile.TemporaryDirectory() as d:
            frag = Fragment(os.path.join(d, "frag"), "wp", "f",
                            "standard", 0)
            frag.open()
            try:
                rng = np.random.default_rng(7)
                warm = n // 5
                rows = rng.integers(0, 50, n + warm).tolist()
                cols = rng.integers(0, 1 << 20, n + warm).tolist()
                for r, c in zip(rows[:warm], cols[:warm]):
                    frag.set_bit(r, c)
                t0 = time.perf_counter()
                for r, c in zip(rows[warm:], cols[warm:]):
                    frag.set_bit(r, c)
                frag.wal_barrier()  # the ack point is part of the cost
                el = time.perf_counter() - t0
                frag._join_snapshot()
            finally:
                frag.close()
        return n / el

    # Interleaved A/B rounds: per-op Fragment.set_bit.
    n_a, n_b = max(1000, int(40_000 * SCALE)), max(500, int(8_000 * SCALE))
    a_ops = b_ops = 0.0
    for _ in range(rounds):
        with _write_path_leg(ext=True, group=True):
            a_ops = max(a_ops, setbit_leg(n_a))
        with _write_path_leg(ext=False, group=False):
            b_ops = max(b_ops, setbit_leg(n_b))
    emit("writepath_setbit_per_op", a_ops, "ops/sec",
         baseline_ops=round(b_ops, 1), speedup=round(a_ops / b_ops, 2))

    # Executor per-op: the full serving stack minus HTTP — parse
    # (point-mutation regex lane), route (write fast lane), mutate —
    # with the commit barrier at the httpd batch-lane cadence (one
    # barrier acks a 64-query pipelined group, server.py's
    # _query_batcher contract). A per-op barrier would measure the
    # bare write(2) syscall (~80 us on this host), which is exactly
    # the cost group commit exists to amortize — the concurrent-
    # writer fsync leg below covers per-op durability.
    def executor_leg(n: int) -> float:
        from pilosa_tpu.executor import Executor
        from pilosa_tpu.models.holder import Holder
        from pilosa_tpu.storage import wal as wal_mod

        with tempfile.TemporaryDirectory() as d:
            holder = Holder(d)
            holder.open()
            try:
                holder.create_index("wp").create_frame("f")
                ex = Executor(holder, host="local", use_mesh=False)
                warm = n // 5
                queries = [f'SetBit(frame="f", rowID={i % 50},'
                           f' columnID={i * 13 % (1 << 20)})'
                           for i in range(n + warm)]
                for q in queries[:warm]:  # containers + caches warm
                    ex.execute("wp", q)
                t0 = time.perf_counter()
                for i, q in enumerate(queries[warm:]):
                    ex.execute("wp", q)
                    if i % 64 == 63:
                        wal_mod.barrier_all()
                wal_mod.barrier_all()
                el = time.perf_counter() - t0
                ex.close()
            finally:
                holder.close()
        return n / el

    ea_ops = eb_ops = 0.0
    for _ in range(rounds):
        with _write_path_leg(ext=True, group=True):
            ea_ops = max(ea_ops, executor_leg(
                max(1000, int(25_000 * SCALE))))
        with _write_path_leg(ext=False, group=False):
            eb_ops = max(eb_ops, executor_leg(
                max(500, int(6_000 * SCALE))))
    emit("writepath_executor_per_op", ea_ops, "ops/sec",
         baseline_ops=round(eb_ops, 1),
         speedup=round(ea_ops / eb_ops, 2))

    # Wire import (real HTTP: encode + concurrent per-slice POSTs +
    # decode + WAL-first apply + commit barrier before the 200) vs the
    # same block applied in-process — the ≥70%-of-in-process target.
    def wire_leg() -> tuple:
        from pilosa_tpu.cluster.client import Client
        from pilosa_tpu.models.holder import Holder
        from pilosa_tpu.server.server import Server

        n = int(1_000_000 * SCALE)
        rng = np.random.default_rng(0)
        # 50 rows x 4 slices: the steady-ingest shape (containers see
        # ~250 bits each) — matches the per-op legs' row space and the
        # host_import_apply density family.
        rows = rng.integers(0, 50, n).astype(np.uint64)
        cols = rng.integers(0, 1 << 22, n).astype(np.uint64)
        with tempfile.TemporaryDirectory() as d:
            srv = Server(d, host="127.0.0.1:0", anti_entropy_interval=0,
                         polling_interval=0)
            srv.open()
            try:
                client = Client(srv.host)
                client.create_index("wi")
                client.create_frame("wi", "f")
                t0 = time.perf_counter()
                client.import_arrays("wi", "f", rows, cols)
                wire = n / (time.perf_counter() - t0)
            finally:
                srv.close()
        with tempfile.TemporaryDirectory() as d:
            holder = Holder(d)
            holder.open()
            try:
                frame = holder.create_index("wi").create_frame("f")
                t0 = time.perf_counter()
                frame.import_bits(rows, cols)
                inproc = n / (time.perf_counter() - t0)
            finally:
                holder.close()
        return wire, inproc

    wire_bps = inproc_bps = 0.0
    for _ in range(rounds):
        with _write_path_leg(ext=True, group=True):
            w, p = wire_leg()
            wire_bps, inproc_bps = max(wire_bps, w), max(inproc_bps, p)
    emit("writepath_wire_import", wire_bps, "bits/sec",
         inprocess_bps=round(inproc_bps, 1),
         wire_over_inprocess=round(wire_bps / inproc_bps, 3))

    # fsync amortization: 32 concurrent writers (a production ingest
    # fan-in), each op durably acked. A: FSYNC=group — concurrent
    # barriers coalesce into one leader fsync per batch (the
    # reduction factor approaches the writer count). B: the
    # un-amortized discipline — write-through WAL, one fsync per op
    # per writer.
    def fsync_leg(group: bool, per: int) -> tuple:
        n_threads = 32
        with tempfile.TemporaryDirectory() as d:
            frag = Fragment(os.path.join(d, "frag"), "wp", "f",
                            "standard", 0)
            frag.open()
            try:
                errs: list = []
                start = threading.Barrier(n_threads)

                def writer(t: int) -> None:
                    rng = np.random.default_rng(t)
                    # 32 disjoint 32 Ki-column stripes tile the 2^20
                    # slice exactly; << 16 would push t >= 16 past it.
                    base = t << 15
                    try:
                        start.wait()
                        for _ in range(per):
                            frag.set_bit(int(rng.integers(0, 50)),
                                         base + int(rng.integers(0, 3000)))
                            if group:
                                frag.wal_barrier()  # durable ack
                            else:
                                os.fsync(frag._file.fileno())
                    except Exception as e:  # noqa: BLE001
                        errs.append(e)

                threads = [threading.Thread(target=writer, args=(t,))
                           for t in range(n_threads)]
                t0 = time.perf_counter()
                for th in threads:
                    th.start()
                for th in threads:
                    th.join()
                el = time.perf_counter() - t0
                if errs:
                    raise errs[0]
                n = n_threads * per
                fsyncs = frag._wal.fsyncs if group else n
                frag._join_snapshot()
            finally:
                frag.close()
        return n / el, fsyncs * 1000.0 / n

    ga_ops = gb_ops = 0.0
    ga_per1k = gb_per1k = float("inf")
    for _ in range(rounds):
        with _write_path_leg(ext=True, group=True, fsync="group"):
            ops, per1k = fsync_leg(True, max(50, int(400 * SCALE)))
            ga_ops, ga_per1k = max(ga_ops, ops), min(ga_per1k, per1k)
        with _write_path_leg(ext=True, group=False, fsync="none"):
            ops, per1k = fsync_leg(False, max(25, int(125 * SCALE)))
            gb_ops, gb_per1k = max(gb_ops, ops), min(gb_per1k, per1k)
    emit("writepath_fsync_group", ga_ops, "ops/sec",
         fsyncs_per_1k=round(ga_per1k, 1),
         baseline_fsyncs_per_1k=round(gb_per1k, 1),
         reduction_x=round(gb_per1k / max(ga_per1k, 1e-9), 1))

    art = {
        "setbit_per_op_ops": round(a_ops, 1),
        "setbit_per_op_baseline_ops": round(b_ops, 1),
        "setbit_per_op_speedup": round(a_ops / b_ops, 2),
        "executor_per_op_ops": round(ea_ops, 1),
        "executor_per_op_baseline_ops": round(eb_ops, 1),
        "wire_import_bits_s": round(wire_bps, 1),
        "wire_import_mbits_s": round(wire_bps / 1e6, 2),
        "inprocess_import_bits_s": round(inproc_bps, 1),
        "wire_over_inprocess": round(wire_bps / inproc_bps, 3),
        "concurrent_durable_ops_s": round(ga_ops, 1),
        "fsyncs_per_1k_group": round(ga_per1k, 1),
        "fsyncs_per_1k_per_op": round(gb_per1k, 1),
        "fsync_reduction_x": round(gb_per1k / max(ga_per1k, 1e-9), 1),
        "rounds": rounds,
        "scale": SCALE,
        "date": time.strftime("%Y-%m-%d"),
    }
    _WRITE_PATH.update(art)
    # Merge into WRITEPATH.json (the canonical write_path artifact
    # bench.py stamps into its line) alongside _write_denominator's
    # native-denominator keys — merge, not clobber: either config may
    # run without the other.
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "WRITEPATH.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {}
    doc.update(art)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)


def config_distributed_topn() -> None:
    """ROADMAP item 3 acceptance artifact: distributed TopN pushdown
    vs the fan-out path, interleaved A/B on a 2-node IN-PROCESS
    cluster (cross-wired static membership, replicas=1 so slices
    genuinely split), plus a single-node reference server over the
    same data, plus the repeated resident Count(Intersect) chain on
    the coordinator — first call pays the fan-out + fold, repeats
    serve from the generation-validated hot-query cache at the
    /generations round-trip floor. Host path only (mesh off): the
    coordination tax is the thing under test, not device compute.
    Folds into MANIFEST.json `distributed_topn` and writes
    DISTRIBUTED.json for bench.py's line of record."""
    import statistics
    import tempfile
    import urllib.request

    saved_env = {k: os.environ.get(k)
                 for k in ("PILOSA_TPU_MESH", "PILOSA_TPU_WARMUP")}
    os.environ["PILOSA_TPU_MESH"] = "0"
    os.environ["PILOSA_TPU_WARMUP"] = "0"
    from pilosa_tpu import SLICE_WIDTH as W
    from pilosa_tpu.cluster.client import Client as PClient
    from pilosa_tpu.cluster.topology import Node
    from pilosa_tpu.server.server import Server

    def post(host, path, body=b"{}"):
        req = urllib.request.Request(f"http://{host}{path}",
                                     data=body, method="POST")
        return urllib.request.urlopen(req, timeout=30).read()

    def query(host, index, body):
        return json.loads(post(host, f"/index/{index}/query",
                               body.encode()))["results"]

    def p50_ms(host, index, body, reps):
        lat = []
        for _ in range(reps):
            t0 = time.perf_counter()
            query(host, index, body)
            lat.append((time.perf_counter() - t0) * 1e3)
        return statistics.median(lat)

    n_slices = 8
    n_rows = 16
    n_bits = max(2000, int(12_000 * SCALE))
    reps = max(5, int(15 * SCALE))
    rounds = 3
    servers = []
    td = tempfile.TemporaryDirectory()
    try:
        def make(name):
            s = Server(os.path.join(td.name, name), host="127.0.0.1:0",
                       anti_entropy_interval=0, polling_interval=0)
            s.open()
            servers.append(s)
            return s

        s1, s2, solo = make("n1"), make("n2"), make("solo")
        nodes = [Node(s1.host), Node(s2.host)]
        for s in (s1, s2):
            s.cluster.nodes = [Node(n.host) for n in nodes]
        # Static membership has no broadcast channel: create the
        # schema on every node explicitly (server_test.go pattern).
        for h in (s1.host, s2.host, solo.host):
            post(h, "/index/dt")
            post(h, "/index/dt/frame/f")
        rng = np.random.default_rng(11)
        rows = rng.integers(0, n_rows, n_bits).astype(np.uint64)
        cols = rng.choice(n_slices * W, size=n_bits,
                          replace=False).astype(np.uint64)
        PClient(s1.host).import_arrays("dt", "f", rows, cols)
        PClient(solo.host).import_arrays("dt", "f", rows, cols)

        topn_q = 'TopN(frame="f", n=5)'
        # The hot-query cache would serve repeats and hide the merge
        # being measured — off for the TopN legs, back on for the
        # chain leg below.
        s1.executor._cluster_cache_entries = 0
        want = query(solo.host, "dt", topn_q)
        assert query(s1.host, "dt", topn_q) == want, \
            "pushdown merge diverged from single-node"

        # Per-round ADJACENT triples (pushdown, fan-out, single-node)
        # so shared-slot drift cancels in the ratios; best-of-rounds
        # is the steady state. A warmup query per mode arms the
        # speculative hint memo (the cold first pushdown pays an
        # extra round by design).
        query(s1.host, "dt", topn_q)
        push = fan = single = float("inf")
        r_single = r_fanout = float("inf")
        for _ in range(rounds):
            s1.executor._topn_pushdown = True
            p = p50_ms(s1.host, "dt", topn_q, reps)
            s1.executor._topn_pushdown = False
            assert query(s1.host, "dt", topn_q) == want
            fo = p50_ms(s1.host, "dt", topn_q, reps)
            sg = p50_ms(solo.host, "dt", topn_q, reps)
            push, fan, single = (min(push, p), min(fan, fo),
                                 min(single, sg))
            r_single = min(r_single, p / max(sg, 1e-9))
            r_fanout = min(r_fanout, p / max(fo, 1e-9))
        s1.executor._topn_pushdown = True
        emit("distributed_topn_p50", push, "ms",
             fanout_p50_ms=round(fan, 3),
             single_node_p50_ms=round(single, 3),
             vs_single=round(r_single, 3),
             vs_fanout=round(r_fanout, 3))

        # Resident chain: repeated Count(Intersect) over the split
        # slice set — repeats validate generation tokens (~one
        # /generations RTT per peer) instead of re-running the
        # fan-out + fold.
        s1.executor._cluster_cache_entries = 64
        chain_q = ('Count(Intersect(Bitmap(frame="f", rowID=0),'
                   ' Bitmap(frame="f", rowID=1)))')
        t0 = time.perf_counter()
        query(s1.host, "dt", chain_q)
        miss_ms = (time.perf_counter() - t0) * 1e3
        hit_ms = p50_ms(s1.host, "dt", chain_q, reps)
        # The floor the hit is bounded by: one bare /generations
        # probe round-trip to the peer.
        probe = []
        for _ in range(reps):
            t0 = time.perf_counter()
            urllib.request.urlopen(
                f"http://{s2.host}/generations?index=dt&slices=0",
                timeout=10).read()
            probe.append((time.perf_counter() - t0) * 1e3)
        rtt_ms = statistics.median(probe)
        from pilosa_tpu.obs import metrics as obs_metrics
        hits = obs_metrics.CLUSTER_CACHE_REQUESTS.labels("hit").value
        emit("distributed_chain_hit_p50", hit_ms, "ms",
             miss_ms=round(miss_ms, 3),
             generations_rtt_ms=round(rtt_ms, 3),
             vs_rtt_floor=round(hit_ms / max(rtt_ms, 1e-9), 3))
        assert hits >= reps, "chain repeats were not cache hits"

        table = {
            "topn_pushdown_p50_ms": round(push, 3),
            "topn_fanout_p50_ms": round(fan, 3),
            "topn_single_node_p50_ms": round(single, 3),
            "topn_vs_single": round(r_single, 3),
            "topn_vs_fanout": round(r_fanout, 3),
            "chain_miss_ms": round(miss_ms, 3),
            "chain_hit_p50_ms": round(hit_ms, 3),
            "generations_rtt_ms": round(rtt_ms, 3),
            "chain_hit_vs_rtt": round(hit_ms / max(rtt_ms, 1e-9), 3),
            "n_slices": n_slices, "n_rows": n_rows, "bits": n_bits,
            "differential_equal": True,
        }
        _DISTRIBUTED_TOPN.update(table)
        with open(os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "DISTRIBUTED.json"),
                "w") as f:
            json.dump(table, f, indent=1)
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        td.cleanup()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def config_resize() -> None:
    """ROADMAP item 5 acceptance artifact: an online 2→3 node resize
    on an in-process cluster under OPEN query load — records the
    resize duration, the streamed volume, and what the migration did
    to query latency (p50/p99 during vs a baseline window measured
    immediately before, same query mix, same slot). Host path only
    (mesh off): the migration machinery is the thing under test.
    Folds into MANIFEST.json `resize` and writes RESIZE.json for
    bench.py's line of record."""
    import statistics
    import tempfile
    import threading
    import urllib.request

    saved_env = {k: os.environ.get(k)
                 for k in ("PILOSA_TPU_MESH", "PILOSA_TPU_WARMUP")}
    os.environ["PILOSA_TPU_MESH"] = "0"
    os.environ["PILOSA_TPU_WARMUP"] = "0"
    from pilosa_tpu import SLICE_WIDTH as W
    from pilosa_tpu.cluster.client import Client as PClient
    from pilosa_tpu.cluster.topology import Node
    from pilosa_tpu.server.server import Server

    def post(host, path, body=b"{}"):
        req = urllib.request.Request(f"http://{host}{path}",
                                     data=body, method="POST")
        return urllib.request.urlopen(req, timeout=30).read()

    def query(host, index, body):
        return json.loads(post(host, f"/index/{index}/query",
                               body.encode()))["results"]

    n_slices = 8
    n_bits = max(4000, int(20_000 * SCALE))
    baseline_s = max(1.0, 2.0 * SCALE)
    servers = []
    td = tempfile.TemporaryDirectory()
    try:
        def make(name):
            s = Server(os.path.join(td.name, name),
                       host="127.0.0.1:0", anti_entropy_interval=0,
                       polling_interval=0)
            s.open()
            servers.append(s)
            return s

        s1, s2, s3 = make("n1"), make("n2"), make("n3")
        for s in servers:
            s.cluster.nodes = [Node(s1.host), Node(s2.host)]
        for h in (s1.host, s2.host, s3.host):
            post(h, "/index/rs")
            post(h, "/index/rs/frame/f")
        rng = np.random.default_rng(29)
        rows = rng.integers(0, 300, n_bits).astype(np.uint64)
        cols = rng.choice(n_slices * W, size=n_bits,
                          replace=False).astype(np.uint64)
        PClient(s1.host).import_arrays("rs", "f", rows, cols)
        for s in servers:
            s.holder.index("rs").set_remote_max_slice(n_slices - 1)
        model0 = int((rows == 0).sum())
        q = 'Count(Bitmap(frame="f", rowID=0))'
        assert query(s1.host, "rs", q)[0] == model0

        # Wrong answers are collected, not asserted inline: an
        # AssertionError inside the loader THREAD would die silently
        # and the artifact would still claim zero_wrong_answers
        # (review finding) — the join below re-raises.
        wrong: list = []

        def sample_window(stop_fn):
            lat = []
            while not stop_fn():
                t0 = time.perf_counter()
                got = query(s1.host, "rs", q)[0]
                lat.append((time.perf_counter() - t0) * 1e3)
                if got != model0:
                    wrong.append(got)
            return lat

        # Baseline window (steady 2-node cluster, same query).
        t_end = time.perf_counter() + baseline_s
        base = sample_window(lambda: time.perf_counter() >= t_end)

        # Resize under the same open load.
        during: list = []
        done_evt = threading.Event()

        def loader():
            try:
                during.extend(sample_window(done_evt.is_set))
            except Exception as e:  # noqa: BLE001 - surfaced below
                wrong.append(f"loader died: {e!r}")

        t = threading.Thread(target=loader)
        t.start()
        post(s1.host, "/cluster/resize", json.dumps(
            {"hosts": [s1.host, s2.host, s3.host]}).encode())
        op = None
        deadline = time.time() + 120
        while time.time() < deadline:
            op = json.loads(urllib.request.urlopen(
                f"http://{s1.host}/cluster/resize",
                timeout=10).read())["op"]
            if op["phase"] in ("done", "aborted"):
                break
            time.sleep(0.05)
        done_evt.set()
        t.join()
        assert op and op["phase"] == "done", op
        assert not wrong, f"WRONG ANSWERS under migration: {wrong[:5]}"
        assert query(s1.host, "rs", q)[0] == model0
        assert query(s3.host, "rs", q)[0] == model0

        def pct(xs, p):
            xs = sorted(xs)
            return xs[min(len(xs) - 1, int(p * len(xs)))]

        duration_s = (op["finishedAt"] or 0) - op["startedAt"]
        base_p50, base_p99 = (statistics.median(base),
                              pct(base, 0.99))
        dur_p50, dur_p99 = (statistics.median(during),
                            pct(during, 0.99))
        table = {
            "resize_duration_s": round(duration_s, 3),
            "slices_moved": op["slicesMoved"],
            "bytes_streamed": op["bytesStreamed"],
            "stream_passes": op["streamPasses"],
            "baseline_p50_ms": round(base_p50, 3),
            "baseline_p99_ms": round(base_p99, 3),
            "during_p50_ms": round(dur_p50, 3),
            "during_p99_ms": round(dur_p99, 3),
            "p99_inflation": round(dur_p99 / max(base_p99, 1e-9), 3),
            "queries_during": len(during),
            "zero_wrong_answers": True,
            "n_slices": n_slices, "bits": n_bits,
            # All three nodes + the streamer share ONE interpreter
            # (GIL) here, so the inflation is an upper bound on what
            # cross-process deployments see; [cluster] resize-pace
            # trades migration duration for serving headroom.
            "note": "in-process cluster: shared-GIL upper bound",
        }
        emit("resize_duration", duration_s, "s",
             p99_inflation=table["p99_inflation"],
             bytes_streamed=op["bytesStreamed"])
        emit("resize_during_p99", dur_p99, "ms",
             baseline_p99_ms=table["baseline_p99_ms"])
        _RESIZE.update(table)
        with open(os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "RESIZE.json"), "w") as f:
            json.dump(table, f, indent=1)
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        td.cleanup()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def config_tenant_isolation() -> None:
    """ISSUE 14 acceptance artifact: interleaved multi-tenant A/B
    against a REAL server subprocess (the load generator must not
    share the server's interpreter, or the measurement itself
    perturbs the quiet tenant).

    Leg A: the quiet tenant alone, closed-loop — its solo p50/p99.
    Leg B: the same quiet loop while an AGGRESSOR tenant (admission
    cap 2, queue quota 2, 2 s wall ceiling) is driven by 8 concurrent
    Retry-After-honoring workers — 4x its cap — running a dense
    multi-row Union/Count (~0.8 s of work per request). Overflow
    sheds as tenant-scoped 429s; requests whose queue wait pushes
    them past the wall ceiling are cost-policy KILLED (402). Leg C
    (the counterfactual): the identical aggressor against the same
    data with NO tenant policy — it eats the global slot pool and the
    quiet tenant queues behind ~0.8 s queries. Rounds interleave A
    and B; C runs once at the end on a fresh default-policy server
    over the same data dir. Both tenants' successful results are
    differential-checked every probe. Folds into MANIFEST.json
    `tenant_isolation` and writes TENANTS.json."""
    import statistics
    import subprocess
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tests"))
    from podenv import cpu_env, free_port, wait_up

    from pilosa_tpu import SLICE_WIDTH as W
    from pilosa_tpu.cluster.client import Client as PClient

    rounds = 3
    window_s = max(1.5, 3.0 * SCALE)
    # 8 workers against a concurrency cap of 2 (+2 queue quota): 4x
    # the cap offered, 2x what the whole admission envelope accepts.
    aggr_workers, aggr_cap, aggr_quota = 8, 2, 2
    wall_ms = 2000
    n_rows, col_stride = 12, 3

    def post(host, path, body=b"", timeout=120):
        req = urllib.request.Request(f"http://{host}{path}",
                                     data=body, method="POST")
        return urllib.request.urlopen(req, timeout=timeout).read()

    td = tempfile.TemporaryDirectory()
    data_dir = os.path.join(td.name, "data")
    logf = open(os.path.join(td.name, "server.log"), "w")
    env = cpu_env()
    env["PILOSA_TPU_WARMUP"] = "0"
    env["PILOSA_TPU_COST_MODEL"] = "0"
    env["PILOSA_TPU_MESH"] = "0"  # the admission machinery is the
    # thing under test (the config_resize precedent); host path keeps
    # the 0.4 CPU backend's serialized device dispatch out of the A/B

    def spawn(tenants_spec):
        port = free_port()
        p = subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu.cli", "server",
             "-d", data_dir, "-b", f"127.0.0.1:{port}",
             "--tenants", tenants_spec,
             "--anti-entropy.interval", "300s"],
            env=env, stdout=logf, stderr=logf, cwd=repo)
        host = f"127.0.0.1:{port}"
        wait_up(host)
        return p, host

    proc, host = spawn(
        f"default:weight=1;aggr:weight=1,concurrency={aggr_cap},"
        f"queue-depth={aggr_quota},max-wall={wall_ms}ms")
    proc_c = None
    try:
        # Dense rows (every {col_stride}rd column over 4 slices):
        # bitmap containers, so the aggressor's Union folds are big
        # contiguous numpy — the workload shape where per-tenant QoS
        # (not the interpreter) decides who waits.
        for index in ("quiet", "aggr"):
            post(host, f"/index/{index}")
            post(host, f"/index/{index}/frame/f")
            for r in range(n_rows):
                cols_d = np.arange(r % col_stride, 4 * W, col_stride,
                                   dtype=np.uint64)
                PClient(host).import_arrays(
                    index, "f", np.full(len(cols_d), r, np.uint64),
                    cols_d)
        model = len(np.arange(0, 4 * W, col_stride))
        # The 12 rows cycle through every column residue, so their
        # union covers the whole 4-slice column space.
        heavy_model = 4 * W
        heavy = ("Count(Union(" + ",".join(
            f'Bitmap(frame="f", rowID={r})'
            for r in range(n_rows)) + "))").encode()
        quiet_body = b'Count(Bitmap(frame="f", rowID=0))'

        wrong: list = []

        def quiet_probe(h):
            t0 = time.perf_counter()
            got = json.loads(post(h, "/index/quiet/query",
                                  quiet_body))["results"][0]
            if got != model:
                wrong.append(("quiet", got))
            return (time.perf_counter() - t0) * 1e3

        def quiet_window(h, seconds):
            lat = []
            t_end = time.perf_counter() + seconds
            while time.perf_counter() < t_end:
                lat.append(quiet_probe(h))
            return lat

        def drive_aggr(h, seconds, counts):
            stop = threading.Event()
            mu = threading.Lock()

            def worker():
                while not stop.is_set():
                    try:
                        got = json.loads(post(
                            h, "/index/aggr/query",
                            heavy))["results"][0]
                        if got != heavy_model:
                            wrong.append(("aggr", got))
                        c, ra = 200, 0.0
                    except urllib.error.HTTPError as e:
                        e.read()
                        c = e.code
                        ra = float(e.headers.get("Retry-After")
                                   or 0.2)
                    with mu:
                        counts[c] = counts.get(c, 0) + 1
                    if c != 200:
                        # Compliant clients honor Retry-After; a
                        # client that ignores it is a DoS, and even
                        # then the quiet tenant's ADMISSION position
                        # is protected (its slots/queue are its own).
                        stop.wait(min(ra, 1.0))

            threads = [threading.Thread(target=worker)
                       for _ in range(aggr_workers)]
            for t in threads:
                t.start()
            time.sleep(0.3)
            out = quiet_window(h, seconds)
            stop.set()
            for t in threads:
                t.join(timeout=60)
            return out

        def pct(xs, p):
            xs = sorted(xs)
            return xs[min(len(xs) - 1, int(p * len(xs)))]

        # Warm both paths once.
        quiet_probe(host)
        try:
            post(host, "/index/aggr/query", heavy)
        except urllib.error.HTTPError as e:
            e.read()

        solo, contended = [], []
        aggr_counts: dict = {}
        for _ in range(rounds):
            solo.extend(quiet_window(host, window_s))      # leg A
            contended.extend(drive_aggr(host, window_s,
                                        aggr_counts))      # leg B
        shed = aggr_counts.get(429, 0)
        killed = aggr_counts.get(402, 0)
        assert not wrong, f"WRONG ANSWERS: {wrong[:5]}"
        assert shed + killed > 0, (
            f"aggressor at {aggr_workers} workers vs cap {aggr_cap}"
            f" was never shed/killed: {aggr_counts}")
        dbg = json.loads(urllib.request.urlopen(
            f"http://{host}/debug/tenants", timeout=10).read())
        burn = (dbg["tenants"].get("quiet", {}).get("slo", {})
                .get("burnRates", {}).get("5m", 0.0))
        aggr_row = dbg["tenants"].get("aggr", {})
        proc.send_signal(2)
        proc.wait(timeout=30)

        # Leg C: the same aggressor, NO tenant policy, same data.
        # Compared against the SAME solo baseline as leg B (one
        # denominator for both ratios).
        proc_c, host_c = spawn("default:weight=1")
        unpol_counts: dict = {}
        quiet_probe(host_c)  # warm the fresh server's caches
        unpoliced = drive_aggr(host_c, window_s, unpol_counts)
        assert not wrong, f"WRONG ANSWERS (unpoliced): {wrong[:5]}"

        solo_p50, solo_p99 = statistics.median(solo), pct(solo, 0.99)
        cont_p50, cont_p99 = (statistics.median(contended),
                              pct(contended, 0.99))
        unpol_p99 = pct(unpoliced, 0.99)
        ratio = cont_p99 / max(solo_p99, 1e-9)
        unpol_ratio = unpol_p99 / max(solo_p99, 1e-9)
        # The artifact ENFORCES its isolation invariants, not just
        # records them: the quiet tenant's burn must sit under the
        # fast-burn threshold under attack, and the policed quiet
        # p99 must beat the unpoliced counterfactual by a wide
        # margin (the machinery's effect). The 1.5x solo target is
        # recorded with a pass flag — on this CPU-only container the
        # residual is interpreter timesharing (environment_note).
        assert burn < 10.0, f"quiet burn {burn} past threshold"
        assert unpol_p99 > 5 * cont_p99, (
            f"no isolation effect: policed p99 {cont_p99:.1f}ms vs"
            f" unpoliced {unpol_p99:.1f}ms")
        table = {
            "quiet_solo_p50_ms": round(solo_p50, 3),
            "quiet_solo_p99_ms": round(solo_p99, 3),
            "quiet_contended_p50_ms": round(cont_p50, 3),
            "quiet_contended_p99_ms": round(cont_p99, 3),
            "quiet_p99_ratio": round(ratio, 3),
            "quiet_p99_ratio_target": 1.5,
            "quiet_p99_ratio_pass": ratio <= 1.5,
            "quiet_p99_unpoliced_ms": round(unpol_p99, 3),
            "quiet_p99_ratio_unpoliced": round(unpol_ratio, 3),
            "isolation_factor": round(unpol_p99 / max(cont_p99,
                                                      1e-9), 2),
            "quiet_burn_5m": burn,
            "burn_threshold": 10.0,
            "aggr_workers": aggr_workers,
            "aggr_admission_cap": aggr_cap,
            "aggr_offered_over_cap": round(aggr_workers / aggr_cap,
                                           2),
            "aggr_wall_ceiling_ms": wall_ms,
            "aggr_ok": aggr_counts.get(200, 0),
            "aggr_shed_429": shed,
            "aggr_killed_402": killed,
            "aggr_penalty_score": aggr_row.get("penaltyScore", 0.0),
            "aggr_unpoliced_ok": unpol_counts.get(200, 0),
            "zero_wrong_answers": True,
            "rounds": rounds,
            "window_s": window_s,
            "samples_solo": len(solo),
            "samples_contended": len(contended),
            "environment_note": (
                "CPU-only container, single interpreter: the"
                " residual contended-vs-solo inflation is"
                " GIL/core timesharing below the scheduler —"
                " admission wait stays ~0.1 ms under full attack"
                " (per-stage profile); on parallel hardware the"
                " admission numbers are the binding ones"),
        }
        _TENANT_ISOLATION.update(table)
        emit("tenant_isolation_quiet_p99", cont_p99, "ms",
             **{k: v for k, v in table.items()
                if k not in ("quiet_contended_p99_ms",
                             "environment_note")})
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "TENANTS.json")
        with open(path, "w") as f:
            json.dump({"written_by": "benchmarks/suite.py"
                                     " config_tenant_isolation",
                       "scale": SCALE, **table}, f, indent=1)
    finally:
        for pp in (proc, proc_c):
            if pp is not None and pp.poll() is None:
                pp.send_signal(2)
                try:
                    pp.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pp.kill()
        logf.close()
        td.cleanup()


def config_tiered() -> None:
    """ISSUE 16 acceptance artifact: the tiered-storage working-set
    manager serving an index ≥ 10× the resident budget.

    Build a bulk of fragments plus a small working set, snapshot
    everything, and measure the working-set Count p50/p99 through the
    executor twice: leg A all-resident (the baseline), leg B after
    demoting EVERYTHING cold and pushing the bulk into the blob tier
    — so local residency starts at zero, the first probe pays the
    blob fetch + block faults (reported as first_ms), and the warm
    window runs with the manager's eviction/retry pass interleaved
    under a budget of total/10. Every probe differential-checks its
    count against the build-time model: zero wrong answers is an
    assertion, not a hope. Folds into MANIFEST.json `tiered` and
    writes TIERED.json."""
    import statistics
    import tempfile

    from pilosa_tpu.executor import Executor
    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.tier.manager import TierManager

    n_bulk = max(10, int(30 * SCALE))
    n_ws = 2
    n_rows, per_row = 4, 20000
    probes_resident = max(60, int(200 * SCALE))
    probes_tiered = max(90, int(300 * SCALE))

    td = tempfile.TemporaryDirectory()
    holder = Holder(os.path.join(td.name, "data"))
    holder.open()
    ex = Executor(holder, host="local", use_mesh=False)
    try:
        rng = np.random.default_rng(16)
        model: dict = {}
        frags: dict = {}
        names = [f"bulk{i}" for i in range(n_bulk)] + \
                [f"ws{i}" for i in range(n_ws)]
        for name in names:
            idx = holder.create_index(name)
            view = idx.create_frame("f").create_view_if_not_exists(
                "standard")
            frag = view.create_fragment_if_not_exists(0)
            rows_np, cols_np, counts = [], [], {}
            for r in range(n_rows):
                cols = np.unique(rng.integers(
                    0, 1 << 20, size=per_row)).astype(np.uint64)
                rows_np.append(np.full(len(cols), r, np.uint64))
                cols_np.append(cols)
                counts[r] = len(cols)
            frag.import_bits(np.concatenate(rows_np),
                             np.concatenate(cols_np))
            model[name] = counts
            frags[name] = frag
        total_bytes = sum(os.path.getsize(f.path)
                          for f in frags.values())
        budget = total_bytes // 10
        ws_bytes = sum(os.path.getsize(frags[f"ws{i}"].path)
                       for i in range(n_ws))

        wrong: list = []

        def probe(i: int) -> float:
            name = f"ws{i % n_ws}"
            r = (i // n_ws) % n_rows
            t0 = time.perf_counter()
            got = ex.execute(
                name, f'Count(Bitmap(frame="f", rowID={r}))')[0]
            dt = (time.perf_counter() - t0) * 1e3
            if got != model[name][r]:
                wrong.append((name, r, got))
            return dt

        def pct(xs, p):
            xs = sorted(xs)
            return xs[min(len(xs) - 1, int(p * len(xs)))]

        probe(0)  # warm the executor path once
        resident = [probe(i) for i in range(probes_resident)]

        mgr = TierManager(
            holder, resident_budget=budget, high_watermark=0.9,
            low_watermark=0.7, idle_s=30.0, blob_idle_s=60.0,
            cold_dir=os.path.join(td.name, "_tier"), blob="dir",
            pace_s=0.0)
        holder.tier = mgr
        mgr.sync()
        for frag in frags.values():
            frag.demote_cold()
        for i in range(n_bulk):
            mgr.push_blob(frags[f"bulk{i}"])
        local_bytes = sum(
            os.path.getsize(f.path) for f in frags.values()
            if os.path.exists(f.path))

        first_ms = probe(0)  # pays the blob fetch + block faults
        for i in range(n_ws):
            # The prefetcher's move for a known-hot working set:
            # promote fully so the warm window measures the resident
            # fast path, not a long cold-fault ramp.
            frags[f"ws{i}"].promote(trigger="prefetch")
        tiered = []
        for i in range(probes_tiered):
            if i % 50 == 25:
                mgr.pass_once()  # eviction pressure stays live
            tiered.append(probe(i))

        assert not wrong, f"WRONG ANSWERS: {wrong[:5]}"
        res_p50, res_p99 = statistics.median(resident), pct(resident,
                                                            0.99)
        t_p50, t_p99 = statistics.median(tiered), pct(tiered, 0.99)
        ratio = t_p99 / max(res_p99, 1e-9)
        oversub = total_bytes / max(budget, 1)
        assert oversub >= 10.0, f"index only {oversub:.1f}× budget"
        assert ratio <= 1.2, (
            f"hot working-set p99 {t_p99:.3f}ms is {ratio:.2f}× the"
            f" all-resident {res_p99:.3f}ms (target ≤ 1.2×)")
        st = mgr.state()
        table = {
            "total_bytes": total_bytes,
            "resident_budget_bytes": budget,
            "oversubscription": round(oversub, 2),
            "working_set_bytes": ws_bytes,
            "local_bytes_after_blob_push": local_bytes,
            "fragments_bulk": n_bulk,
            "fragments_ws": n_ws,
            "resident_p50_ms": round(res_p50, 4),
            "resident_p99_ms": round(res_p99, 4),
            "tiered_p50_ms": round(t_p50, 4),
            "tiered_p99_ms": round(t_p99, 4),
            "tiered_first_probe_ms": round(first_ms, 3),
            "p99_ratio": round(ratio, 3),
            "p99_ratio_target": 1.2,
            "p99_ratio_pass": ratio <= 1.2,
            "zero_wrong_answers": True,
            "samples_resident": len(resident),
            "samples_tiered": len(tiered),
            "blob_pushes": st["blobPushes"],
            "blob_fetches": st["blobFetches"],
            "promotions": st["promotions"],
            "demotions": st["demotions"],
        }
        _TIERED.update(table)
        emit("tiered_hot_ws_p99", t_p99, "ms", first_ms=round(
            first_ms, 3), **{k: v for k, v in table.items()
                             if k != "tiered_p99_ms"})
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "TIERED.json")
        with open(path, "w") as f:
            json.dump({"written_by": "benchmarks/suite.py"
                                     " config_tiered",
                       "scale": SCALE, **table}, f, indent=1)
    finally:
        ex.close()
        holder.close()
        td.cleanup()


def config_backup() -> None:
    """Disaster-recovery acceptance artifact (ISSUE 20), two legs:
    (a) backup-while-serving overhead — the bench-leg query p50 with
    a full cluster-backup coordinator pass IN FLIGHT for every
    on-sample (steady-state warm pool, the coordinator's default
    inter-fragment pacing) vs no backup, interleaved in alternating
    rounds (the config_obs_overhead pattern at a 100% backup duty
    cycle); acceptance: on/off p50 ratio ≤ 1.05.
    (b) restore wall time — the same archive restored into a FRESH
    empty node (schema recreate + digest-verified admission + WAL
    replay), with a correctness probe against the source's answers.
    Host path only (mesh off): the snapshot/push/verify machinery is
    the thing under test. Folds into MANIFEST.json ``backup`` for
    bench.py's line of record."""
    import statistics
    import tempfile
    import urllib.request

    saved_env = {k: os.environ.get(k)
                 for k in ("PILOSA_TPU_MESH", "PILOSA_TPU_WARMUP")}
    os.environ["PILOSA_TPU_MESH"] = "0"
    os.environ["PILOSA_TPU_WARMUP"] = "0"
    from pilosa_tpu import SLICE_WIDTH as W
    from pilosa_tpu.backup import archive as backup_archive
    from pilosa_tpu.backup import coordinator as backup_coord
    from pilosa_tpu.backup import restore as backup_restore
    from pilosa_tpu.cluster.client import Client as PClient
    from pilosa_tpu.server.server import Server
    from pilosa_tpu.utils.config import BackupConfig

    def post(host, path, body=b"{}"):
        req = urllib.request.Request(f"http://{host}{path}",
                                     data=body, method="POST")
        return urllib.request.urlopen(req, timeout=30).read()

    def query(host, body):
        return json.loads(post(host, "/index/b/query",
                               body.encode()))["results"]

    n_slices = 8
    n_rows = 12
    n_bits = max(4000, int(20_000 * SCALE))
    servers = []
    td = tempfile.TemporaryDirectory()
    try:
        arch = os.path.join(td.name, "archive")
        bc = BackupConfig(archive=f"dir:{arch}", wal_interval=60.0)
        srv = Server(os.path.join(td.name, "src"),
                     host="127.0.0.1:0", anti_entropy_interval=0,
                     polling_interval=0, backup_config=bc)
        srv.open()
        servers.append(srv)
        post(srv.host, "/index/b")
        post(srv.host, "/index/b/frame/f")
        rng = np.random.default_rng(20)
        rows = rng.integers(0, n_rows, n_bits).astype(np.uint64)
        cols = rng.choice(n_slices * W, size=n_bits,
                          replace=False).astype(np.uint64)
        PClient(srv.host).import_arrays("b", "f", rows, cols)
        # Drain the import backlog out of the WAL archiver so every
        # backup pass pays the same (steady-state) archiving cost
        # instead of the first on-window eating the whole backlog.
        srv.wal_archiver.flush()
        want = [query(srv.host, f"Count(Bitmap(rowID={r},"
                                f' frame="f"))')[0]
                for r in range(n_rows)]

        children = ", ".join(f"Bitmap(rowID={r}, frame=f)"
                             for r in range(n_rows))
        q = f"Union({children})"

        def run_group(samples, n=40):
            for _ in range(n):
                srv.executor._bitmap_results.clear()
                t0 = time.perf_counter()
                query(srv.host, q)
                samples.append(time.perf_counter() - t0)

        warm: list = []
        run_group(warm, 40)

        def backup_done(coord):
            return (coord.finished_at
                    or coord.phase in (backup_coord.PHASE_DONE,
                                       backup_coord.PHASE_FAILED))

        def wait_backup(coord):
            while not backup_done(coord):
                time.sleep(0.002)
            assert coord.phase == backup_coord.PHASE_DONE, coord.error

        # Warm the pool with one full pass so every measured pass is
        # steady state (snapshot + verify + exists-skip — the
        # economics every backup after the first actually has).
        wait_backup(srv.start_backup("full"))

        # The on-window is the production scenario itself: ONE backup
        # in flight (per-fragment WAL-barriered snapshot over HTTP,
        # footer verify, body digest, pool exists-checks, journal +
        # manifest fsyncs, with the coordinator's default
        # inter-fragment pacing — pacing IS the discipline that keeps
        # backup work out of serving's way) while the bench leg
        # queries. Every on-sample STARTS with the coordinator
        # active, so the on-window duty cycle is 100%, still far
        # above production (one backup per day, not back-to-back
        # rounds).
        def on_round(samples):
            coord = srv.start_backup("full")
            n = 0
            while not backup_done(coord):
                srv.executor._bitmap_results.clear()
                t0 = time.perf_counter()
                query(srv.host, q)
                samples.append(time.perf_counter() - t0)
                n += 1
            assert coord.phase == backup_coord.PHASE_DONE, coord.error
            return n

        on_samples: list = []
        off_samples: list = []
        passes = 0
        rounds = max(6, int(12 * SCALE))
        for _ in range(rounds):
            run_group(off_samples)
            on_round(on_samples)
            passes += 1
        assert len(on_samples) >= rounds, \
            "backup passes too short to sample under"
        on_p50 = statistics.median(on_samples)
        off_p50 = statistics.median(off_samples)
        ratio = on_p50 / max(off_p50, 1e-9)

        # Restore leg: a FRESH empty node, the real admission path
        # (re-crc every object, re-digest every body, WAL replay),
        # then the answers must match the source's.
        rest = Server(os.path.join(td.name, "restored"),
                      host="127.0.0.1:0", anti_entropy_interval=0,
                      polling_interval=0)
        rest.open()
        servers.append(rest)
        store = backup_archive.open_archive(f"dir:{arch}",
                                            rest.holder.path)
        t0 = time.perf_counter()
        summary = backup_restore.run_restore(rest.host, store)
        restore_wall = time.perf_counter() - t0
        got = [query(rest.host, f"Count(Bitmap(rowID={r},"
                                f' frame="f"))')[0]
               for r in range(n_rows)]
        assert got == want, "restored answers diverged from source"

        _BACKUP.update({
            "on_p50_ms": round(on_p50 * 1e3, 4),
            "off_p50_ms": round(off_p50 * 1e3, 4),
            "ratio": round(ratio, 4),
            "samples_on": len(on_samples),
            "samples_off": len(off_samples),
            "rounds": rounds,
            "backup_passes_during_on": passes,
            "restore_wall_s": round(restore_wall, 4),
            "restore_fragments": summary["fragments"],
            "restore_wal_only_fragments": summary["walOnlyFragments"],
            "restore_wal_ops_bytes": summary["walOpsBytes"],
            "restore_answers_match": True,
            "n_slices": n_slices, "n_rows": n_rows, "bits": n_bits,
            "query": f"Union over {n_rows} rows",
            "cadence_note":
                "every on-sample starts with a full coordinator pass"
                " in flight (steady-state warm pool, default"
                " inter-fragment pacing) — a 100% backup duty cycle,"
                " far above production's one pass per operator"
                " request",
            "device": USE_DEVICE,
            "target_ratio": 1.05,
        })
        emit("backup_overhead_on_p50", on_p50 * 1e3, "ms")
        emit("backup_overhead_off_p50", off_p50 * 1e3, "ms")
        emit("backup_overhead_ratio", ratio, "x_on_vs_off",
             target=1.05)
        emit("backup_restore_wall", restore_wall, "s",
             fragments=summary["fragments"])
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        td.cleanup()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def main(argv: Optional[list] = None) -> None:
    """Full pass by default; ``suite.py <config_name>...`` runs just
    the named configs (e.g. ``suite.py config_write_path``) and folds
    their families into MANIFEST.json, carrying every other family
    forward from the prior full pass."""
    configs = (_measure_sync_floor,
               config1_fragment_intersect_count,
               config2_union_difference_1k_rows,
               config2_executor_wide_union,
               config3_topn_latency,
               config3_topn1000_end_to_end,
               config4_mesh_count_over_slices,
               config4_executor_routing,
               config5_cluster_topn,
               config5_executor_cluster_topn,
               config_topn1000_1024slices,
               config_residency_repeat_latency,
               config_host_write_and_import,
               config_http_pipelined_setbit,
               config_wire_import,
               config_write_path,
               config_distributed_topn,
               config_resize,
               config_tenant_isolation,
               config_tiered,
               config_obs_overhead,
               config_obs_history,
               config_scrub_overhead,
               config_planner,
               config_replay,
               config_backup,
               config_query_cost,
               config_container_mix,
               config_compile_stability,
               emit_compile_cache)
    names = [a for a in (sys.argv[1:] if argv is None else argv)
             if not a.startswith("-")]
    if names:
        table = {fn.__name__: fn for fn in configs}
        unknown = [n for n in names if n not in table]
        if unknown:
            raise SystemExit(
                f"unknown config(s) {unknown}; "
                f"choose from {sorted(table)}")
        fns = [table[n] for n in names]
    else:
        fns = list(configs)
    for fn in fns:
        try:
            fn()
        except Exception as e:  # noqa: BLE001 - report and continue
            emit(fn.__name__, -1, "error", error=str(e)[:200])
    try:
        write_manifest(partial=bool(names))
    except Exception as e:  # noqa: BLE001 - manifest must not kill runs
        print(f"manifest write failed: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()

