"""Recorded-traffic replay artifact: capture -> replay -> shadow.

The ROADMAP item-4 sustained-QPS artifact, recorded from real traffic
shape instead of synthetic arrivals (docs/OBSERVABILITY.md):

1. start an in-process server with ``[capture] mode = "full"`` and
   drive a mixed read/write workload through HTTP — SetBit /
   SetFieldValue writes, Bitmap / Count / fused Union-Intersect-
   Difference trees, TopN, BSI Range reads — so every request lands
   in the capture ring with its arrival gaps, lane, and digest;
2. export the stream from /debug/capture/records, tile it to the
   target length, and re-issue it with the multi-process open-loop
   driver (pilosa_tpu.obs.replay) compressed to >= 20K QPS offered,
   recording per-lane p50/p99, shed rates, and achieved-vs-offered
   QPS honestly (this container's host ceiling decides achieved);
3. shadow-diff proof: replay the same stream against two identically
   seeded servers (writes to both in order, read digests compared) —
   zero mismatches self-vs-self — then flip ONE bit on the candidate
   side and show the diff catches it, naming the plan fingerprint;
4. capture-overhead A/B: interleaved on(sampled default)/off groups,
   p50 ratio target <= 1.02, plus the nop-path proof when disabled.

Writes benchmarks/REPLAY.json and folds MANIFEST ``replay`` +
``capture_overhead`` sections. Run directly or via
``benchmarks/suite.py config_replay``.

Env knobs: PILOSA_REPLAY_TARGET_QPS (offered target, default 21000),
PILOSA_REPLAY_CAPTURE_N (captured query count, default 3000),
PILOSA_REPLAY_PROCESSES (driver processes, default 4).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import urllib.request

_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_DIR))

# The artifact measures the serving/capture/replay planes, not the
# device: keep the serving path deterministic and CPU-local.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PILOSA_TPU_MESH"] = "0"
os.environ["PILOSA_TPU_WARMUP"] = "0"

TARGET_QPS = float(os.environ.get("PILOSA_REPLAY_TARGET_QPS", "21000"))
CAPTURE_N = int(os.environ.get("PILOSA_REPLAY_CAPTURE_N", "3000"))
PROCESSES = int(os.environ.get("PILOSA_REPLAY_PROCESSES", "4"))


def _post(host: str, path: str, body: bytes = b"",
          timeout: float = 30.0):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def _start_server(tmp_dir: str, mode: str = "full"):
    from pilosa_tpu.server.server import Server
    from pilosa_tpu.utils.config import CaptureConfig, QueryConfig

    server = Server(
        tmp_dir, host="127.0.0.1:0",
        anti_entropy_interval=0, polling_interval=0,
        query_config=QueryConfig(concurrency=8, queue_depth=64),
        capture_config=CaptureConfig(mode=mode))
    server.open()
    _post(server.host, "/index/i", b"{}")
    _post(server.host, "/index/i/frame/f", json.dumps(
        {"options": {"fields": [
            {"name": "v", "min": 0, "max": 1000}
        ]}}).encode())
    return server


def _drive_workload(host: str, n: int) -> None:
    """The captured mixed stream: ~1/8 writes, reads spanning single
    bitmaps, fused trees, TopN, and BSI Range — the query shapes whose
    digests the shadow diff must canonicalize."""
    import random
    rng = random.Random(19)
    fused = ('Count(Intersect(Union(Bitmap(rowID=1, frame="f"),'
             ' Bitmap(rowID=2, frame="f")),'
             ' Difference(Bitmap(rowID=3, frame="f"),'
             ' Bitmap(rowID=4, frame="f"))))')
    for i in range(n):
        r = i % 8
        if r == 0:
            q = (f'SetBit(rowID={rng.randrange(16)}, frame="f",'
                 f' columnID={rng.randrange(65536)})')
        elif r == 1:
            q = (f'SetFieldValue(frame="f",'
                 f' columnID={rng.randrange(4096)},'
                 f' v={rng.randrange(1000)})')
        elif r in (2, 3):
            q = f'Bitmap(rowID={rng.randrange(16)}, frame="f")'
        elif r == 4:
            q = fused
        elif r == 5:
            q = 'TopN(frame="f", n=5)'
        elif r == 6:
            q = f'Range(frame="f", v > {rng.randrange(500)})'
        else:
            q = (f'Count(Union(Bitmap(rowID={rng.randrange(8)},'
                 f' frame="f"), Bitmap(rowID={rng.randrange(8, 16)},'
                 f' frame="f")))')
        _post(host, "/index/i/query", q.encode())


def _tile(records: list[dict], copies: int) -> list[dict]:
    """Concatenate ``copies`` shifted repetitions of the stream — the
    'scaled captured workload': same shape and mix, longer run."""
    if copies <= 1 or not records:
        return records
    span = (records[-1]["t"] - records[0]["t"]) or 1e-3
    out: list[dict] = []
    for c in range(copies):
        for rec in records:
            r = dict(rec)
            r["t"] = r["t"] + c * span
            if "mono" in r:
                r["mono"] = r["mono"] + c * span
            out.append(r)
    return out


def run_replay():
    """Capture a live mixed workload, then re-drive it multi-process
    at >= TARGET_QPS offered."""
    from pilosa_tpu.obs import replay as obs_replay

    with tempfile.TemporaryDirectory() as tmp:
        server = _start_server(tmp, mode="full")
        try:
            t0 = time.perf_counter()
            _drive_workload(server.host, CAPTURE_N)
            capture_s = time.perf_counter() - t0
            records = obs_replay.fetch_records(server.host,
                                               limit=10000)
            # Scale: tile the stream so the compressed schedule holds
            # the offered target for ~1s+, then compress the recorded
            # gaps to hit TARGET_QPS offered.
            n_q = sum(1 for r in records if r["kind"] == "query")
            span = max(1e-3, records[-1]["t"] - records[0]["t"])
            copies = max(1, int(round(TARGET_QPS * 1.0
                                      / max(n_q, 1))))
            tiled = _tile(records, copies)
            rate = (TARGET_QPS * (span * copies)
                    / max(n_q * copies, 1))
            summary = obs_replay.replay(
                tiled, server.host, rate=rate,
                processes=PROCESSES, senders=48)
            summary["captured_records"] = len(records)
            summary["capture_wall_s"] = round(capture_s, 3)
            summary["tiled_copies"] = copies
            summary["target_offered_qps"] = TARGET_QPS
            return summary, records
        finally:
            server.close()


def run_shadow(records: list[dict]) -> dict:
    """Self-shadow proof + seeded-fault detection over the captured
    stream, against two identically seeded (empty) servers: the
    shadow write phase replays the captured writes to both in order,
    so read digests must agree bit-for-bit; then one flipped bit on
    the candidate must surface as a mismatch naming the plan
    fingerprint."""
    from pilosa_tpu.obs import replay as obs_replay

    with tempfile.TemporaryDirectory() as tb, \
            tempfile.TemporaryDirectory() as tc:
        base = _start_server(tb, mode="off")
        cand = _start_server(tc, mode="off")
        try:
            self_diff = obs_replay.shadow(records, base.host,
                                          cand.host, senders=16)
            # Seeded fault: ONE bit flipped on the candidate only.
            _post(cand.host, "/index/i/query",
                  b'SetBit(rowID=1, frame="f", columnID=31337)')
            fault_diff = obs_replay.shadow(
                [r for r in records if r.get("lane") == "read"],
                base.host, cand.host, senders=16)
        finally:
            base.close()
            cand.close()
    return {
        "self": {k: v for k, v in self_diff.items() if k != "dumps"},
        "self_zero_mismatches": self_diff["mismatches"] == 0,
        "seeded_fault": {
            "fault": "SetBit(rowID=1, columnID=31337) on candidate"
                     " only",
            "mismatches": fault_diff["mismatches"],
            "detected": fault_diff["mismatches"] > 0,
            "first_dumps": [
                {k: d.get(k) for k in ("pql", "plan",
                                       "baselineDigest",
                                       "candidateDigest")}
                for d in fault_diff["dumps"][:3]],
        },
    }


def run_overhead() -> dict:
    """Interleaved capture on/off A/B at the sampled default, through
    the full HTTP stack (the config_obs_overhead discipline: small
    alternating groups so shared-VM noise lands on both modes), plus
    the nop-path proof: mode=off never touches the ring."""
    from pilosa_tpu.obs.capture import CaptureStore

    with tempfile.TemporaryDirectory() as tmp:
        server = _start_server(tmp, mode="sampled")
        cap = server.capture
        try:
            q = b'Count(Bitmap(rowID=1, frame="f"))'
            _post(server.host, "/index/i/query", q)  # warm

            def run_group(samples, n=60):
                for _ in range(n):
                    t0 = time.perf_counter()
                    _post(server.host, "/index/i/query", q)
                    samples.append(time.perf_counter() - t0)

            on: list = []
            off: list = []
            warm: list = []
            run_group(warm, 40)
            # Per-query interleave, pair order alternated: both
            # populations sample the SAME instants of shared-VM load,
            # so the p50 ratio isolates the capture cost itself
            # instead of whatever the neighbor VM was doing during
            # one mode's block.
            for i in range(1200):
                legs = [("off", off), ("sampled", on)]
                if i % 2:
                    legs.reverse()
                for mode, sink in legs:
                    cap.mode = mode
                    run_group(sink, 1)
            cap.mode = "off"
            written_before = cap.ring.written
            run_group([], 50)
            nop_appends = cap.ring.written - written_before
        finally:
            server.close()
    on.sort()
    off.sort()
    on_p50 = on[len(on) // 2]
    off_p50 = off[len(off) // 2]
    return {
        "on_p50_ms": round(on_p50 * 1e3, 4),
        "off_p50_ms": round(off_p50 * 1e3, 4),
        "ratio": round(on_p50 / off_p50, 4),
        "target_ratio": 1.02,
        "mode": "sampled (default, 1-in-16 reads, every write)",
        "samples_per_mode": len(on),
        "nop_path": {"disabled_appends": nop_appends,
                     "proven": nop_appends == 0},
    }


def _fold_into_manifest(doc: dict) -> None:
    path = os.path.join(_DIR, "MANIFEST.json")
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        manifest = {"canonical_artifacts": {}, "metrics": {}}
    manifest.setdefault("canonical_artifacts", {})[
        "replay"] = "REPLAY.json"
    manifest["replay"] = doc["replay"]
    manifest["capture_overhead"] = doc["capture_overhead"]
    metrics = manifest.setdefault("metrics", {})
    metrics["replay_offered_qps"] = {
        "value": doc["replay"]["offered_qps"], "unit": "qps"}
    metrics["replay_achieved_qps"] = {
        "value": doc["replay"]["achieved_qps"], "unit": "qps"}
    metrics["capture_overhead_ratio"] = {
        "value": doc["capture_overhead"]["ratio"],
        "unit": "x_on_vs_off", "target": 1.02}
    with open(path, "w") as f:
        json.dump(manifest, f, indent=1)


def run() -> dict:
    replay_summary, records = run_replay()
    shadow_summary = run_shadow(records)
    overhead = run_overhead()
    out = {
        "written_by": "benchmarks/replay.py",
        "note": "Recorded-traffic open-loop replay"
                " (docs/OBSERVABILITY.md): a captured mixed"
                " read/write stream re-driven multi-process with"
                " recorded arrival gaps compressed to the offered"
                " target; latency counts from the scheduled send"
                " time, so overload shows up as p99, and shed counts"
                " 429/402/507 answers. achieved_qps is this host's"
                " honest ceiling for the python serving stack.",
        "replay": replay_summary,
        "shadow": shadow_summary,
        "capture_overhead": overhead,
    }
    with open(os.path.join(_DIR, "REPLAY.json"), "w") as f:
        json.dump(out, f, indent=1)
    _fold_into_manifest(out)
    return out


def main() -> None:
    out = run()
    print(json.dumps({
        "metric": "replay",
        "offered_qps": out["replay"]["offered_qps"],
        "achieved_qps": out["replay"]["achieved_qps"],
        "shadow_self_mismatches":
            out["shadow"]["self"]["mismatches"],
        "seeded_fault_detected":
            out["shadow"]["seeded_fault"]["detected"],
        "capture_overhead_ratio": out["capture_overhead"]["ratio"],
    }))


if __name__ == "__main__":
    main()
