"""Open-loop latency under load: p50/p99 + rejected count vs the
admission cap.

Serving quality under overload is decided by admission policy, not
kernel speed: a server without a bounded queue answers saturating
load with unbounded queueing (latency grows without limit), while the
sched subsystem's admission controller answers it with fast 429s and
keeps the admitted requests' latency flat. This benchmark measures
exactly that contract against an in-process server:

1. measure the server's closed-loop service rate for the probe query,
2. run two OPEN-LOOP phases at fixed arrival rates — below (~0.4×)
   and above (~3×) the measured capacity — where requests fire on a
   fixed schedule regardless of completions (so queueing delay shows
   up as latency, the open-loop property closed-loop benchmarks hide),
3. record per-phase p50/p99 of successful requests, the 429 count,
   and throughput into benchmarks/LATENCY.json, and fold the headline
   numbers into benchmarks/MANIFEST.json alongside the roofline
   artifacts.

Latency is measured from the SCHEDULED send time (open-loop
accounting: sender-pool delay counts as latency). Run directly
(``python -m benchmarks.latency_under_load``) or via ``python
bench.py --latency-under-load``.

Env knobs: PILOSA_LUL_CONCURRENCY (admission cap, default 4),
PILOSA_LUL_QUEUE_DEPTH (default 8), PILOSA_LUL_PHASE_S (seconds per
phase, default 3), PILOSA_LUL_MAX_RPS (arrival-rate clamp, default
250).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_DIR))

# The benchmark measures the admission/queueing layer, not the device:
# keep the serving path deterministic and CPU-local.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PILOSA_TPU_MESH"] = "0"
os.environ["PILOSA_TPU_WARMUP"] = "0"

PROBE_QUERY = ("Count(Union(" + ", ".join(
    f'Bitmap(frame="f", rowID={r})' for r in range(32)) + "))").encode()


def _post(host: str, path: str, body: bytes = b"",
          timeout: float = 30.0):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def _percentile(sorted_vals: list[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1,
            max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def _start_server(tmp_dir: str, concurrency: int, queue_depth: int):
    from pilosa_tpu import SLICE_WIDTH
    from pilosa_tpu.server.server import Server
    from pilosa_tpu.utils.config import QueryConfig
    import numpy as np

    server = Server(tmp_dir, host="127.0.0.1:0",
                    anti_entropy_interval=0, polling_interval=0,
                    query_config=QueryConfig(concurrency=concurrency,
                                             queue_depth=queue_depth))
    server.open()
    _post(server.host, "/index/i", b"{}")
    _post(server.host, "/index/i/frame/f", b"{}")
    # 32 rows × 4 slices of bits: enough per-query work that the probe
    # exercises a real fold, small enough to build instantly.
    idx = server.holder.index("i")
    frame = idx.frame("f")
    rng = np.random.default_rng(7)
    for r in range(32):
        cols = rng.choice(4 * SLICE_WIDTH, size=2000,
                          replace=False).astype(np.uint64)
        frame.import_bits(np.full(len(cols), r, np.uint64), cols, None)
    return server


def _measure_capacity_rps(host: str, seconds: float = 1.0) -> float:
    """Closed-loop sequential service rate of the probe query."""
    _post(host, "/index/i/query", PROBE_QUERY)  # warm
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        _post(host, "/index/i/query", PROBE_QUERY)
        n += 1
    return n / (time.perf_counter() - t0)


def _run_phase(host: str, rate_rps: float, duration_s: float,
               n_senders: int = 64) -> dict:
    """Fixed-arrival-rate open loop: one request every 1/rate seconds,
    fired by a sender pool; latency counts from the SCHEDULED time."""
    n_requests = max(1, int(rate_rps * duration_s))
    interval = 1.0 / rate_rps
    latencies: list[float] = []
    rejected = 0
    errors = 0
    mu = threading.Lock()
    ticket = {"i": 0}
    t0 = time.perf_counter() + 0.05  # let senders reach the gate

    def sender():
        nonlocal rejected, errors
        while True:
            with mu:
                i = ticket["i"]
                if i >= n_requests:
                    return
                ticket["i"] = i + 1
            scheduled = t0 + i * interval
            delay = scheduled - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                _post(host, "/index/i/query", PROBE_QUERY)
                lat = time.perf_counter() - scheduled
                with mu:
                    latencies.append(lat)
            except urllib.error.HTTPError as e:
                with mu:
                    if e.code == 429:
                        rejected += 1
                    else:
                        errors += 1
                e.read()
            except OSError:
                with mu:
                    errors += 1

    threads = [threading.Thread(target=sender)
               for _ in range(min(n_senders, n_requests))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    latencies.sort()
    return {
        "rate_rps": round(rate_rps, 1),
        "duration_s": duration_s,
        "offered": n_requests,
        "completed": len(latencies),
        "rejected": rejected,
        "errors": errors,
        "p50_ms": round(_percentile(latencies, 50) * 1e3, 3),
        "p99_ms": round(_percentile(latencies, 99) * 1e3, 3),
    }


def run() -> dict:
    concurrency = int(os.environ.get("PILOSA_LUL_CONCURRENCY", "4"))
    queue_depth = int(os.environ.get("PILOSA_LUL_QUEUE_DEPTH", "8"))
    phase_s = float(os.environ.get("PILOSA_LUL_PHASE_S", "3"))
    max_rps = float(os.environ.get("PILOSA_LUL_MAX_RPS", "250"))

    with tempfile.TemporaryDirectory() as tmp:
        server = _start_server(tmp, concurrency, queue_depth)
        try:
            capacity = _measure_capacity_rps(server.host)
            below_rate = min(max_rps, max(2.0, 0.4 * capacity))
            above_rate = min(max_rps, max(below_rate * 2, 3.0 * capacity))
            below = _run_phase(server.host, below_rate, phase_s)
            time.sleep(0.5)  # drain between phases
            above = _run_phase(server.host, above_rate, phase_s)
            admission = server.admission.snapshot()
        finally:
            server.close()

    out = {
        "written_by": "benchmarks/latency_under_load.py",
        "note": "Open-loop fixed-arrival-rate latency through the full"
                " HTTP + admission stack (sched subsystem). Latency is"
                " measured from the scheduled send time; 'rejected'"
                " counts 429 answers. Above the cap the server must"
                " reject, not queue unboundedly: p99 of ADMITTED"
                " requests stays bounded while 'rejected' absorbs the"
                " overload.",
        "config": {"concurrency": concurrency,
                   "queue_depth": queue_depth,
                   "probe": "Count(Union over 32 rows, 4 slices)",
                   "closed_loop_capacity_rps": round(capacity, 1)},
        "below_cap": below,
        "above_cap": above,
        "admission": admission,
    }
    path = os.path.join(_DIR, "LATENCY.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    _fold_into_manifest(out)
    return out


def _fold_into_manifest(result: dict) -> None:
    """Record the headline numbers in benchmarks/MANIFEST.json next to
    the roofline artifacts (LATENCY.json stays the canonical file)."""
    path = os.path.join(_DIR, "MANIFEST.json")
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        manifest = {"canonical_artifacts": {}, "metrics": {}}
    manifest.setdefault("canonical_artifacts", {})[
        "latency_under_load"] = "LATENCY.json"
    metrics = manifest.setdefault("metrics", {})
    for phase in ("below_cap", "above_cap"):
        r = result[phase]
        metrics[f"latency_{phase}_p50"] = {
            "value": r["p50_ms"], "unit": "ms",
            "rate_rps": r["rate_rps"]}
        metrics[f"latency_{phase}_p99"] = {
            "value": r["p99_ms"], "unit": "ms",
            "rate_rps": r["rate_rps"]}
        metrics[f"latency_{phase}_rejected"] = {
            "value": r["rejected"], "unit": "requests",
            "offered": r["offered"]}
    with open(path, "w") as f:
        json.dump(manifest, f, indent=1)


def main() -> None:
    out = run()
    print(json.dumps({
        "metric": "latency_under_load",
        "below_cap_p50_ms": out["below_cap"]["p50_ms"],
        "below_cap_p99_ms": out["below_cap"]["p99_ms"],
        "below_cap_rejected": out["below_cap"]["rejected"],
        "above_cap_p50_ms": out["above_cap"]["p50_ms"],
        "above_cap_p99_ms": out["above_cap"]["p99_ms"],
        "above_cap_rejected": out["above_cap"]["rejected"],
        "capacity_rps": out["config"]["closed_loop_capacity_rps"],
    }))


if __name__ == "__main__":
    main()
