"""Longevity soak: a 2-node gossip cluster under continuous mixed load.

Not a pytest (it runs for minutes by design) — a reproducible harness
whose results land in RESULTS.md. It exercises, at once, the surfaces
that only misbehave over time: WAL growth + snapshotting under a write
storm (MAX_OP_N forced low -> snapshot storms), anti-entropy sweeps
against live writes, gossip probes across BOTH a mid-soak clean restart
AND a mid-soak SIGKILL of node B (WAL replay + torn-tail recovery under
load), the batched write path (one writer issues 100-call pipelined
bodies), and the Python heap (sampled via /debug/pprof/heap). Per-op
write latencies are collected for p50/p99/p999; the verdict also fails
on RSS growth (leak detection over the run).

Usage: python benchmarks/soak.py [minutes]   (default 10)

Prints one JSON line per minute (ops so far, error count, RSS of each
server, traced heap) and a final PASS/FAIL verdict with the consistency
check: every sampled row's Bitmap must equal the model on BOTH nodes
after a final anti-entropy pass.
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))

from podenv import cpu_env, free_port, wait_up  # noqa: E402

SLICE_SPAN = 4 * (1 << 20)   # 4 slices of columns
ROWS = 64


def http(method, host, path, body=b"", timeout=60):
    req = urllib.request.Request(f"http://{host}{path}", data=body,
                                 method=method)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.read()


def query(host, pql, timeout=60):
    raw = http("POST", host, "/index/si/query", pql.encode(),
               timeout=timeout)
    return json.loads(raw)["results"]


class Node:
    def __init__(self, name, data_dir, port, internal_port, seed=""):
        self.name = name
        self.data_dir = data_dir
        self.port = port
        self.host = f"127.0.0.1:{port}"
        self.internal_port = internal_port
        self.seed = seed
        self.log = open(os.path.join(data_dir, "..", f"{name}.log"), "a")
        self.proc = None

    def start(self):
        env = cpu_env()
        env["PILOSA_TPU_MESH"] = "0"  # device-free children: a kill or
        # crash here must never touch the shared accelerator state
        env["PILOSA_TPU_MAX_OP_N"] = "200"  # snapshot storm cadence
        argv = [sys.executable, "-m", "pilosa_tpu.cli", "server",
                "-d", self.data_dir, "-b", self.host,
                "--cluster.type", "gossip",
                "--cluster.hosts", CLUSTER_HOSTS,
                "--cluster.replicas", "2",
                "--cluster.internal-port", str(self.internal_port),
                "--anti-entropy.interval", "45s",
                "--log-path", os.path.join(self.data_dir, "..",
                                           f"{self.name}-server.log")]
        if self.seed:
            argv += ["--cluster.gossip-seed", self.seed]
        self.proc = subprocess.Popen(argv, env=env, stdout=self.log,
                                     stderr=self.log, cwd=_REPO)
        wait_up(self.host)

    def stop(self, sig=signal.SIGINT, timeout=30):
        if self.proc is None:
            return
        self.proc.send_signal(sig)
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()
        self.proc = None

    def rss_mb(self):
        if self.proc is None:
            return 0.0
        try:
            with open(f"/proc/{self.proc.pid}/statm") as f:
                return int(f.read().split()[1]) * 4096 / (1 << 20)
        except OSError:
            return 0.0


def main():
    minutes = float(sys.argv[1]) if len(sys.argv) > 1 else 10.0
    base = f"/tmp/pilosa-soak-{os.getpid()}"
    os.makedirs(base, exist_ok=True)
    pa, pb = free_port(), free_port()
    ga, gb = free_port(), free_port()
    global CLUSTER_HOSTS
    CLUSTER_HOSTS = f"127.0.0.1:{pa},127.0.0.1:{pb}"

    for name, port in (("a", pa), ("b", pb)):
        os.makedirs(f"{base}/{name}", exist_ok=True)
    na = Node("a", f"{base}/a", pa, ga)
    nb = Node("b", f"{base}/b", pb, gb, seed=f"127.0.0.1:{ga}")
    na.start()
    nb.start()
    nodes = [na, nb]

    http("POST", na.host, "/index/si", b"{}")
    http("POST", na.host, "/index/si/frame/sf", b"{}")
    time.sleep(2)  # let the schema gossip

    model = {r: set() for r in range(ROWS)}
    # Every cell ever SET (never pruned): a final extra bit on a
    # set-then-cleared cell is an anti-entropy RESURRECTION — a clear
    # whose replica fan-out was mid-flight when the 45 s sweep read
    # its block gets undone by the 2-copy set-biased MergeBlock
    # majority ((2+1)//2 = 1; the reference has the same arithmetic).
    # Proven deterministically in tests/test_server.py::
    # test_anti_entropy_resurrects_clear_racing_the_sweep; observed
    # ~1-2 times per 60-min run. Tolerated up to a bound and REPORTED;
    # never-set extras and missing sets remain hard failures.
    set_ever = {r: set() for r in range(ROWS)}
    # Bits whose final state is unknowable: the write errored
    # client-side (restart window) but may have applied server-side —
    # at-least-once semantics, exactly like the reference's replicated
    # writes (no rollback of a partially-applied fan-out).
    uncertain = {r: set() for r in range(ROWS)}
    model_mu = threading.Lock()
    stop = threading.Event()
    stats = {"writes": 0, "reads": 0, "errors": 0, "restarts": 0}

    write_lat = []
    lat_mu = threading.Lock()

    # In-flight op registry per cell: (set_count, clear_count). A SET
    # overlapping an in-flight CLEAR on the same cell (or vice versa)
    # is order-ambiguous — the server linearizes by arrival, the model
    # by response order, and they can disagree. Any such overlap marks
    # the cell uncertain (monotone). A 60-min run once failed its
    # check by exactly ONE bit this way (~1-in-10^6 writes at this
    # cell-space, which is why shorter soaks never saw it); both nodes
    # agreed with each other, proving the storage converged and only
    # the harness model was ambiguous.
    inflight: dict = {}

    def _begin(r, c, is_set):
        with model_mu:
            s, cl = inflight.get((r, c), (0, 0))
            if (cl if is_set else s):
                uncertain[r].add(c)
            inflight[(r, c)] = (s + (1 if is_set else 0),
                                cl + (0 if is_set else 1))

    def _end(r, c, is_set, conflicted_ok):
        with model_mu:
            s, cl = inflight[(r, c)]
            if (cl if is_set else s):
                uncertain[r].add(c)
            s, cl = (s - 1, cl) if is_set else (s, cl - 1)
            if s or cl:
                inflight[(r, c)] = (s, cl)
            else:
                del inflight[(r, c)]
            if conflicted_ok:
                (model[r].add if is_set else model[r].discard)(c)
                if is_set:
                    set_ever[r].add(c)

    def writer(seed):
        rng = random.Random(seed)
        while not stop.is_set():
            r = rng.randrange(ROWS)
            c = rng.randrange(SLICE_SPAN)
            setbit = rng.random() < 0.9
            host = nodes[rng.randrange(2)].host
            verb = "SetBit" if setbit else "ClearBit"
            _begin(r, c, setbit)
            t0 = time.perf_counter()
            try:
                query(host, f'{verb}(frame="sf", rowID={r},'
                            f' columnID={c})', timeout=30)
            except Exception:
                stats["errors"] += 1  # restart window errors tolerated
                with model_mu:
                    uncertain[r].add(c)
                _end(r, c, setbit, conflicted_ok=False)
                time.sleep(0.5)
                continue
            el = time.perf_counter() - t0
            with lat_mu:
                write_lat.append(el)
                if len(write_lat) > 2_000_000:
                    del write_lat[:1_000_000]
            # NOTE: uncertain is MONOTONE — a cell touched by an
            # errored request stays unverifiable: the timed-out
            # request's bytes can still be sitting in a server
            # connection buffer and apply AFTER this success
            # (at-least-once, same as the reference's replicated
            # writes). Round-5's first 60-min run failed its
            # consistency check by exactly one such zombie bit.
            _end(r, c, setbit, conflicted_ok=True)
            stats["writes"] += 1

    def batch_writer(seed):
        """Round-5 batched write path: 100-call bodies through the
        executor mutate-batch run + the fragments' native batch
        engine."""
        rng = random.Random(seed)
        while not stop.is_set():
            r = rng.randrange(ROWS)
            cols = [rng.randrange(SLICE_SPAN) for _ in range(100)]
            host = nodes[rng.randrange(2)].host
            body = "\n".join(
                f'SetBit(frame="sf", rowID={r}, columnID={c})'
                for c in cols)
            for c in cols:
                _begin(r, c, True)
            try:
                query(host, body, timeout=60)
            except Exception:
                stats["errors"] += 1
                with model_mu:
                    uncertain[r].update(cols)
                for c in cols:
                    _end(r, c, True, conflicted_ok=False)
                time.sleep(0.5)
                continue
            for c in cols:
                _end(r, c, True, conflicted_ok=True)
            stats["writes"] += 100

    def reader(seed):
        rng = random.Random(seed)
        while not stop.is_set():
            host = nodes[rng.randrange(2)].host
            r = rng.randrange(ROWS)
            try:
                if rng.random() < 0.5:
                    query(host, f'Count(Bitmap(frame="sf", rowID={r}))',
                          timeout=30)
                else:
                    query(host, 'TopN(frame="sf", n=5)', timeout=30)
            except Exception:
                stats["errors"] += 1
                time.sleep(0.5)
                continue
            stats["reads"] += 1
            time.sleep(0.02)

    threads = [threading.Thread(target=writer, args=(i,), daemon=True)
               for i in range(2)]
    threads += [threading.Thread(target=batch_writer, args=(5,),
                                 daemon=True)]
    threads += [threading.Thread(target=reader, args=(10 + i,),
                                 daemon=True) for i in range(2)]
    for t in threads:
        t.start()

    t0 = time.monotonic()
    deadline = t0 + minutes * 60
    restarted = False
    killed = False
    minute = 0
    rss_curve = []
    http("GET", na.host, "/debug/pprof/heap")  # arm tracing on A
    while time.monotonic() < deadline:
        time.sleep(min(60, max(1, deadline - time.monotonic())))
        minute += 1
        heap = http("GET", na.host,
                    "/debug/pprof/heap?n=1").decode().splitlines()[0]
        rss_curve.append((round(na.rss_mb(), 1), round(nb.rss_mb(), 1)))
        print(json.dumps({
            "minute": minute, **stats,
            "rss_a_mb": rss_curve[-1][0],
            "rss_b_mb": rss_curve[-1][1],
            "heap_a": heap}), flush=True)
        if not restarted and time.monotonic() - t0 > minutes * 20:
            # Mid-soak (1/3): clean-restart node B under load.
            restarted = True
            stats["restarts"] += 1
            nb.stop()
            time.sleep(2)
            nb.start()
            print(json.dumps({"event": "restarted b"}), flush=True)
        elif killed is False and time.monotonic() - t0 > minutes * 40:
            # Mid-soak (2/3): SIGKILL node B — WAL replay + torn-tail
            # trim under load, the crash-durability path at soak scale.
            killed = True
            stats["restarts"] += 1
            nb.stop(sig=signal.SIGKILL, timeout=10)
            time.sleep(2)
            nb.start()
            print(json.dumps({"event": "sigkilled+revived b"}),
                  flush=True)

    stop.set()
    for t in threads:
        t.join(timeout=30)

    # Settle, then final consistency: both nodes answer the model for a
    # sample of rows (anti-entropy has had >1 sweep since the restart).
    time.sleep(3)
    rng = random.Random(0)
    failures = []
    resurrections = []
    for r in rng.sample(range(ROWS), 16):
        with model_mu:
            base = model[r] - uncertain[r]
            upper = model[r] | uncertain[r]
            ever = set_ever[r]
        for node in nodes:
            got = set(query(node.host,
                            f'Bitmap(frame="sf", rowID={r})')[0]["bits"])
            extra = got - upper
            rez = extra & ever       # set-then-cleared: resurrection
            hard_extra = extra - ever  # never set: invented bit
            if hard_extra or (base - got):
                failures.append((node.name, r, len(hard_extra),
                                 len(base - got),
                                 sorted(hard_extra)[:3],
                                 sorted(base - got)[:3]))
            for c in rez:
                resurrections.append((node.name, r, c))
    if len(resurrections) > 20:
        failures.append(("resurrection-storm", len(resurrections)))
    # Latency percentiles over the whole run (tail = snapshot storms,
    # restarts, anti-entropy interference).
    with lat_mu:
        lats = sorted(write_lat)
    pct = {}
    if lats:
        for name, q in (("p50", 0.5), ("p99", 0.99), ("p999", 0.999)):
            pct[name + "_ms"] = round(
                lats[min(len(lats) - 1, int(q * len(lats)))] * 1e3, 2)
    # RSS flatness: compare each node's median RSS over the first vs
    # last quarter of the run; a leak shows as unbounded growth.
    rss_verdict = "flat"
    if len(rss_curve) >= 8:
        qn = len(rss_curve) // 4
        for side, name in ((0, "a"), (1, "b")):
            first = sorted(c[side] for c in rss_curve[:qn])[qn // 2]
            last = sorted(c[side] for c in rss_curve[-qn:])[qn // 2]
            if last > 2.0 * first + 200:
                rss_verdict = f"LEAK:{name} {first}->{last}MB"
                failures.append(("rss", name, first, last))
    verdict = "PASS" if not failures else f"FAIL: {failures[:4]}"
    print(json.dumps({"verdict": verdict,
                      "resurrections": sorted(resurrections)[:8],
                      **stats, **pct,
                      "rss": rss_verdict,
                      "minutes": minutes}), flush=True)
    na.stop()
    nb.stop()
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
