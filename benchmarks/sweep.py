"""Extended differential + fuzz sweeps (one-off confidence runs).

Bigger and longer than the CI-sized versions in tests/: a differential
stream of mixed mutations and queries against a Python set model
through the full executor, and a bulk/batch/point mutation fuzz over
the roaring engine with exact value-set equality and serialized round
trips. Round 5 ran 10x1500 differential steps and 8x60 fuzz steps
(~370 K containers/bitmap) clean; rerun after storage or executor
changes.

Usage: python benchmarks/sweep.py [diff_seeds] [diff_steps] [fuzz_seeds]
"""

from __future__ import annotations

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from pilosa_tpu import SLICE_WIDTH  # noqa: E402
from pilosa_tpu.executor import Executor  # noqa: E402
from pilosa_tpu.models.holder import Holder  # noqa: E402
from pilosa_tpu.storage import roaring  # noqa: E402


def differential(seed: int, steps: int) -> None:
    rng = np.random.default_rng(seed)
    with tempfile.TemporaryDirectory() as d:
        holder = Holder(d)
        holder.open()
        try:
            holder.create_index("d").create_frame("f")
            ex = Executor(holder, host="local", use_mesh=False)
            frame = holder.frame("d", "f")
            bits: dict[int, set] = {}
            n_rows, n_cols = 60, 3 * SLICE_WIDTH
            for step in range(steps):
                kind = int(rng.integers(0, 9))
                if kind < 3:
                    r = int(rng.integers(0, n_rows))
                    c = int(rng.integers(0, n_cols))
                    got = ex.execute(
                        "d", f"SetBit(frame=f, rowID={r},"
                             f" columnID={c})")[0]
                    s = bits.setdefault(r, set())
                    assert got == (c not in s), (seed, step)
                    s.add(c)
                elif kind == 3:
                    r = int(rng.integers(0, n_rows))
                    c = int(rng.integers(0, n_cols))
                    got = ex.execute(
                        "d", f"ClearBit(frame=f, rowID={r},"
                             f" columnID={c})")[0]
                    s = bits.get(r, set())
                    assert got == (c in s), (seed, step)
                    s.discard(c)
                elif kind == 4:
                    k = int(rng.integers(1, 3000))
                    rows = rng.integers(0, n_rows, k).astype(np.uint64)
                    cols = rng.integers(0, n_cols, k).astype(np.uint64)
                    frame.import_bits(rows, cols)
                    for r, c in zip(rows.tolist(), cols.tolist()):
                        bits.setdefault(r, set()).add(c)
                elif kind == 5:
                    r = int(rng.integers(0, n_rows))
                    got = ex.execute(
                        "d", f"Count(Bitmap(frame=f, rowID={r}))")[0]
                    assert got == len(bits.get(r, set())), (seed, step)
                elif kind == 6:
                    ids = rng.integers(
                        0, n_rows, int(rng.integers(2, 20))).tolist()
                    q = "Count(Union(" + ", ".join(
                        f"Bitmap(frame=f, rowID={r})"
                        for r in ids) + "))"
                    want = len(set().union(
                        *(bits.get(r, set()) for r in ids)))
                    assert ex.execute("d", q)[0] == want, (seed, step)
                elif kind == 7:
                    a, b = rng.integers(0, n_rows, 2).tolist()
                    sa = bits.get(a, set())
                    sb = bits.get(b, set())
                    gi = ex.execute(
                        "d", f"Count(Intersect(Bitmap(frame=f,"
                             f" rowID={a}), Bitmap(frame=f,"
                             f" rowID={b})))")[0]
                    assert gi == len(sa & sb), (seed, step)
                    gd = ex.execute(
                        "d", f"Count(Difference(Bitmap(frame=f,"
                             f" rowID={a}), Bitmap(frame=f,"
                             f" rowID={b})))")[0]
                    assert gd == len(sa - sb), (seed, step)
                else:
                    src = int(rng.integers(0, n_rows))
                    got = ex.execute(
                        "d", f"TopN(Bitmap(frame=f, rowID={src}),"
                             f" frame=f, n=5)")[0]
                    ssrc = bits.get(src, set())
                    for p in got:
                        assert p.count == len(
                            bits.get(p.id, set()) & ssrc), (seed, step)
        finally:
            holder.close()


def fuzz(seed: int, steps: int = 60) -> tuple[int, int]:
    rng = np.random.default_rng(seed)
    bm = roaring.Bitmap()
    model: set = set()
    universes = [
        lambda n: rng.integers(0, 1 << 20, n),
        lambda n: rng.integers(0, 1 << 36, n),
        lambda n: (np.uint64(0xFFFFFFFFFFFF0000)
                   + rng.integers(0, 1 << 15, n).astype(np.uint64)),
        lambda n: rng.integers(0, 1 << 44, n),
    ]
    for step in range(steps):
        u = universes[int(rng.integers(0, 4))]
        kind = int(rng.integers(0, 5))
        n = int(rng.integers(1, 40000))
        vals = np.asarray(u(n), dtype=np.uint64)
        before = len(model)
        if kind <= 1:
            added = bm.add_many(vals)
            model.update(vals.tolist())
            assert added == len(model) - before, (seed, step)
        elif kind == 2:
            removed = bm.remove_many(vals)
            model.difference_update(vals.tolist())
            assert removed == before - len(model), (seed, step)
        elif kind == 3:
            do_set = bool(rng.integers(0, 2))
            ch = bm.apply_batch(vals, set=do_set, wal=False)
            if do_set:
                model.update(vals.tolist())
                assert len(ch) == len(model) - before, (seed, step)
            else:
                model.difference_update(vals.tolist())
                assert len(ch) == before - len(model), (seed, step)
        else:
            v = int(vals[0])
            assert bm._add(v) == (v not in model)
            model.add(v)
    want = (np.sort(np.fromiter(model, np.uint64, len(model)))
            if model else np.empty(0, np.uint64))
    assert np.array_equal(bm.values(), want), (seed, "value set")
    back = roaring.Bitmap.unmarshal(bm.marshal())
    assert np.array_equal(back.values(), want), (seed, "round trip")
    return len(model), len(bm.keys)


def main() -> None:
    diff_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    diff_steps = int(sys.argv[2]) if len(sys.argv) > 2 else 1500
    fuzz_seeds = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    for seed in range(20, 20 + diff_seeds):
        differential(seed, diff_steps)
        print(f"differential seed {seed}: {diff_steps} steps ok",
              flush=True)
    for seed in range(50, 50 + fuzz_seeds):
        nvals, nconts = fuzz(seed)
        print(f"fuzz seed {seed}: exact ({nvals} values,"
              f" {nconts} containers)", flush=True)
    print("SWEEP CLEAN")


if __name__ == "__main__":
    main()
