"""Packed dense bitmap layout — the device-side data representation.

The reference's compute walks compressed roaring containers with scalar/SIMD
loops (roaring.go:1192-1558 + assembly_amd64.s). TPUs want dense, regular,
vectorized data: here a fragment's rows live in HBM as a row-major
``uint32[n_rows, 32768]`` matrix — 2^20 columns / 32 bits per word — and all
set algebra is elementwise ops over whole rows (pilosa_tpu.ops.kernels).

u32 is the natural TPU word (native lane type; XLA has no u64 popcount
advantage), and the layout lines up with the storage format for free: a
roaring bitmap container is 1024 little-endian u64 words covering a 2^16
position range, which reinterpret as exactly the 2048 little-endian u32
device words of that range — so packing a dense container is a memcpy, no
bit manipulation.

Column ids are u64 host-side (positions up to 2^64); the device only ever
sees word indices within a slice, which fit comfortably in i32.
"""

from __future__ import annotations

import numpy as np

from .. import SLICE_WIDTH
from ..storage.roaring import Bitmap

WORD_BITS = 32
# u32 words per slice row: 2^20 / 32 = 32768 (a multiple of the 128-lane
# TPU tile, so rows map onto the VPU with no padding).
WORDS_PER_SLICE = SLICE_WIDTH // WORD_BITS
# u32 words per roaring container range (2^16 positions / 32).
_WORDS_PER_CONTAINER = (1 << 16) // WORD_BITS


def pack_bitmap(b: Bitmap, n_words: int, out: np.ndarray | None = None,
                base_word: int = 0) -> np.ndarray:
    """Pack a roaring bitmap into a dense u32 word vector.

    ``b``'s positions are interpreted relative to ``base_word * 32``; words
    outside [0, n_words) are ignored. Dense containers blit via u64→u32
    reinterpretation; array containers scatter.
    """
    if out is None:
        out = np.zeros(n_words, dtype=np.uint32)
    for key, c in zip(b.keys, b.containers):
        if c.n == 0:
            continue
        word0 = key * _WORDS_PER_CONTAINER - base_word
        if word0 >= n_words or word0 + _WORDS_PER_CONTAINER <= 0:
            continue
        if not c.is_array():
            dst0, dst1 = max(word0, 0), min(word0 + _WORDS_PER_CONTAINER,
                                            n_words)
            src = c.bitmap.view("<u4")[dst0 - word0:dst1 - word0]
            out[dst0:dst1] |= src
        else:
            a = c.array
            widx = word0 + (a >> np.uint32(5)).astype(np.int64)
            keep = (widx >= 0) & (widx < n_words)
            np.bitwise_or.at(out, widx[keep],
                             np.uint32(1) << (a[keep] & np.uint32(31)))
    return out


def pack_storage_row(storage: Bitmap, row_id: int,
                     out: np.ndarray) -> np.ndarray:
    """Pack one row of a fragment-local storage bitmap into dense words.

    ``storage`` holds positions ``pos = row * SLICE_WIDTH + col`` (the
    fragment bit layout, reference fragment.go:1511-1514); the result is
    the dense words of columns [0, 2^20) of that row.
    """
    row_bm = storage.offset_range(0, row_id * SLICE_WIDTH,
                                  (row_id + 1) * SLICE_WIDTH)
    return pack_bitmap(row_bm, out.shape[-1], out=out)


def pack_rows(storage: Bitmap, row_ids) -> np.ndarray:
    """Pack rows of a fragment-local storage bitmap into u32[n, 32768]."""
    row_ids = list(row_ids)
    out = np.zeros((len(row_ids), WORDS_PER_SLICE), dtype=np.uint32)
    for i, row in enumerate(row_ids):
        pack_storage_row(storage, row, out[i])
    return out


def unpack_words(words: np.ndarray) -> np.ndarray:
    """Dense u32 word vector → sorted u64 bit positions (host)."""
    from ..storage import native
    return native.unpack_words(np.ascontiguousarray(words))


def unpack_to_bitmap(words: np.ndarray, base_word: int = 0) -> Bitmap:
    """Dense u32 word vector → roaring bitmap with positions offset by
    ``base_word * 32``."""
    pos = unpack_words(words)
    if base_word:
        pos = pos + np.uint64(base_word * WORD_BITS)
    return Bitmap.from_sorted(pos)


def sparse_words(b: Bitmap, n_words: int, base_word: int = 0
                 ) -> tuple[np.ndarray, np.ndarray]:
    """Sparse word form of a roaring bitmap: (sorted unique i32 word
    indices, u32 word values) — the upload payload of the device
    densify kernel (ops.pallas_kernels.densify_pallas). Bounded by SET
    words (= on-disk density), not row width: bitmap containers list
    their nonzero u32 words directly, array containers group positions
    by word with one reduceat. Positions relative to ``base_word*32``;
    words outside [0, n_words) are dropped."""
    idx_parts: list[np.ndarray] = []
    val_parts: list[np.ndarray] = []
    for key, c in zip(b.keys, b.containers):
        if c.n == 0:
            continue
        word0 = key * _WORDS_PER_CONTAINER - base_word
        if word0 >= n_words or word0 + _WORDS_PER_CONTAINER <= 0:
            continue
        if not c.is_array():
            view = c.bitmap.view("<u4")
            nz = np.flatnonzero(view)
            widx = word0 + nz.astype(np.int64)
            keep = (widx >= 0) & (widx < n_words)
            idx_parts.append(widx[keep].astype(np.int32))
            val_parts.append(view[nz[keep]])
        else:
            a = c.array
            widx = word0 + (a >> np.uint32(5)).astype(np.int64)
            keep = (widx >= 0) & (widx < n_words)
            widx, a = widx[keep], a[keep]
            if not len(widx):
                continue
            bits = np.uint32(1) << (a & np.uint32(31))
            # positions are sorted, so equal word indices are adjacent:
            # one reduceat ORs each word's bits together.
            starts = np.concatenate(
                ([0], np.flatnonzero(np.diff(widx)) + 1))
            idx_parts.append(widx[starts].astype(np.int32))
            val_parts.append(np.bitwise_or.reduceat(bits, starts))
    if not idx_parts:
        return (np.empty(0, np.int32), np.empty(0, np.uint32))
    return np.concatenate(idx_parts), np.concatenate(val_parts)


def sparse_row_words(storage: Bitmap, row_id: int
                     ) -> tuple[np.ndarray, np.ndarray]:
    """sparse_words for one fragment row (pos = row*SLICE_WIDTH + col)."""
    row_bm = storage.offset_range(0, row_id * SLICE_WIDTH,
                                  (row_id + 1) * SLICE_WIDTH)
    return sparse_words(row_bm, WORDS_PER_SLICE)


def sparse_rows(storage: Bitmap, row_ids, pad_to: int | None = None
                ) -> tuple[np.ndarray, np.ndarray]:
    """Padded sparse form of a row block: ``([n, P] i32 idx, [n, P] u32
    val)`` with ``val == 0`` padding (a densify no-op). ``P`` is the max
    set-word count over the rows, rounded up to ``pad_to`` granularity
    (shape-bucketing keeps the device kernel's compile cache small)."""
    rows = [sparse_row_words(storage, r) for r in row_ids]
    p = max((len(i) for i, _ in rows), default=0)
    if pad_to:
        p = max(pad_to, -(-p // pad_to) * pad_to)
    p = max(p, 1)
    idx = np.zeros((len(rows), p), dtype=np.int32)
    val = np.zeros((len(rows), p), dtype=np.uint32)
    for n, (i, v) in enumerate(rows):
        idx[n, :len(i)] = i
        val[n, :len(v)] = v
    return idx, val
