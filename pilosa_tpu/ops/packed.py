"""Packed dense bitmap layout — the device-side data representation.

The reference's compute walks compressed roaring containers with scalar/SIMD
loops (roaring.go:1192-1558 + assembly_amd64.s). TPUs want dense, regular,
vectorized data: here a fragment's rows live in HBM as a row-major
``uint32[n_rows, 32768]`` matrix — 2^20 columns / 32 bits per word — and all
set algebra is elementwise ops over whole rows (pilosa_tpu.ops.kernels).

u32 is the natural TPU word (native lane type; XLA has no u64 popcount
advantage), and the layout lines up with the storage format for free: a
roaring bitmap container is 1024 little-endian u64 words covering a 2^16
position range, which reinterpret as exactly the 2048 little-endian u32
device words of that range — so packing a dense container is a memcpy, no
bit manipulation.

Column ids are u64 host-side (positions up to 2^64); the device only ever
sees word indices within a slice, which fit comfortably in i32.
"""

from __future__ import annotations

import numpy as np

from .. import SLICE_WIDTH
from ..storage.roaring import Bitmap, runs_to_words

WORD_BITS = 32
# u32 words per slice row: 2^20 / 32 = 32768 (a multiple of the 128-lane
# TPU tile, so rows map onto the VPU with no padding).
WORDS_PER_SLICE = SLICE_WIDTH // WORD_BITS
# u32 words per roaring container range (2^16 positions / 32).
_WORDS_PER_CONTAINER = (1 << 16) // WORD_BITS


def pack_bitmap(b: Bitmap, n_words: int, out: np.ndarray | None = None,
                base_word: int = 0) -> np.ndarray:
    """Pack a roaring bitmap into a dense u32 word vector.

    ``b``'s positions are interpreted relative to ``base_word * 32``; words
    outside [0, n_words) are ignored. Dense containers blit via u64→u32
    reinterpretation; array containers scatter.
    """
    if out is None:
        out = np.zeros(n_words, dtype=np.uint32)
    for key, c in zip(b.keys, b.containers):
        if c.n == 0:
            continue
        word0 = key * _WORDS_PER_CONTAINER - base_word
        if word0 >= n_words or word0 + _WORDS_PER_CONTAINER <= 0:
            continue
        if not c.is_array():
            dst0, dst1 = max(word0, 0), min(word0 + _WORDS_PER_CONTAINER,
                                            n_words)
            # Run containers decode to dense words here — the device
            # residency upload path (parallel.residency leaf_slab /
            # candidate_block) sees bit-plane slabs regardless of the
            # host storage kind.
            words64 = (c.bitmap if c.bitmap is not None
                       else runs_to_words(c.runs))
            src = words64.view("<u4")[dst0 - word0:dst1 - word0]
            out[dst0:dst1] |= src
        else:
            a = c.array
            widx = word0 + (a >> np.uint32(5)).astype(np.int64)
            keep = (widx >= 0) & (widx < n_words)
            np.bitwise_or.at(out, widx[keep],
                             np.uint32(1) << (a[keep] & np.uint32(31)))
    return out


def pack_storage_row(storage: Bitmap, row_id: int,
                     out: np.ndarray) -> np.ndarray:
    """Pack one row of a fragment-local storage bitmap into dense words.

    ``storage`` holds positions ``pos = row * SLICE_WIDTH + col`` (the
    fragment bit layout, reference fragment.go:1511-1514); the result is
    the dense words of columns [0, 2^20) of that row.
    """
    row_bm = storage.offset_range(0, row_id * SLICE_WIDTH,
                                  (row_id + 1) * SLICE_WIDTH)
    return pack_bitmap(row_bm, out.shape[-1], out=out)


def pack_rows(storage: Bitmap, row_ids) -> np.ndarray:
    """Pack rows of a fragment-local storage bitmap into u32[n, 32768]."""
    row_ids = list(row_ids)
    out = np.zeros((len(row_ids), WORDS_PER_SLICE), dtype=np.uint32)
    for i, row in enumerate(row_ids):
        pack_storage_row(storage, row, out[i])
    return out


def unpack_words(words: np.ndarray) -> np.ndarray:
    """Dense u32 word vector → sorted u64 bit positions (host)."""
    from ..storage import native
    return native.unpack_words(np.ascontiguousarray(words))


def unpack_to_bitmap(words: np.ndarray, base_word: int = 0) -> Bitmap:
    """Dense u32 word vector → roaring bitmap with positions offset by
    ``base_word * 32``.

    Container-direct build: the dense vector IS the container layout
    (2048 u32 words per 2^16-value container), so dense containers
    become zero-copy u64 views of the fetched array and only sparse
    ones expand to value arrays — the expand-every-position
    ``from_sorted`` path cost ~8 B/bit plus a full re-merge, which was
    most of the device materialize leg's repack time (VERDICT r4 item
    5). Requires container alignment (base_word and len multiples of
    2048), which every device block satisfies; anything else falls
    back to the general path."""
    from ..storage.roaring import (ARRAY_MAX_SIZE, Container,
                                   bitmap_words_to_values)
    per_container = _WORDS_PER_CONTAINER  # 2048 u32 words
    if (base_word % per_container or len(words) % per_container
            or words.dtype != np.uint32 or not words.flags.c_contiguous):
        pos = unpack_words(words)
        if base_word:
            pos = pos + np.uint64(base_word * WORD_BITS)
        return Bitmap.from_sorted(pos)
    counts = np.bitwise_count(words).astype(np.int64) \
        .reshape(-1, per_container).sum(axis=1)
    b = Bitmap()
    base_key = base_word // per_container
    w64 = words.view("<u8").reshape(-1, _WORDS_PER_CONTAINER // 2)
    for ci in np.flatnonzero(counts).tolist():
        n = int(counts[ci])
        span64 = w64[ci]
        if n > ARRAY_MAX_SIZE:
            # Zero-copy view into the fetched block, COW-marked: the
            # block outlives the bitmap via the view references.
            c = Container.from_bitmap(span64, n=n, mapped=True)
        else:
            c = Container.from_array(bitmap_words_to_values(span64))
        b.keys.append(base_key + ci)
        b.containers.append(c)
    return b


def sparse_words(b: Bitmap, n_words: int, base_word: int = 0
                 ) -> tuple[np.ndarray, np.ndarray]:
    """Sparse word form of a roaring bitmap: (sorted unique i32 word
    indices, u32 word values) — the upload payload of the device
    densify kernel (ops.pallas_kernels.densify_pallas). Bounded by SET
    words (= on-disk density), not row width: bitmap containers list
    their nonzero u32 words directly, array containers group positions
    by word with one reduceat. Positions relative to ``base_word*32``;
    words outside [0, n_words) are dropped."""
    idx_parts: list[np.ndarray] = []
    val_parts: list[np.ndarray] = []
    for key, c in zip(b.keys, b.containers):
        if c.n == 0:
            continue
        word0 = key * _WORDS_PER_CONTAINER - base_word
        if word0 >= n_words or word0 + _WORDS_PER_CONTAINER <= 0:
            continue
        if not c.is_array():
            view = (c.bitmap if c.bitmap is not None
                    else runs_to_words(c.runs)).view("<u4")
            nz = np.flatnonzero(view)
            widx = word0 + nz.astype(np.int64)
            keep = (widx >= 0) & (widx < n_words)
            idx_parts.append(widx[keep].astype(np.int32))
            val_parts.append(view[nz[keep]])
        else:
            a = c.array
            widx = word0 + (a >> np.uint32(5)).astype(np.int64)
            keep = (widx >= 0) & (widx < n_words)
            widx, a = widx[keep], a[keep]
            if not len(widx):
                continue
            bits = np.uint32(1) << (a & np.uint32(31))
            # positions are sorted, so equal word indices are adjacent:
            # one reduceat ORs each word's bits together.
            starts = np.concatenate(
                ([0], np.flatnonzero(np.diff(widx)) + 1))
            idx_parts.append(widx[starts].astype(np.int32))
            val_parts.append(np.bitwise_or.reduceat(bits, starts))
    if not idx_parts:
        return (np.empty(0, np.int32), np.empty(0, np.uint32))
    return np.concatenate(idx_parts), np.concatenate(val_parts)


def sparse_row_words(storage: Bitmap, row_id: int
                     ) -> tuple[np.ndarray, np.ndarray]:
    """sparse_words for one fragment row (pos = row*SLICE_WIDTH + col)."""
    row_bm = storage.offset_range(0, row_id * SLICE_WIDTH,
                                  (row_id + 1) * SLICE_WIDTH)
    return sparse_words(row_bm, WORDS_PER_SLICE)


# 128 words per bucket group - must match pallas_kernels._DENSIFY_LANES.
_DENSIFY_LANES = 128


def bucket_rows(storage: Bitmap, row_ids,
                n_words: int = WORDS_PER_SLICE
                ) -> tuple[np.ndarray, np.ndarray]:
    """Bucketed sparse form of a row block for the device densify
    kernel (ops.pallas_kernels.densify_pallas): ``([T, n_words/128, G]
    u32 lanes, same-shape u32 values)``, where slot g of 128-word group
    s of row t is one set word (its lane 0-127 and value); ``val == 0``
    slots are padding. G is the max set-word count in any row's group,
    rounded up to a power of two (shape-bucketing keeps the kernel's
    compile cache small). Transfer size is ``T * n_words/16 * G`` bytes
    vs ``4 * T * n_words`` dense — the win whenever G stays small,
    which is exactly the sparse/clustered case the cost model routes
    here."""
    subs = n_words // _DENSIFY_LANES
    rows = [sparse_row_words(storage, r) for r in row_ids]
    return bucket_prepared(rows, subs)


def _bucket_plan(rows: list, subs: int) -> tuple[int, list]:
    """One bincount pass over pre-extracted pairs: (g_pad, metas) —
    shared by sparse_gate (the decision) and bucket_prepared (the
    fill), so the cold path pays the grouping exactly once."""
    g_max = 1
    metas = []
    for pair in rows:
        if pair is None or not len(pair[0]):
            metas.append(None)
            continue
        idx, val = pair
        groups = (idx >> 7).astype(np.int64)
        counts = np.bincount(groups, minlength=subs)
        g_max = max(g_max, int(counts.max()))
        starts = np.zeros(subs + 1, dtype=np.int64)
        np.cumsum(counts, out=starts[1:])
        rank = np.arange(len(idx), dtype=np.int64) - starts[groups]
        metas.append((groups, rank, idx, val))
    return 1 << (g_max - 1).bit_length(), metas


def bucket_prepared(rows: list, subs: int, plan=None
                    ) -> tuple[np.ndarray, np.ndarray]:
    """bucket_rows over pre-extracted ``(idx, val)`` pairs (None for
    absent rows) — the shared form for multi-fragment blocks, where
    extraction happens once and feeds either the sparse upload or the
    host dense scatter (ops.packed.densify_host). ``plan`` is the
    (g_pad, metas) a prior sparse_gate computed."""
    g_pad, metas = plan if plan is not None else _bucket_plan(rows, subs)
    lanes = np.zeros((len(rows), subs, g_pad), dtype=np.uint32)
    vals = np.zeros((len(rows), subs, g_pad), dtype=np.uint32)
    for t, meta in enumerate(metas):
        if meta is None:
            continue
        groups, rank, idx, val = meta
        lanes[t, groups, rank] = (idx & 127).astype(np.uint32)
        vals[t, groups, rank] = val
    return lanes, vals


def densify_host(rows: list, n_words: int) -> np.ndarray:
    """Pre-extracted ``(idx, val)`` pairs → dense ``[T, n_words]`` u32
    host-side (the dense-upload leg when the sparse gate says no —
    reuses the extraction instead of re-walking containers)."""
    out = np.zeros((len(rows), n_words), dtype=np.uint32)
    for t, pair in enumerate(rows):
        if pair is None or not len(pair[0]):
            continue
        out[t, pair[0]] = pair[1]
    return out


def sparse_gate(rows: list, n_words: int,
                margin: float = 2.0) -> tuple[bool, tuple]:
    """Should a block of pre-extracted rows ship sparse? Returns
    (use_sparse, plan) — pass ``plan`` to bucket_prepared to reuse the
    grouping pass. Sparse pays when the bucketed payload —
    ``T * n_words/16 * G`` bytes — is under ``dense/margin`` and G is
    within the kernel's VMEM envelope; the measured crossover
    (benchmarks/DENSIFY.json) shows 3-6x wins at G<=16 and a 0.5x LOSS
    at G=128, so the gate is deliberately conservative."""
    subs = n_words // _DENSIFY_LANES
    plan = _bucket_plan(rows, subs)
    g_pad = plan[0]
    sparse_bytes = len(rows) * subs * g_pad * 8
    dense_bytes = len(rows) * n_words * 4
    return (g_pad <= 32
            and sparse_bytes * margin <= dense_bytes), plan
