"""Fused Pallas TPU kernels for the count hot path.

XLA already fuses ``popcount(a & b)`` with its row reduction; the Pallas
variant exists to (a) control tiling explicitly for the long-row case (a 1 B
column row is 32 M words — 128 MB — streamed HBM→VMEM in double-buffered
tiles), and (b) guarantee a single pass with no intermediate even across
fusion-boundary surprises. On non-TPU backends everything falls back to the
XLA kernels (pilosa_tpu.ops.kernels), which are the semantics reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .kernels import _BITWISE

# Row/word tile sizes. 8×4096 u32 ×2 operands = 256 KB VMEM per step —
# small enough to double-buffer, wide enough to stream HBM at full rate.
_TILE_R = 8
_TILE_W = 4096
_LANES = 128


def should_use_pallas(a: jax.Array) -> bool:
    try:
        platform = a.devices().pop().platform if hasattr(a, "devices") \
            else jax.default_backend()
    except Exception:
        platform = jax.default_backend()
    return platform == "tpu"


def _count_kernel(op_name, a_ref, b_ref, out_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    words = _BITWISE[op_name](a_ref[:], b_ref[:])
    pc = jax.lax.population_count(words).astype(jnp.int32)
    tr, tw = pc.shape
    out_ref[:] += pc.reshape(tr, tw // _LANES, _LANES).sum(axis=1)


@functools.partial(jax.jit, static_argnums=(0, 3))
def _op_count_padded(op: str, a: jax.Array, b: jax.Array,
                     interpret: bool = False) -> jax.Array:
    rows, words = a.shape
    grid = (rows // _TILE_R, words // _TILE_W)
    partials = pl.pallas_call(
        functools.partial(_count_kernel, op),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TILE_R, _TILE_W), lambda i, j: (i, j)),
            pl.BlockSpec((_TILE_R, _TILE_W), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((_TILE_R, _LANES), lambda i, j: (i, 0)),
        interpret=interpret,
    )(a, b)
    return jnp.sum(partials, axis=-1)


def op_count_rows_pallas(op: str, a: jax.Array, b: jax.Array,
                         interpret: bool = False) -> jax.Array:
    """Fused ``sum(popcount(a ⊕ b), axis=-1)`` as one Pallas kernel.

    Accepts ``[n_words]`` or ``[n_rows, n_words]``; pads to tile multiples
    (zero words contribute zero to every count, so padding is free).
    """
    squeeze = a.ndim == 1
    if squeeze:
        a, b = a[None, :], b[None, :]
    if a.shape[0] == 1 and a.shape[1] % (_TILE_R * _LANES) == 0:
        # A single long row would be padded to _TILE_R rows (8× wasted
        # reads). Counts are position-invariant, so fold it into a row
        # block and sum the per-row partials.
        w = a.shape[1]
        folded = op_count_rows_pallas(
            op, a.reshape(_TILE_R, w // _TILE_R),
            b.reshape(_TILE_R, w // _TILE_R), interpret)
        total = jnp.sum(folded)
        return total if squeeze else total[None]
    rows, words = a.shape
    pr = (-rows) % _TILE_R
    pw = (-words) % _TILE_W
    if pr or pw:
        a = jnp.pad(a, ((0, pr), (0, pw)))
        b = jnp.pad(b, ((0, pr), (0, pw)))
    out = _op_count_padded(op, a, b, interpret)
    out = out[:rows]
    return out[0] if squeeze else out
