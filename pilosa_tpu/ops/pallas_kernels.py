"""Fused Pallas TPU kernels for the count hot path.

XLA already fuses ``popcount(a & b)`` with its row reduction; the Pallas
variant exists to (a) control tiling explicitly for the long-row case (a 1 B
column row is 32 M words — 128 MB — streamed HBM→VMEM in double-buffered
tiles), and (b) guarantee a single pass with no intermediate even across
fusion-boundary surprises. On non-TPU backends everything falls back to the
XLA kernels (pilosa_tpu.ops.kernels), which are the semantics reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .kernels import _BITWISE

# Row/word tile sizes. 8×4096 u32 ×2 operands = 256 KB VMEM per step —
# small enough to double-buffer, wide enough to stream HBM at full rate.
_TILE_R = 8
_TILE_W = 4096
_LANES = 128


def platform_of(a: jax.Array) -> str:
    """Platform of the array's device (default backend for tracers and
    abstract values) — the input to pallas_mode."""
    try:
        return a.devices().pop().platform if hasattr(a, "devices") \
            else jax.default_backend()
    except Exception:  # noqa: BLE001 - tracer/abstract values
        return jax.default_backend()


def pallas_mode(platform: str) -> str | None:
    """How the serving path should run these Pallas kernels on
    ``platform``.

    DEFAULT IS XLA (returns None): the round-4 kernel-level A/B at the
    literal BASELINE shapes (benchmarks/PALLAS_AB.json) measured XLA
    fusion equal-or-faster on 5 of 6 serving shapes — 1.23x at the
    1 B-bit metric-of-record shape, 3.7x on a single long row, ~1.5x on
    TopN candidate blocks; the single Pallas "win" was 0.96x (noise).
    These kernels remain available as an explicit experiment
    (PILOSA_TPU_PALLAS=1|force → compiled on TPU) and as a correctness
    harness (=interpret, used by CPU tests), matching the reference's
    rule of dispatching to its asm path only when CPUID proves it pays
    (roaring/assembly_asm.go:15,40-80). The sparse-upload densify
    kernel (densify_pallas) is NOT gated here — scatter is XLA's known
    TPU weak spot, so the sparse-upload path selects it independently
    (see parallel.residency's sparse block builds).
    """
    import os
    v = os.environ.get("PILOSA_TPU_PALLAS", "xla")
    if v in ("1", "force", "auto"):
        # "auto" kept for round-3 compatibility: it now means "let the
        # recorded A/B decide", and the A/B said XLA — but an explicit
        # opt-in should still get the Pallas path on real TPU.
        return "compiled" if platform == "tpu" and v != "auto" else None
    if v == "interpret":
        return "interpret"
    return None


def _count_kernel(op_name, a_ref, b_ref, out_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    words = _BITWISE[op_name](a_ref[:], b_ref[:])
    pc = jax.lax.population_count(words).astype(jnp.int32)
    tr, tw = pc.shape
    out_ref[:] += pc.reshape(tr, tw // _LANES, _LANES).sum(axis=1)


@functools.partial(jax.jit, static_argnums=(0, 3))
def _op_count_padded(op: str, a: jax.Array, b: jax.Array,
                     interpret: bool = False) -> jax.Array:
    rows, words = a.shape
    grid = (rows // _TILE_R, words // _TILE_W)
    partials = pl.pallas_call(
        functools.partial(_count_kernel, op),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TILE_R, _TILE_W), lambda i, j: (i, j)),
            pl.BlockSpec((_TILE_R, _TILE_W), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((_TILE_R, _LANES), lambda i, j: (i, 0)),
        interpret=interpret,
    )(a, b)
    return jnp.sum(partials, axis=-1)


def _eval_expr_ref(expr, leaves_ref):
    """Evaluate a hashable expr tree over a Pallas leaves ref: ``("leaf",
    i)`` loads leaf block i, ``(op, a, b)`` combines in VMEM — the whole
    PQL bitmap expression runs per tile with no HBM intermediates."""
    if expr[0] == "leaf":
        return leaves_ref[expr[1]]
    return _BITWISE[expr[0]](_eval_expr_ref(expr[1], leaves_ref),
                             _eval_expr_ref(expr[2], leaves_ref))


def _expr_count_kernel(expr, leaves_ref, out_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    words = _eval_expr_ref(expr, leaves_ref)
    pc = jax.lax.population_count(words).astype(jnp.int32)
    tr, tw = pc.shape
    out_ref[:] += pc.reshape(tr, tw // _LANES, _LANES).sum(axis=1)


@functools.partial(jax.jit, static_argnums=(0, 2))
def expr_count_rows_pallas(expr, leaves: jax.Array,
                           interpret: bool = False) -> jax.Array:
    """Per-slice-row counts of a bitmap expression, one fused kernel.

    ``leaves`` is ``[n_leaves, S, W]`` u32; returns ``[S]`` int32 of
    ``sum(popcount(expr(leaves[:, s])))``. The expression tree, the
    popcount, and the word reduction all run tile-resident in VMEM —
    the serving-path generalization of the 2-operand count kernel
    (replacing roaring.go:1192-1268's per-container-pair loops for an
    arbitrary expression). Pads rows/words to tile multiples (zero
    words count zero).
    """
    n_leaves, rows, words = leaves.shape
    tile_w = min(_TILE_W, -(-words // _LANES) * _LANES)
    pr = (-rows) % _TILE_R
    pw = (-words) % tile_w
    if pr or pw:
        leaves = jnp.pad(leaves, ((0, 0), (0, pr), (0, pw)))
    grid = (leaves.shape[1] // _TILE_R, leaves.shape[2] // tile_w)
    partials = pl.pallas_call(
        functools.partial(_expr_count_kernel, expr),
        out_shape=jax.ShapeDtypeStruct((leaves.shape[1], _LANES),
                                       jnp.int32),
        grid=grid,
        in_specs=[pl.BlockSpec((n_leaves, _TILE_R, tile_w),
                               lambda i, j: (0, i, j))],
        out_specs=pl.BlockSpec((_TILE_R, _LANES), lambda i, j: (i, 0)),
        interpret=interpret,
    )(leaves)
    return jnp.sum(partials, axis=-1)[:rows]


def _eval_expr_ref_t(expr, leaves_ref):
    """_eval_expr_ref for the slice-major leaves layout of the TopN
    kernel: the block is ``[1, n_leaves, tile_w]``, so leaf i loads as
    ``leaves_ref[:, i, :]`` → ``[1, tile_w]``."""
    if expr[0] == "leaf":
        return leaves_ref[:, expr[1], :]
    return _BITWISE[expr[0]](_eval_expr_ref_t(expr[1], leaves_ref),
                             _eval_expr_ref_t(expr[2], leaves_ref))


def _topn_block_kernel(expr, rows_ref, leaves_ref, out_ref):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    words = rows_ref[0]                      # [TILE_R, tile_w]
    if expr is not None:
        src = _eval_expr_ref_t(expr, leaves_ref)  # [1, tile_w]
        words = jnp.bitwise_and(words, src)       # broadcast over rows
    pc = jax.lax.population_count(words).astype(jnp.int32)
    tr, tw = pc.shape
    out_ref[0] += pc.reshape(tr, tw // _LANES, _LANES).sum(axis=1)


@functools.partial(jax.jit, static_argnums=(0, 3))
def topn_block_count_pallas(expr, rows: jax.Array, leaves: jax.Array,
                            interpret: bool = False) -> jax.Array:
    """Per-(slice, candidate) counts of ``popcount(row ∩ expr)``.

    ``rows`` is ``[S, R, W]``, ``leaves`` ``[n_leaves, S, W]`` (ignored
    when ``expr`` is None → plain row popcounts). Returns ``[S, R]``
    int32. The TopN exact-count hot loop as one fused kernel: candidate
    tile, source-expression tile, AND, popcount, and reduction all stay
    in VMEM (the vectorized device replacement for the reference's
    sequential per-row IntersectionCount, fragment.go:560-614).
    """
    n_slices, rows_n, words = rows.shape
    tile_w = min(_TILE_W, -(-words // _LANES) * _LANES)
    pr = (-rows_n) % _TILE_R
    pw = (-words) % tile_w
    if pr or pw:
        rows = jnp.pad(rows, ((0, 0), (0, pr), (0, pw)))
        leaves = jnp.pad(leaves, ((0, 0), (0, 0), (0, pw)))
    grid = (n_slices, rows.shape[1] // _TILE_R, rows.shape[2] // tile_w)
    n_leaves = max(leaves.shape[0], 1)
    if leaves.shape[0] == 0:  # expr None: feed a 1-leaf dummy block
        leaves = jnp.zeros((1, n_slices, rows.shape[2]), jnp.uint32)
    # Slice-major leaves layout: the per-slice leaf block's trailing two
    # dims become (n_leaves, tile_w), satisfying the TPU tiling rule
    # (second-to-last must divide 8 OR equal the array dim — a size-1
    # slice block over [L, S, W] does neither when S isn't tiny).
    leaves_t = jnp.transpose(leaves, (1, 0, 2))  # [S, L, W]
    partials = pl.pallas_call(
        functools.partial(_topn_block_kernel, expr),
        out_shape=jax.ShapeDtypeStruct(
            (n_slices, rows.shape[1], _LANES), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, _TILE_R, tile_w), lambda s, i, j: (s, i, j)),
            pl.BlockSpec((1, n_leaves, tile_w), lambda s, i, j: (s, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, _TILE_R, _LANES),
                               lambda s, i, j: (s, i, 0)),
        interpret=interpret,
    )(rows, leaves_t)
    return jnp.sum(partials, axis=-1)[:, :rows_n]


def op_count_rows_pallas(op: str, a: jax.Array, b: jax.Array,
                         interpret: bool = False) -> jax.Array:
    """Fused ``sum(popcount(a ⊕ b), axis=-1)`` as one Pallas kernel.

    Accepts ``[n_words]`` or ``[n_rows, n_words]``; pads to tile multiples
    (zero words contribute zero to every count, so padding is free).
    """
    squeeze = a.ndim == 1
    if squeeze:
        a, b = a[None, :], b[None, :]
    if a.shape[0] == 1 and a.shape[1] % (_TILE_R * _LANES) == 0:
        # A single long row would be padded to _TILE_R rows (8× wasted
        # reads). Counts are position-invariant, so fold it into a row
        # block and sum the per-row partials.
        w = a.shape[1]
        folded = op_count_rows_pallas(
            op, a.reshape(_TILE_R, w // _TILE_R),
            b.reshape(_TILE_R, w // _TILE_R), interpret)
        total = jnp.sum(folded)
        return total if squeeze else total[None]
    rows, words = a.shape
    pr = (-rows) % _TILE_R
    pw = (-words) % _TILE_W
    if pr or pw:
        a = jnp.pad(a, ((0, pr), (0, pw)))
        b = jnp.pad(b, ((0, pr), (0, pw)))
    out = _op_count_padded(op, a, b, interpret)
    out = out[:rows]
    return out[0] if squeeze else out


# -- sparse densify: the cold-path upload killer ---------------------------
#
# First queries used to ship DENSE words through the ~1.1 GB/s tunnel
# (128 KB per slice row regardless of density). The sparse path ships
# set words bucketed by 128-lane group — ``[T, 256, G]`` (lane, value)
# slots, G = max set words in any row's 128-word group — and densifies
# ON DEVICE with this kernel: G fully-vectorized one-hot OR passes over
# the VMEM-resident output tile. No scatter, no dynamic indexing: XLA's
# scatter lowering made the sparse path a loss (benchmarks/RESULTS.md
# negative result #2), and Mosaic forbids scalar/dynamic-lane VMEM
# access, so the layout is arranged host-side to make the kernel a pure
# vector computation (ops.packed.bucket_rows). This is the device
# analogue of the reference materializing a row in O(containers), not
# O(row width) (roaring.go:253-285).

_DENSIFY_TILE_R = 8  # TPU block sublane minimum: 8 rows per grid step
_DENSIFY_LANES = 128  # output tile: words viewed as [sublanes, 128 lanes]
_DENSIFY_TILE_S = 32  # 128-word groups per grid step (bounds the VMEM
                      # stack: each unrolled G pass holds one
                      # [8, 32, 128] u32 temp = 128 KB)


def _densify_kernel(lane_ref, val_ref, out_ref):
    lanes = jax.lax.broadcasted_iota(
        jnp.uint32, (1, 1, _DENSIFY_LANES), 2)
    acc = jnp.zeros(out_ref.shape, jnp.uint32)
    for g in range(lane_ref.shape[2]):  # static: one vector pass per slot
        lane_g = lane_ref[:, :, g][:, :, None]   # [8, tile_s, 1]
        val_g = val_ref[:, :, g][:, :, None]
        acc = acc | jnp.where(lanes == lane_g, val_g, jnp.uint32(0))
    out_ref[:] = acc


@functools.partial(jax.jit, static_argnums=(2, 3))
def densify_pallas(lane: jax.Array, val: jax.Array, n_words: int,
                   interpret: bool = False) -> jax.Array:
    """Bucketed sparse rows → dense u32 rows.

    ``lane``/``val`` are ``[T, n_words/128, G]``: slot g of group s of
    row t holds a word value and its lane (0-127) within the group;
    ``val == 0`` slots are padding (OR no-ops, any lane). Returns
    ``[T, n_words]``. Produced by ops.packed.bucket_rows."""
    t_rows, subs, g_slots = lane.shape
    if subs * _DENSIFY_LANES != n_words:
        raise ValueError("lane/val buckets do not match n_words")
    pr = (-t_rows) % _DENSIFY_TILE_R
    if pr:
        lane = jnp.pad(lane, ((0, pr), (0, 0), (0, 0)))
        val = jnp.pad(val, ((0, pr), (0, 0), (0, 0)))
    t_pad = t_rows + pr
    # Mosaic's stack model keeps every unrolled G pass's temp alive
    # concurrently (G x [8, tile_s, 128] u32), so the sublane tile
    # shrinks as G grows to stay inside the ~16 MB scoped-VMEM limit:
    # G * tile_s * 4 KB <= 8 MB. Beyond G=256 the data is dense enough
    # that callers must take the dense path (cost gate enforces this).
    if g_slots > 256:
        raise ValueError("densify_pallas: G > 256 — block too dense "
                         "for the sparse path; pack dense instead")
    tile_s = min(_DENSIFY_TILE_S, subs, max(8, 2048 // g_slots))
    while tile_s > 1 and subs % tile_s:
        tile_s //= 2
    if subs % tile_s or (tile_s < 8 and tile_s != subs):
        # grid = subs//tile_s must cover every group exactly, and the
        # block sublane dim must divide 8 or equal subs (Mosaic rule).
        raise ValueError(f"densify_pallas: no legal sublane tile for "
                         f"subs={subs}, G={g_slots}")
    out = pl.pallas_call(
        _densify_kernel,
        out_shape=jax.ShapeDtypeStruct(
            (t_pad, subs, _DENSIFY_LANES), jnp.uint32),
        grid=(t_pad // _DENSIFY_TILE_R, subs // tile_s),
        in_specs=[
            pl.BlockSpec((_DENSIFY_TILE_R, tile_s, g_slots),
                         lambda i, j: (i, j, 0)),
            pl.BlockSpec((_DENSIFY_TILE_R, tile_s, g_slots),
                         lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec(
            (_DENSIFY_TILE_R, tile_s, _DENSIFY_LANES),
            lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(lane, val)
    return out.reshape(t_pad, n_words)[:t_rows]
