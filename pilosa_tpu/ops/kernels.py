"""XLA device kernels over packed u32 words — the compute hot path.

This layer replaces the reference's native popcount kernels
(roaring/assembly_amd64.s: popcntAndSliceAsm and siblings, dispatched from
roaring.go:1266-1268,1431-1443): each fused op is one jitted XLA computation
``reduce(population_count(a ⊕ b))`` that XLA compiles to a single
VPU-resident loop over HBM — bitwise op, popcount, and row reduction fused,
nothing materialized.

Conventions:
- operands are u32 arrays, either ``[n_words]`` (one row) or
  ``[n_rows, n_words]`` (a row block); ops are elementwise in the last axis.
- counts are int32 per row (a slice row holds ≤ 2^20 bits, and even a full
  1 B-column row count fits int32); callers sum across rows/slices host-side
  in Python ints, or via psum on the mesh (pilosa_tpu.parallel).
- all entry points are jit-compiled with the op name static, so each
  (op, shape) pair compiles once and is cached.

A fused Pallas variant of the count kernels lives in
pilosa_tpu.ops.pallas_kernels; `op_count` auto-selects it on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_BITWISE = {
    "and": jnp.bitwise_and,
    "or": jnp.bitwise_or,
    "xor": jnp.bitwise_xor,
    "andnot": lambda a, b: jnp.bitwise_and(a, jnp.bitwise_not(b)),
}

OPS = tuple(_BITWISE)


@functools.partial(jax.jit, static_argnums=0)
def op_count_rows(op: str, a: jax.Array, b: jax.Array) -> jax.Array:
    """Fused ``popcount(a ⊕ b)`` summed over the word axis → int32 per row."""
    words = _BITWISE[op](a, b)
    return jnp.sum(jax.lax.population_count(words).astype(jnp.int32), axis=-1)


@functools.partial(jax.jit, static_argnums=0)
def _op_count_total_parts(op: str, a: jax.Array, b: jax.Array):
    words = _BITWISE[op](a, b)
    pc = jax.lax.population_count(words).astype(jnp.int32)
    row = jnp.sum(pc, axis=-1).ravel()
    # Split per-row counts into 16-bit halves before the cross-row reduce:
    # int64 is unavailable without x64, and a plain int32 sum overflows past
    # 2^31 total bits. Exact for ≤ 2^15 rows (lo ≤ 65535·2^15 < 2^31).
    # Stacked into ONE output: separate outputs each pay a host-fetch
    # round trip (~65 ms through a tunnel).
    return jnp.stack([jnp.sum(row >> 16), jnp.sum(row & 0xFFFF)])


def op_count_total(op: str, a: jax.Array, b: jax.Array) -> int:
    """Fused ``popcount(a ⊕ b)`` reduced over every axis → exact Python int.

    The Count() building block: shape-agnostic, so callers can hand XLA the
    layout that tiles best. Per-row counts stay in int32 (each row ≤ 2^31
    bits); the cross-row total is recombined host-side so it cannot
    overflow. Supports up to 2^15 rows per call.
    """
    if a.ndim > 1 and a.shape[0] > (1 << 15):
        raise ValueError("op_count_total: more than 2^15 rows per call")
    hilo = np.asarray(_op_count_total_parts(op, a, b))
    return (int(hilo[0]) << 16) + int(hilo[1])


@jax.jit
def popcount_rows(a: jax.Array) -> jax.Array:
    """Per-row popcount → int32."""
    return jnp.sum(jax.lax.population_count(a).astype(jnp.int32), axis=-1)


@functools.partial(jax.jit, static_argnums=0)
def row_block_op_count(op: str, rows: jax.Array, other: jax.Array
                       ) -> jax.Array:
    """Count ``popcount(rows[i] ⊕ other)`` for every row of a block.

    The TopN building block: ``rows`` is ``[n_rows, n_words]`` (the candidate
    row block resident in HBM), ``other`` a single ``[n_words]`` filter row
    broadcast against it. Replaces the reference's sequential
    per-row IntersectionCount loop (fragment.go:560-614) with one
    vectorized pass — different algorithm, same semantics.
    """
    words = _BITWISE[op](rows, other[None, :])
    return jnp.sum(jax.lax.population_count(words).astype(jnp.int32), axis=-1)


def op_count(op: str, a: jax.Array, b: jax.Array) -> jax.Array:
    """Fused count, auto-selecting the Pallas kernel on TPU (interpret
    mode when forced via PILOSA_TPU_PALLAS=interpret for CPU tests)."""
    from . import pallas_kernels
    mode = pallas_kernels.pallas_mode(pallas_kernels.platform_of(a))
    if mode is not None:
        return pallas_kernels.op_count_rows_pallas(
            op, a, b, interpret=(mode == "interpret"))
    return op_count_rows(op, a, b)


# -- BSI bit-plane comparison circuit (storage.bsi row layout) ----------------

# Supported comparison operators; "><" (between) composes two circuits
# at the caller (>= low AND <= high).
BSI_OPS = ("==", "!=", "<", "<=", ">", ">=")


def _bsi_eq_lt_gt(pbits, planes):
    """One MSB→LSB pass of the bit-sliced comparison over stacked
    planes ``[depth+1, ..., W]`` (planes[0] = existence, planes[1+i] =
    offset-value bit i): (eq, lt, gt) matched-word triples. ``pbits``
    is the predicate's bits LSB-first (``[depth]`` u32 of 0/1) and is
    TRACED — one compiled program serves every predicate at a given
    depth. Plain jnp body: usable inside jit/shard_map contexts."""
    depth = planes.shape[0] - 1
    eq = planes[0]
    lt = jnp.zeros_like(eq)
    gt = jnp.zeros_like(eq)
    for i in reversed(range(depth)):
        plane = planes[1 + i]
        bit = pbits[i] != 0
        not_plane = jnp.bitwise_not(plane)
        lt = jnp.where(bit, lt | (eq & not_plane), lt)
        gt = jnp.where(bit, gt, gt | (eq & plane))
        eq = jnp.where(bit, eq & plane, eq & not_plane)
    return eq, lt, gt


def bsi_compare_select(op: str, pbits, planes):
    """Matched words of ``value OP predicate`` from the circuit triple
    (``op`` static; see _bsi_eq_lt_gt for the layout)."""
    eq, lt, gt = _bsi_eq_lt_gt(pbits, planes)
    if op == "==":
        return eq
    if op == "!=":
        return planes[0] & jnp.bitwise_not(eq)
    if op == "<":
        return lt
    if op == "<=":
        return lt | eq
    if op == ">":
        return gt
    if op == ">=":
        return gt | eq
    raise ValueError(f"invalid BSI op: {op!r}")


@functools.partial(jax.jit, static_argnums=0)
def bsi_compare_words(op: str, pbits: jax.Array,
                      planes: jax.Array) -> jax.Array:
    """The whole comparison circuit as ONE XLA program: stacked
    bit-plane words in, matched words out — the single-device form of
    parallel.mesh.bsi_range_sharded. Compiles once per (op, depth,
    shape); the predicate rides in as data."""
    return bsi_compare_select(op, pbits, planes)


def bsi_predicate_bits(upred: int, depth: int) -> np.ndarray:
    """LSB-first u32 bit vector of an offset-space predicate."""
    return np.array([(upred >> i) & 1 for i in range(depth)],
                    dtype=np.uint32)


def bsi_compare_words_host(op: str, upred: int,
                           planes: np.ndarray) -> np.ndarray:
    """Pure-numpy twin of bsi_compare_words (the no-device fallback;
    also the differential oracle for the XLA program)."""
    depth = planes.shape[0] - 1
    eq = planes[0].copy()
    lt = np.zeros_like(eq)
    gt = np.zeros_like(eq)
    for i in reversed(range(depth)):
        plane = planes[1 + i]
        if (upred >> i) & 1:
            lt |= eq & ~plane
            eq &= plane
        else:
            gt |= eq & plane
            eq &= ~plane
    if op == "==":
        return eq
    if op == "!=":
        return planes[0] & ~eq
    if op == "<":
        return lt
    if op == "<=":
        return lt | eq
    if op == ">":
        return gt
    if op == ">=":
        return gt | eq
    raise ValueError(f"invalid BSI op: {op!r}")

