"""Bounded per-fingerprint plan store behind ``GET /debug/plans``.

One row per plan fingerprint (the normalized-shape hash, so literal
row ids and operand order collapse together): hit count, latency
p50/p99 over a bounded reservoir, estimated-vs-actual drift, the last
observed plan tree, and an example PQL. LRU-bounded — the store is a
debugging surface, not a history (obs.history keeps the time series).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

MAX_FINGERPRINTS = 256
_DURATIONS = 64
_DRIFTS = 64


class PlanStore:
    def __init__(self, max_fingerprints: int = MAX_FINGERPRINTS):
        self.max_fingerprints = max_fingerprints
        self._mu = threading.Lock()
        self._rows: OrderedDict[str, dict] = OrderedDict()

    def record(self, fingerprint: str, plan,
               duration_s: float, pql: str = "",
               est_rows=None, actual_rows=None) -> None:
        """``plan`` is the serialized tree, or a zero-arg callable
        producing it — the hot path passes a callable so a repeated
        fingerprint skips per-query serialization (the stored tree
        refreshes at most once a second)."""
        with self._mu:
            row = self._rows.get(fingerprint)
            if row is None:
                row = {"count": 0,
                       "durations": deque(maxlen=_DURATIONS),
                       "drifts": deque(maxlen=_DRIFTS),
                       "lastPlan": None, "examplePql": "",
                       "lastSeen": 0.0, "_planAt": 0.0}
                self._rows[fingerprint] = row
                while len(self._rows) > self.max_fingerprints:
                    self._rows.popitem(last=False)
            self._rows.move_to_end(fingerprint)
            row["count"] += 1
            row["durations"].append(duration_s)
            now = time.time()
            if callable(plan):
                if row["lastPlan"] is None or now - row["_planAt"] >= 1.0:
                    row["lastPlan"] = plan()
                    row["_planAt"] = now
            else:
                row["lastPlan"] = plan
                row["_planAt"] = now
            row["lastSeen"] = now
            if pql and not row["examplePql"]:
                row["examplePql"] = pql[:200]
            if est_rows is not None and actual_rows is not None:
                row["drifts"].append(
                    (actual_rows + 1) / (est_rows + 1))

    def snapshot(self, limit: int = 64) -> dict:
        with self._mu:
            items = list(self._rows.items())
        items.sort(key=lambda kv: kv[1]["count"], reverse=True)
        plans = []
        for fp, row in items[:limit]:
            durs = sorted(row["durations"])
            drifts = sorted(row["drifts"])
            entry = {
                "fingerprint": fp,
                "count": row["count"],
                "p50Ms": round(_quantile(durs, 0.5) * 1e3, 3),
                "p99Ms": round(_quantile(durs, 0.99) * 1e3, 3),
                "lastSeen": row["lastSeen"],
                "examplePql": row["examplePql"],
                "lastPlan": row["lastPlan"],
            }
            if drifts:
                entry["estActualDrift"] = {
                    "median": round(_quantile(drifts, 0.5), 3),
                    "p99": round(_quantile(drifts, 0.99), 3),
                    "n": len(drifts),
                }
            plans.append(entry)
        return {"fingerprints": len(items), "plans": plans}


def _quantile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            max(0, int(q * (len(sorted_vals) - 1) + 0.5)))
    return sorted_vals[i]
