"""Cost-based planning for read queries (ROADMAP item 1).

The executor consults the planner once per read query, before the
cluster-cache key is computed and before any fan-out. Planning operates
on a CLONE of the parsed call tree and produces (rewritten calls, a
``PlanRecord``); the executor then runs the rewritten tree and fills in
per-node actuals. Four decision kinds, each observable in the plan tree
and in ``pilosa_planner_decisions_total{outcome}``:

- **reorder** — ``Intersect``/``Union`` operands sorted smallest-first
  by estimated cardinality (the galloping-intersection ordering,
  arXiv:1402.6407 §4): the host fold then carries the smallest running
  operand, and the pairwise ``intersection_count`` shortcut sees its
  cheap operand first. ``Difference`` is never reordered (left operand
  is semantic).
- **short_circuit** — branches PROVEN empty are not executed: an
  exactly-empty operand empties an ``Intersect``, is dropped from a
  ``Union``/``Difference`` subtrahend list, and empties a whole call
  (``Count`` answers 0 with no fan-out). Proofs are exact only — every
  slice enumerated against local fragments (absent fragment = 0 bits);
  sampled or non-local estimates never short-circuit.
- **cse** — duplicate pure bitmap subtrees are hoisted through a
  generation-token-keyed per-slice subresult cache (SubresultCache):
  the second occurrence WITHIN a batch and repeats ACROSS queries fold
  once per slice and then hit. Keys carry the slice's (uid, generation)
  tokens (cluster.generations), so any write to any involved fragment
  invalidates by key mismatch — the PR-9 whole-query cache rule,
  generalized to interior nodes.
- **placement** — per-subtree host/device choice priced from the
  measured ``costmodel`` constants (sync floor, host fold rate, upload
  rate) instead of the global slice/leaf gates alone: a ``host`` hint
  makes the executor skip the device attempt for that subtree; the
  device gates still apply when the hint is ``auto``/``device``.

Estimates come from ``Fragment`` rank caches (``cache.get(rid)``) with
a ``row_count`` fallback, summed across slices — exact up to
``EXACT_SLICES`` slices, sampled+extrapolated past that. Estimation
never faults cold tier fragments in (cache-only, inexact) and never
reaches across the cluster (non-local slices extrapolate from the
local fraction, inexact).

Finished plans are memoized (``plan_query_cached``): a repeated query
reuses its plan after an epoch-validation sweep over the exact facts
the plan's proofs rest on, which is what keeps planner-on p50 within
the ≤2% overhead budget on hot repeated queries.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional

from ..cluster import generations
from ..obs import metrics as obs_metrics
from ..ops.packed import WORDS_PER_SLICE
from ..pql.ast import Call, Condition
from .record import PlanNode, PlanRecord, fingerprint_calls

# Slice-count ceiling for exact (every slice enumerated) estimation;
# past it the planner samples ESTIMATE_SAMPLES slices and extrapolates.
EXACT_SLICES = 64
ESTIMATE_SAMPLES = 8

# The ops the planner rewrites / caches. Placement + estimation also
# understand Count/TopN wrappers (their bitmap child is planned).
_BITMAP_OPS = ("Intersect", "Union", "Difference")

# Per-operand estimation entries kept (keyed by fragment mutation
# epoch, so a write invalidates in place).
_ESTIMATE_CACHE_ENTRIES = 4096
# Canonical subtrees remembered for cross-query CSE detection.
_SEEN_ENTRIES = 1024
# Finished plans memoized per (index, canonical calls, slices) — the
# repeated-query fast path the ≤2% overhead budget requires. Validity
# is fact-checked per hit (plan_query_cached), never assumed.
_PLAN_MEMO_ENTRIES = 256


def _observe_misestimate(node: PlanNode, rows: int) -> None:
    node.actual_rows = rows
    if node.est_rows is None:
        return
    ratio = (rows + 1) / (node.est_rows + 1)
    obs_metrics.PLANNER_MISESTIMATE.observe(ratio)


class SubresultCache:
    """Bounded per-slice interior-node result cache.

    Key: (index, canonical subtree, slice, generation tokens of every
    frame/view the subtree reads at that slice). A mutation bumps the
    fragment generation, the token tuple changes, and the stale entry
    simply stops matching (it ages out by LRU) — no explicit
    invalidation channel, the PR-9 contract.
    """

    def __init__(self, max_entries: int = 512,
                 max_bits: int = 32 << 20):
        self.max_entries = max_entries
        self.max_bits = max_bits
        self._mu = threading.Lock()
        self._entries: OrderedDict[tuple, tuple] = OrderedDict()
        self._bits = 0

    def get(self, key: tuple):
        with self._mu:
            ent = self._entries.get(key)
            if ent is None:
                obs_metrics.PLANNER_SUBRESULT_EVENTS.labels(
                    "miss").inc()
                return None
            self._entries.move_to_end(key)
        obs_metrics.PLANNER_SUBRESULT_EVENTS.labels("hit").inc()
        return ent[0]

    def put(self, key: tuple, bm, bits: int) -> None:
        with self._mu:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bits -= old[1]
            self._entries[key] = (bm, bits)
            self._bits += bits
            while (len(self._entries) > self.max_entries
                   or self._bits > self.max_bits):
                if len(self._entries) <= 1 and \
                        self._bits <= self.max_bits:
                    break
                _, (_, b) = self._entries.popitem(last=False)
                self._bits -= b
                obs_metrics.PLANNER_SUBRESULT_EVENTS.labels(
                    "evict").inc()
        obs_metrics.PLANNER_SUBRESULT_EVENTS.labels("store").inc()

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()
            self._bits = 0

    def stats(self) -> dict:
        with self._mu:
            return {"entries": len(self._entries), "bits": self._bits}


class Planner:
    """One per executor. Thread-safe: planning itself runs on the
    query thread; the seen/estimate LRUs take the planner lock."""

    def __init__(self, holder, margin: float = 0.5,
                 subresult_entries: int = 512,
                 subresult_bits: int = 32 << 20):
        self.holder = holder
        self.margin = margin
        self.subresults = SubresultCache(subresult_entries,
                                         subresult_bits)
        # Measured cost constants (parallel.costmodel Calibration);
        # the executor installs its calibrated model's constants once
        # a mesh exists, warmup primes the persisted ones earlier.
        self.calibration = None
        self._mu = threading.Lock()
        self._seen: OrderedDict[str, int] = OrderedDict()
        self._estimates: OrderedDict[tuple, tuple] = OrderedDict()
        # Finished-plan memo: key -> {planned, roots, fingerprint,
        # decisions, deps, cse_nodes} (plan_query_cached).
        self._plans: OrderedDict[tuple, dict] = OrderedDict()
        # Decision roll-up for the blackbox / debug snapshot.
        self.decision_totals: dict[str, int] = {}

    # -- public entry points -------------------------------------------------

    def plan_query(self, index: str, calls: list[Call], slices,
                   all_local: bool = True,
                   record: Optional[PlanRecord] = None,
                   deps: Optional[list] = None
                   ) -> tuple[list[Call], PlanRecord]:
        """Plan a batch of read calls. Returns (rewritten clones, the
        populated record). Caller is responsible for gating (write
        queries and disabled planning never reach here). ``deps``, when
        given, collects the (frame, view, fragment-epoch) facts the
        plan's estimates rest on — the memo validity set."""
        t0 = time.perf_counter()
        if record is None:
            record = PlanRecord(fingerprint_calls(calls))
        idx = self.holder.index(index)
        slices = tuple(int(s) for s in slices)
        planned: list[Call] = []
        for call in calls:
            c = call.clone()
            node = self._plan_call(idx, index, c, slices, all_local,
                                   record, covered=True, deps=deps)
            record.roots.append(node)
            planned.append(c)
        record.note("planned")
        self._bump("planned")
        obs_metrics.PLANNER_PLAN_SECONDS.observe(
            time.perf_counter() - t0)
        return planned, record

    def plan_query_cached(self, index: str, calls: list[Call], slices,
                          all_local: bool = True, node: str = ""
                          ) -> tuple[list[Call], PlanRecord]:
        """``plan_query`` behind a bounded memo: a repeated query (the
        hot shape the PR-9 caches serve) reuses its finished plan
        instead of re-walking estimation and fingerprinting, so
        planning amortizes to a key build plus a validity sweep.

        Safety: every entry carries the exact facts its proofs rest on
        — frame/view identity and per-fragment mutation epochs,
        including PROVABLY ABSENT fragments/views (a fragment appearing
        breaks an emptiness proof as surely as a write). Any mismatch
        discards the entry and replans, so a memoized short-circuit can
        never outlive the emptiness it proved. Plan NODES are shared
        across hits; the per-query PlanRecord (actuals, stitched legs)
        is always fresh."""
        slices = tuple(int(s) for s in slices)
        try:
            key = (index, tuple(_memo_call_key(c) for c in calls),
                   slices, bool(all_local))
            with self._mu:
                ent = self._plans.get(key)
                if ent is not None:
                    self._plans.move_to_end(key)
        except TypeError:
            # Unhashable literal somewhere in the tree — plan uncached.
            key = ent = None
        if ent is not None and self._deps_valid(index, ent["deps"]):
            ent["hits"] = hits = ent["hits"] + 1
            rec = PlanRecord(ent["fingerprint"], node=node)
            # Roots/calls are aliased, not copied: plan shape is
            # immutable after planning (only per-node actuals race,
            # and those are observability-only).
            rec.roots = ent["roots"]
            rec.decisions.update(ent["decisions"])
            rec.sample = hits % 16 == 0
            # A hit is another sighting of every cacheable subtree —
            # keep the cross-query CSE ladder climbing to store state.
            for n in ent["cse_nodes"]:
                if not n.cache_store:
                    self._mark_cse(n)
            return ent["planned"], rec
        rec = PlanRecord(fingerprint_calls(calls), node=node)
        deps: list[tuple] = []
        planned, rec = self.plan_query(index, calls, slices,
                                       all_local=all_local,
                                       record=rec, deps=deps)
        cse_nodes = [n for root in rec.roots
                     for n in _walk_nodes(root) if n.cache_lookup]
        if key is not None:
            ent = {"planned": planned, "roots": list(rec.roots),
                   "fingerprint": rec.fingerprint,
                   "decisions": rec.decision_summary(),
                   "deps": deps, "cse_nodes": cse_nodes, "hits": 0}
            with self._mu:
                self._plans[key] = ent
                while len(self._plans) > _PLAN_MEMO_ENTRIES:
                    self._plans.popitem(last=False)
        return planned, rec

    def _deps_valid(self, index: str, deps) -> bool:
        """True when every fact a memoized plan depends on still
        holds. Identity checks (``is``) catch drop-and-recreate, not
        just mutation."""
        idx = self.holder.index(index)
        if idx is None:
            return False
        try:
            for d in deps:
                kind = d[0]
                if kind == "frag":
                    _, view, s, epoch = d
                    frag = view.fragments.get(s)
                    cur = (None if frag is None
                           else getattr(frag, "_epoch", 0))
                    if cur != epoch:
                        return False
                elif kind == "view":
                    _, frame, view = d
                    if frame.views.get("standard") is not view:
                        return False
                else:  # "frame"
                    _, name, frame = d
                    if idx.frames.get(name) is not frame:
                        return False
        except Exception:  # noqa: BLE001 - any doubt means replan
            return False
        return True

    def explain(self, index: str, calls: list[Call], slices,
                all_local: bool = True) -> dict:
        """EXPLAIN-only (?plan=1): plan without executing."""
        _, record = self.plan_query(index, calls, slices,
                                    all_local=all_local)
        return record.to_tree()

    def snapshot(self) -> dict:
        """Planner state for the blackbox / debug surfaces."""
        with self._mu:
            totals = dict(self.decision_totals)
            seen = len(self._seen)
        out = {"decisions": totals, "seenSubtrees": seen,
               "subresultCache": self.subresults.stats()}
        if self.calibration is not None:
            out["calibration"] = self.calibration.to_dict()
        return out

    # -- decision bookkeeping ------------------------------------------------

    def _bump(self, outcome: str) -> None:
        obs_metrics.PLANNER_DECISIONS.labels(outcome).inc()
        with self._mu:
            self.decision_totals[outcome] = \
                self.decision_totals.get(outcome, 0) + 1

    def _decide(self, record: PlanRecord, node: PlanNode,
                outcome: str) -> None:
        node.decisions.append(outcome)
        record.note(outcome)
        self._bump(outcome)

    # -- recursive planning --------------------------------------------------

    def _plan_call(self, idx, index: str, call: Call, slices,
                   all_local: bool, record: PlanRecord,
                   covered: bool = False,
                   deps: Optional[list] = None) -> PlanNode:
        """Plan one call subtree in place (mutates the clone).
        ``covered`` marks subtrees the executor's whole-result caches
        already key (the root of a Union/Intersect/Difference call) —
        those skip subresult-cache marking to avoid double storage."""
        name = call.name
        if name == "Bitmap":
            return self._plan_leaf(idx, call, slices, all_local, deps)
        if name in _BITMAP_OPS:
            return self._plan_bitmap_op(idx, index, call, slices,
                                        all_local, record, covered,
                                        deps)
        # Wrappers (Count/TopN/...) — plan bitmap children; the call
        # itself is a pass-through node.
        node = PlanNode(name)
        for child in call.children:
            node.children.append(self._plan_call(
                idx, index, child, slices, all_local, record,
                deps=deps))
        if node.children:
            first = node.children[0]
            node.est_rows = first.est_rows
            node.exact = first.exact
            if name == "Count" and first.short_circuit:
                # Count of a proven-empty subtree answers 0 without
                # fan-out.
                node.short_circuit = True
                self._decide(record, node, "short_circuit")
        return node

    def _plan_bitmap_op(self, idx, index: str, call: Call, slices,
                        all_local: bool, record: PlanRecord,
                        covered: bool,
                        deps: Optional[list] = None) -> PlanNode:
        node = PlanNode(call.name)
        child_nodes = [self._plan_call(idx, index, c, slices,
                                       all_local, record, deps=deps)
                       for c in call.children]

        # Short-circuit rewrites (exact proofs only).
        if call.name == "Intersect":
            if any(c.exact and c.est_rows == 0 for c in child_nodes):
                node.short_circuit = True
                node.est_rows, node.exact = 0, True
                node.children = child_nodes
                self._decide(record, node, "short_circuit")
                return node
        elif call.name == "Union":
            keep = [i for i, c in enumerate(child_nodes)
                    if not (c.exact and c.est_rows == 0)]
            if not keep:
                node.short_circuit = True
                node.est_rows, node.exact = 0, True
                node.children = child_nodes
                self._decide(record, node, "short_circuit")
                return node
            if len(keep) < len(child_nodes):
                call.children = [call.children[i] for i in keep]
                child_nodes = [child_nodes[i] for i in keep]
                self._decide(record, node, "short_circuit")
        elif call.name == "Difference":
            if (child_nodes and child_nodes[0].exact
                    and child_nodes[0].est_rows == 0):
                node.short_circuit = True
                node.est_rows, node.exact = 0, True
                node.children = child_nodes
                self._decide(record, node, "short_circuit")
                return node
            keep = [0] + [i for i in range(1, len(child_nodes))
                          if not (child_nodes[i].exact
                                  and child_nodes[i].est_rows == 0)]
            if child_nodes and len(keep) < len(child_nodes):
                call.children = [call.children[i] for i in keep]
                child_nodes = [child_nodes[i] for i in keep]
                self._decide(record, node, "short_circuit")

        # Reorder commutative operands smallest-first.
        if call.name in ("Intersect", "Union") and len(child_nodes) > 1:
            order = sorted(
                range(len(child_nodes)),
                key=lambda i: (child_nodes[i].est_rows
                               if child_nodes[i].est_rows is not None
                               else float("inf")))
            if order != list(range(len(child_nodes))):
                call.children = [call.children[i] for i in order]
                child_nodes = [child_nodes[i] for i in order]
                self._decide(record, node, "reordered")

        node.children = child_nodes

        # Combined estimate.
        ests = [c.est_rows for c in child_nodes]
        known = [e for e in ests if e is not None]
        all_exact = bool(child_nodes) and all(c.exact
                                              for c in child_nodes)
        if call.name == "Intersect" and known:
            node.est_rows = min(known)
            node.exact = all_exact and node.est_rows == 0
        elif call.name == "Union" and len(known) == len(ests):
            node.est_rows = sum(known)
            node.exact = all_exact and node.est_rows == 0
        elif call.name == "Difference" and ests and ests[0] is not None:
            node.est_rows = ests[0]
            node.exact = child_nodes[0].exact and node.est_rows == 0

        # Purity: every descendant contributed a known frame/view set.
        frames: set = set()
        pure = bool(child_nodes)
        for c in child_nodes:
            if not c.frames:
                pure = False
                break
            frames.update(c.frames)
        if pure:
            node.frames = frozenset(frames)
            node.key = str(call)
            if not covered:
                self._mark_cse(node)
            self._placement(node, slices)
        return node

    def _plan_leaf(self, idx, call: Call, slices,
                   all_local: bool,
                   deps: Optional[list] = None) -> PlanNode:
        node = PlanNode("Bitmap")
        frame_name = call.args.get("frame")
        if not isinstance(frame_name, str) or not frame_name:
            frame_name = "general"  # executor.DEFAULT_FRAME
        if idx is None or call.args.get("filter") is not None:
            return node
        frame = idx.frames.get(frame_name)
        if frame is None:
            # No frame: estimation stays open (the executor raises its
            # own FrameNotFound; planning must not pre-empt errors).
            return node
        try:
            row_id, row_ok = call.uint_arg(frame.row_label)
        except ValueError:
            row_ok = False
            row_id = 0
        if not row_ok:
            # Inverse leaves (columnID) read the inverse view over a
            # different slice domain; leave them unestimated.
            return node
        node.detail = f"{frame_name}/{row_id}"
        view = frame.views.get("standard")
        node.frames = frozenset((f"{frame_name}/standard",))
        node.key = str(call)
        if deps is not None:
            deps.append(("frame", frame_name, frame))
            # A view APPEARING breaks a proof ("no view" = exact 0).
            deps.append(("view", frame, view))
        est, exact = self._estimate_row(view, row_id, slices,
                                        all_local, deps)
        node.est_rows, node.exact = est, exact
        return node

    def _estimate_row(self, view, row_id: int, slices,
                      all_local: bool,
                      deps: Optional[list] = None) -> tuple[int, bool]:
        """Estimated bits for one (frame, standard view, row) over
        ``slices``. Exact only when every slice was enumerated against
        a local fragment (or a provably absent one)."""
        if view is None:
            return (0, all_local)
        if len(slices) > EXACT_SLICES:
            step = max(1, len(slices) // ESTIMATE_SAMPLES)
            sample = slices[::step][:ESTIMATE_SAMPLES]
            total, _ = self._sum_slices(view, row_id, sample, False,
                                        deps)
            scaled = int(total * len(slices) / max(len(sample), 1))
            return (scaled, False)
        return self._sum_slices(view, row_id, slices, all_local, deps)

    def _sum_slices(self, view, row_id: int, slices,
                    all_local: bool,
                    deps: Optional[list] = None) -> tuple[int, bool]:
        total = 0
        exact = all_local
        for s in slices:
            frag = view.fragments.get(s)
            if frag is None:
                # Locally absent fragment = 0 bits — exact only when
                # this node owns every slice of the query. The absence
                # itself is a memo dependency: a fragment appearing
                # voids the proof.
                if deps is not None:
                    deps.append(("frag", view, s, None))
                continue
            key = (id(view), row_id, s)
            epoch = getattr(frag, "_epoch", 0)
            if deps is not None:
                deps.append(("frag", view, s, epoch))
            with self._mu:
                hit = self._estimates.get(key)
                if hit is not None and hit[0] == epoch:
                    self._estimates.move_to_end(key)
                    total += hit[1]
                    continue
            n = 0
            try:
                if frag.cache is not None:
                    n = int(frag.cache.get(row_id))
                if n <= 0:
                    if (frag.tier is not None
                            and frag.tier_state != "hot"):
                        # Never fault a cold fragment in to plan a
                        # query; the estimate stays open.
                        exact = False
                        continue
                    n = int(frag.row_count(row_id))
            except Exception:
                exact = False
                continue
            total += n
            with self._mu:
                self._estimates[key] = (epoch, n)
                while len(self._estimates) > _ESTIMATE_CACHE_ENTRIES:
                    self._estimates.popitem(last=False)
        return (total, exact)

    # -- CSE + placement -----------------------------------------------------

    def _mark_cse(self, node: PlanNode) -> None:
        """Interior pure subtrees consult the subresult cache; a
        subtree STORES once its canonical form has been seen twice
        (within one batch or across queries) — first sightings only
        register, so one-off shapes never occupy cache budget."""
        with self._mu:
            count = self._seen.get(node.key, 0) + 1
            self._seen[node.key] = count
            self._seen.move_to_end(node.key)
            while len(self._seen) > _SEEN_ENTRIES:
                self._seen.popitem(last=False)
        node.cache_lookup = True
        node.cache_store = count >= 2
        if count >= 2 and "cse" not in node.decisions:
            node.decisions.append("cse")

    def _placement(self, node: PlanNode, slices) -> None:
        """Price host vs device for this subtree from the measured
        constants. Only a clear host win becomes a hint (the costmodel
        margin rule); everything else stays ``auto`` and the usual
        device gates decide."""
        cal = self.calibration
        if cal is None or not slices:
            return
        leaves = _count_leaves(node)
        n_slices = len(slices)
        slab = n_slices * WORDS_PER_SLICE * 4
        device_bytes = leaves * slab
        host_bytes = 0
        for leaf_est in _leaf_estimates(node):
            if leaf_est is None:
                host_bytes += slab
            else:
                # Roaring walk cost: ~2 bytes/bit in array containers,
                # capped at the dense slab.
                host_bytes += min(leaf_est * 2, slab)
        host = cal.host_cost(host_bytes)
        device = cal.device_cost(device_bytes)
        node.est_cost_s = min(host, device)
        if host < self.margin * device:
            node.placement = "host"
            node.decisions.append("placement:host")
            self._bump("placement")
        else:
            node.placement = "device"

    # -- subresult cache wiring ----------------------------------------------

    def subresult_key(self, index: str, node: PlanNode,
                      slice: int) -> Optional[tuple]:
        """The generation-token cache key for one planned subtree at
        one slice, or None when any involved fragment is untracked."""
        toks = generations.slice_tokens(self.holder, index, slice)
        out = []
        for fv in sorted(node.frames):
            out.append((fv, toks.get(fv, (0, 0))))
        return (index, node.key, int(slice), tuple(out))


def _memo_call_key(call: Call) -> tuple:
    """Structural memo key for one call — a nested tuple, much cheaper
    to build than the canonical string. Raises TypeError on an
    unhashable literal (caller plans uncached)."""
    items = []
    for k in sorted(call.args):
        v = call.args[k]
        if isinstance(v, Condition):
            v = (v.op, v.value if not isinstance(v.value, list)
                 else tuple(v.value))
        elif isinstance(v, list):
            v = tuple(v)
        items.append((k, v))
    return (call.name, tuple(items),
            tuple(_memo_call_key(c) for c in call.children))


def _walk_nodes(node: PlanNode):
    yield node
    for c in node.children:
        yield from _walk_nodes(c)


def _count_leaves(node: PlanNode) -> int:
    if not node.children:
        return 1
    return sum(_count_leaves(c) for c in node.children)


def _leaf_estimates(node: PlanNode):
    if not node.children:
        yield node.est_rows
        return
    for c in node.children:
        yield from _leaf_estimates(c)
