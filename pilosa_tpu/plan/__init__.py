"""Cost-based query planning + the EXPLAIN/ANALYZE observability plane.

- ``planner`` — the planner itself: cardinality estimation, operand
  reordering, short-circuiting, CSE via the generation-token-keyed
  subresult cache, per-subtree placement.
- ``record`` — plan trees, the per-query ``ctx.plan`` record, the
  X-Pilosa-Plan stitching wire, the normalized fingerprint.
- ``store`` — the bounded per-fingerprint store behind /debug/plans.
"""

from .planner import Planner, SubresultCache
from .record import (PLAN_HEADER, PlanNode, PlanRecord, enabled,
                     fingerprint_calls, set_enabled)
from .store import PlanStore

__all__ = ["Planner", "SubresultCache", "PlanNode", "PlanRecord",
           "PlanStore", "PLAN_HEADER", "enabled", "set_enabled",
           "fingerprint_calls"]
