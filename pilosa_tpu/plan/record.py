"""Per-query plan artifacts: the plan tree, its wire record, and the
stable fingerprint.

The planner (plan.planner) rewrites a read query before execution; this
module is the OBSERVABILITY half — every planned query carries a
``PlanRecord`` on its QueryContext (``ctx.plan``, next to ``ctx.cost``)
holding the chosen plan tree with per-node estimated-vs-actual
cardinality and cost. The record follows the PR-4 cost-ledger shape:

- remote legs serialize their plan into the ``X-Pilosa-Plan`` response
  header (48 KiB budget) and the coordinator's client stitches it back
  under the originating record (``add_remote_json``), so ``?profile=1``
  shows ONE plan tree spanning the whole cluster;
- ``?profile=1`` embeds ``to_tree()`` in the response (EXPLAIN ANALYZE);
  ``?plan=1`` returns the same shape without executing (EXPLAIN);
- the module enable switch mirrors obs.accounting: planning stays on by
  default and ``set_enabled(False)`` (or PILOSA_TPU_PLANNER=0) restores
  the unplanned dispatcher for A/B measurement.

Fingerprint stability contract (docs/OBSERVABILITY.md): the fingerprint
hashes the NORMALIZED canonical tree — numeric literals (row/column ids,
TopN n, BSI condition values) become ``?`` while frame/view/field names
are kept, and commutative operands (Intersect/Union children) are
sorted by their normalized form. Two queries with the same shape over
the same frames share a fingerprint regardless of literal ids or
operand order, so ``/debug/plans`` aggregates them into one row.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Optional

from ..pql.ast import Call, Condition

PLAN_HEADER = "X-Pilosa-Plan"

# Remote legs stitched under one coordinator record; past the cap extra
# legs are dropped (the accounting MAX_CHILDREN rule — a plan is a
# debugging artifact, not an unbounded ledger).
MAX_CHILDREN = 64

_enabled = os.environ.get("PILOSA_TPU_PLANNER", "1") != "0"


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


# -- fingerprint --------------------------------------------------------------


def _norm_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return "?"
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_norm_value(x) for x in v) + "]"
    return repr(v)


def normalize_call(call: Call) -> str:
    """The normalized canonical form one call hashes to — numeric
    literals ``?``'d out, commutative children sorted."""
    parts = [normalize_call(c) for c in call.children]
    if call.name in ("Intersect", "Union"):
        parts.sort()
    for k in sorted(call.args):
        v = call.args[k]
        if isinstance(v, Condition):
            parts.append(f"{k} {v.op} ?")
        else:
            parts.append(f"{k}={_norm_value(v)}")
    return f"{call.name}({','.join(parts)})"


def fingerprint_calls(calls) -> str:
    text = "\n".join(normalize_call(c) for c in calls)
    return hashlib.sha1(text.encode()).hexdigest()[:12]


# -- the plan tree ------------------------------------------------------------


class PlanNode:
    """One operator of the chosen plan. ``est_rows``/``est_cost_s``
    are the planner's predictions; ``actual_rows``/``actual_s`` are
    filled by the executor as the node runs (ANALYZE). ``decisions``
    records what the planner DID here (reordered / short_circuit /
    cse / placement:*) so a plan reads as a decision log, not just a
    shape."""

    __slots__ = ("op", "detail", "est_rows", "exact", "est_cost_s",
                 "placement", "decisions", "children", "actual_rows",
                 "actual_s", "frames", "key", "cache_lookup",
                 "cache_store", "short_circuit")

    def __init__(self, op: str, detail: str = ""):
        self.op = op
        self.detail = detail
        self.est_rows: Optional[int] = None
        self.exact = False
        self.est_cost_s: Optional[float] = None
        self.placement = "auto"
        self.decisions: list[str] = []
        self.children: list[PlanNode] = []
        self.actual_rows: Optional[int] = None
        self.actual_s: Optional[float] = None
        # Planner wiring (not serialized): frame/view keys under this
        # subtree, the canonical subtree string (the subresult-cache
        # key stem), and the cache/short-circuit marks.
        self.frames: frozenset = frozenset()
        self.key = ""
        self.cache_lookup = False
        self.cache_store = False
        self.short_circuit = False

    def to_json(self) -> dict:
        out: dict = {"op": self.op}
        if self.detail:
            out["detail"] = self.detail
        if self.est_rows is not None:
            out["estRows"] = int(self.est_rows)
            out["exact"] = self.exact
        if self.est_cost_s is not None:
            out["estCostS"] = round(self.est_cost_s, 6)
        if self.placement != "auto":
            out["placement"] = self.placement
        if self.decisions:
            out["decisions"] = list(self.decisions)
        if self.actual_rows is not None:
            out["actualRows"] = int(self.actual_rows)
        if self.actual_s is not None:
            out["actualS"] = round(self.actual_s, 6)
        if self.children:
            out["children"] = [c.to_json() for c in self.children]
        return out


class PlanRecord:
    """The per-query plan ledger riding ``ctx.plan`` (the ctx.cost
    pattern): root plan nodes (one per planned call), the query
    fingerprint, a decision roll-up, and remote-leg plans stitched in
    from X-Pilosa-Plan headers."""

    __slots__ = ("fingerprint", "node", "roots", "decisions",
                 "children", "analyze", "sample", "_mu")

    def __init__(self, fingerprint: str, node: str = ""):
        self.fingerprint = fingerprint
        self.node = node
        self.roots: list[PlanNode] = []
        self.decisions: dict[str, int] = {}
        self.children: list[dict] = []
        self.analyze = False
        # Observability sampling gate: freshly-planned queries and a
        # 1-in-16 slice of plan-memo hits carry full per-node actuals
        # and feed the plan store / misestimation stream; the rest skip
        # that bookkeeping (the ≤2% overhead budget). ?profile=1
        # (analyze) always records.
        self.sample = True
        self._mu = threading.Lock()

    def note(self, outcome: str, n: int = 1) -> None:
        with self._mu:
            self.decisions[outcome] = self.decisions.get(outcome, 0) + n

    def add_remote_json(self, payload: str) -> None:
        """Stitch one remote leg's plan (its wire_json) under this
        record — the trace/cost header-stitching contract."""
        try:
            child = json.loads(payload)
        except (ValueError, TypeError):
            return
        if not isinstance(child, dict):
            return
        with self._mu:
            if len(self.children) < MAX_CHILDREN:
                self.children.append(child)

    def decision_summary(self) -> dict:
        with self._mu:
            return dict(self.decisions)

    def to_tree(self) -> dict:
        out: dict = {
            "fingerprint": self.fingerprint,
            "node": self.node,
            "calls": [r.to_json() for r in self.roots],
        }
        summary = self.decision_summary()
        if summary:
            out["decisions"] = summary
        with self._mu:
            if self.children:
                out["legs"] = list(self.children)
        return out

    def wire_json(self, max_bytes: int = 48 << 10) -> str:
        """The X-Pilosa-Plan payload, kept under the header budget the
        way trace spans are: drop stitched legs first, then per-node
        detail, halving until it fits."""
        tree = self.to_tree()
        payload = json.dumps(tree, separators=(",", ":"))
        while len(payload) > max_bytes:
            legs = tree.get("legs")
            if legs:
                del legs[len(legs) // 2:]
                if not legs:
                    tree.pop("legs", None)
            elif tree.get("calls"):
                del tree["calls"][len(tree["calls"]) // 2:]
            else:
                break
            payload = json.dumps(tree, separators=(",", ":"))
        return payload


def current_plan() -> Optional[PlanRecord]:
    """The calling thread's bound plan record, if its query has one —
    the executor's per-slice hooks run in pool threads that carry the
    context via sched_context.use."""
    from ..sched.context import current
    ctx = current()
    return getattr(ctx, "plan", None) if ctx is not None else None
