"""pilosa_tpu — a TPU-native distributed bitmap index.

A ground-up re-design of Pilosa (reference: /root/reference, Go) for TPU
hardware: host-side storage keeps the reference's roaring snapshot+op-log file
format, while the compute hot path (container intersect/union/andnot/popcount,
TopN) runs as XLA/Pallas kernels over dense packed words held in HBM, and the
per-slice map-reduce is a `shard_map` over a `jax.sharding.Mesh` with ICI
collectives for the reductions.

Layer map (mirrors SURVEY.md §1):
    cli/        command-line verbs (server, import, export, backup, ...)
    server/     HTTP API + server runtime
    pql/        query language lexer/parser/AST
    executor    per-call dispatch + cluster map-reduce
    cluster/    topology, jump-hash sharding, broadcast, node-to-node client
    models/     holder → index → frame → view schema hierarchy
    storage/    fragment (snapshot+oplog), roaring bitmaps, caches, attrs
    ops/        device kernel layer: packed bitmaps + XLA/Pallas kernels
    parallel/   mesh construction, shard_map slice executor, HBM residency
    utils/      time quantum engine, stats, config, iterators
"""

__version__ = "0.1.0"

# SliceWidth is the number of columns in a slice (reference: fragment.go:47).
SLICE_WIDTH = 1 << 20
