"""View: container of fragments by slice for one orientation/time granularity.

Reference: view.go. Names: ``standard``, ``inverse``, plus time-suffixed
variants (``standard_2017``, ``standard_201701``, ...). Directory layout
``<frame>/views/<name>/fragments/<slice>`` (view.go:186-189). Creating a
fragment for a new max slice notifies the cluster via the on_create_slice
hook (view.go:219-254 broadcasts CreateSliceMessage).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Optional

from .. import SLICE_WIDTH
from ..storage import cache as cache_mod
from ..storage.fragment import Fragment
from ..utils import logger as logger_mod
from ..utils.stats import NOP

VIEW_STANDARD = "standard"
VIEW_INVERSE = "inverse"
# BSI integer-field views: one per field, column-sharded like standard
# (pilosa 1.0's viewFieldPrefix).
VIEW_FIELD_PREFIX = "field_"


def is_inverse_view(name: str) -> bool:
    return name.startswith(VIEW_INVERSE)


def is_field_view(name: str) -> bool:
    return name.startswith(VIEW_FIELD_PREFIX)


def field_view_name(field: str) -> str:
    return VIEW_FIELD_PREFIX + field


def is_valid_view(name: str) -> bool:
    return (name.startswith(VIEW_STANDARD)
            or name.startswith(VIEW_INVERSE)
            or is_field_view(name))


class View:
    def __init__(self, path: str, index: str, frame: str, name: str,
                 cache_type: str = cache_mod.DEFAULT_CACHE_TYPE,
                 cache_size: int = cache_mod.DEFAULT_CACHE_SIZE,
                 row_attr_store=None,
                 on_create_slice: Optional[Callable[[int], None]] = None,
                 stats=NOP, logger=logger_mod.NOP, quarantine=None):
        self.logger = logger
        self.quarantine = quarantine  # holder's QuarantineRegistry
        self.path = path
        self.index = index
        self.frame = frame
        self.name = name
        self.cache_type = cache_type
        self.cache_size = cache_size
        self.row_attr_store = row_attr_store
        self.on_create_slice = on_create_slice
        self.stats = stats
        self.fragments: dict[int, Fragment] = {}
        self._max_slice = 0
        self._mu = threading.RLock()

    # -- lifecycle

    @property
    def fragments_path(self) -> str:
        return os.path.join(self.path, "fragments")

    def fragment_path(self, slice: int) -> str:
        return os.path.join(self.fragments_path, str(slice))

    def open(self) -> None:
        with self._mu:
            os.makedirs(self.fragments_path, exist_ok=True)
            for entry in sorted(os.listdir(self.fragments_path)):
                if entry.endswith(".blob") and entry[:-5].isdigit():
                    # Blob-tier stub (pilosa_tpu.tier): the data file
                    # left local disk, but the fragment must stay
                    # discoverable — Fragment.open recognizes the
                    # stub and opens in the blob state.
                    entry = entry[:-5]
                elif not entry.isdigit():
                    continue
                slice = int(entry)
                if slice in self.fragments:
                    continue
                frag = self._new_fragment(slice)
                frag.open()
                self.fragments[slice] = frag
            self._max_slice = max(self.fragments, default=0)

    def close(self) -> None:
        with self._mu:
            for frag in self.fragments.values():
                frag.close()
            self.fragments.clear()

    def _new_fragment(self, slice: int) -> Fragment:
        return Fragment(self.fragment_path(slice), self.index, self.frame,
                        self.name, slice, cache_type=self.cache_type,
                        cache_size=self.cache_size,
                        row_attr_store=self.row_attr_store,
                        stats=self.stats.with_tags(f"slice:{slice}"),
                        logger=self.logger, quarantine=self.quarantine)

    # -- fragments

    def fragment(self, slice: int) -> Optional[Fragment]:
        return self.fragments.get(slice)

    def create_fragment_if_not_exists(self, slice: int) -> Fragment:
        with self._mu:
            frag = self.fragments.get(slice)
            if frag is not None:
                return frag
            frag = self._new_fragment(slice)
            frag.open()
            # Announce only when the max slice grows (view.go:232-246).
            if slice > self._max_slice:
                self._max_slice = slice
                if self.on_create_slice is not None:
                    self.on_create_slice(slice)
            self.fragments[slice] = frag
            self.stats.count("maxSlice", 1)
            return frag

    def max_slice(self) -> int:
        with self._mu:
            return max(self._max_slice, max(self.fragments, default=0))

    # -- bit ops (route column → slice; view.go:265-283)

    def set_bit(self, row_id: int, column_id: int) -> bool:
        frag = self.create_fragment_if_not_exists(column_id // SLICE_WIDTH)
        return frag.set_bit(row_id, column_id)

    def clear_bit(self, row_id: int, column_id: int) -> bool:
        frag = self.create_fragment_if_not_exists(column_id // SLICE_WIDTH)
        return frag.clear_bit(row_id, column_id)

    def mutate_bits(self, row_ids: np.ndarray, column_ids: np.ndarray,
                    set: bool) -> np.ndarray:
        """Batched set/clear through the fragments' native batch engine:
        one stable argsort groups the ops by slice, one batched mutation
        per touched fragment. Returns a per-op changed bool array (WAL'd
        durability identical to the per-op path — fragment.set_bits)."""
        import numpy as _np
        rows = _np.asarray(row_ids, dtype=_np.uint64)
        cols = _np.asarray(column_ids, dtype=_np.uint64)
        changed = _np.zeros(len(rows), dtype=bool)
        if not len(rows):
            return changed
        slices = cols // _np.uint64(SLICE_WIDTH)
        order = _np.argsort(slices, kind="stable")
        srt = slices[order]
        bounds = _np.flatnonzero(srt[1:] != srt[:-1]) + 1
        starts = _np.concatenate(([0], bounds, [len(srt)]))
        w = _np.uint64(SLICE_WIDTH)
        for s, e in zip(starts[:-1].tolist(), starts[1:].tolist()):
            idx = order[s:e]
            frag = self.create_fragment_if_not_exists(int(srt[s]))
            op = frag.set_bits if set else frag.clear_bits
            ch_pos = op(rows[idx], cols[idx])
            if len(ch_pos):
                pos = rows[idx] * w + cols[idx] % w
                # Only the FIRST occurrence of a duplicated op changed
                # (per-op semantics: the repeat is an idempotent no-op).
                uniq, first = _np.unique(pos, return_index=True)
                hit = _np.isin(uniq, ch_pos, assume_unique=True)
                changed[idx[first[hit]]] = True
        return changed
