"""Frame: a named row-space within an index.

Reference: frame.go. Holds a views map (standard / inverse / time views), a
row attribute store, and options (rowLabel, inverseEnabled, cacheType,
cacheSize, timeQuantum) persisted as a protobuf ``.meta`` file
(frame.go:280-336). SetBit fans out to the standard view plus one view per
time-quantum unit (frame.go:446-485); the inverse view stores the transpose
(row/col swapped) so columns are row-addressable.
"""

from __future__ import annotations

import datetime as dt
import os
import threading
from dataclasses import dataclass
from typing import Optional

import numpy as np

from .. import SLICE_WIDTH
from ..errors import PilosaError, validate_label
from ..proto import internal_pb2 as pb
from ..storage import bsi
from ..storage import cache as cache_mod
from ..utils.arrays import group_by_key, sort_dedupe
from ..storage.attrs import AttrStore
from ..utils import logger as logger_mod
from ..utils import timequantum as tq
from ..utils.stats import NOP
from .view import (VIEW_INVERSE, VIEW_STANDARD, View, field_view_name,
                   is_field_view, is_inverse_view, is_valid_view)

DEFAULT_ROW_LABEL = "rowID"


@dataclass
class Field:
    """A BSI integer field of a frame: values in [min, max] stored as
    bit-plane rows in the ``field_<name>`` view (storage.bsi)."""
    name: str
    min: int = 0
    max: int = 0

    def __post_init__(self):
        validate_label(self.name)
        if self.max < self.min:
            raise PilosaError(
                f"field max ({self.max}) must be >= min ({self.min})")
        if bsi.bit_depth(self.min, self.max) > bsi.MAX_BIT_DEPTH:
            raise PilosaError("field range too wide (max 63 bits)")

    @property
    def bit_depth(self) -> int:
        return bsi.bit_depth(self.min, self.max)

    @property
    def view_name(self) -> str:
        return field_view_name(self.name)

    def encode(self) -> pb.FieldMeta:
        return pb.FieldMeta(Name=self.name, Min=self.min, Max=self.max)

    @staticmethod
    def decode(meta: pb.FieldMeta) -> "Field":
        return Field(name=meta.Name, min=meta.Min, max=meta.Max)

    def to_json(self) -> dict:
        return {"name": self.name, "min": self.min, "max": self.max}


@dataclass
class FrameOptions:
    row_label: str = DEFAULT_ROW_LABEL
    inverse_enabled: bool = False
    cache_type: str = cache_mod.DEFAULT_CACHE_TYPE
    cache_size: int = cache_mod.DEFAULT_CACHE_SIZE
    time_quantum: str = ""
    fields: Optional[list[Field]] = None

    def encode(self) -> pb.FrameMeta:
        return pb.FrameMeta(RowLabel=self.row_label,
                            InverseEnabled=self.inverse_enabled,
                            CacheType=self.cache_type,
                            CacheSize=self.cache_size,
                            TimeQuantum=self.time_quantum,
                            Fields=[f.encode() for f in self.fields or []])

    @staticmethod
    def decode(meta: pb.FrameMeta) -> "FrameOptions":
        return FrameOptions(row_label=meta.RowLabel or DEFAULT_ROW_LABEL,
                            inverse_enabled=meta.InverseEnabled,
                            cache_type=meta.CacheType
                            or cache_mod.DEFAULT_CACHE_TYPE,
                            cache_size=meta.CacheSize
                            or cache_mod.DEFAULT_CACHE_SIZE,
                            time_quantum=meta.TimeQuantum,
                            fields=[Field.decode(f)
                                    for f in meta.Fields] or None)


class Frame:
    def __init__(self, path: str, index: str, name: str,
                 options: Optional[FrameOptions] = None,
                 on_create_slice=None, stats=NOP, logger=logger_mod.NOP,
                 quarantine=None):
        self.logger = logger
        self.path = path
        self.index = index
        self.name = name
        self.options = options or FrameOptions()
        self.quarantine = quarantine  # holder's QuarantineRegistry
        self.views: dict[str, View] = {}
        self.row_attr_store = AttrStore(os.path.join(path, "attrs"))
        self.on_create_slice = on_create_slice
        self.stats = stats
        self._mu = threading.RLock()

    # -- lifecycle -----------------------------------------------------------

    @property
    def meta_path(self) -> str:
        return os.path.join(self.path, ".meta")

    def views_path(self) -> str:
        return os.path.join(self.path, "views")

    def open(self) -> None:
        with self._mu:
            os.makedirs(self.views_path(), exist_ok=True)
            self._load_meta()
            self._save_meta()
            self.row_attr_store.open()
            for entry in sorted(os.listdir(self.views_path())):
                if not is_valid_view(entry):
                    continue
                view = self._new_view(entry)
                view.open()
                self.views[entry] = view

    def close(self) -> None:
        with self._mu:
            for v in self.views.values():
                v.close()
            self.views.clear()
            self.row_attr_store.close()

    def _load_meta(self) -> None:
        try:
            with open(self.meta_path, "rb") as f:
                self.options = FrameOptions.decode(
                    pb.FrameMeta.FromString(f.read()))
        except FileNotFoundError:
            pass

    def _save_meta(self) -> None:
        blob = self.options.encode().SerializeToString()
        tmp = self.meta_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, self.meta_path)

    # -- options accessors ---------------------------------------------------

    @property
    def row_label(self) -> str:
        return self.options.row_label

    @property
    def inverse_enabled(self) -> bool:
        return self.options.inverse_enabled

    def time_quantum(self) -> str:
        return self.options.time_quantum

    def set_time_quantum(self, q: str) -> None:
        with self._mu:
            self.options.time_quantum = tq.parse_time_quantum(q)
            self._save_meta()

    # -- BSI integer fields (storage.bsi row layout) -------------------------

    def fields(self) -> list[Field]:
        with self._mu:
            return list(self.options.fields or [])

    def field(self, name: str) -> Optional[Field]:
        with self._mu:
            for f in self.options.fields or []:
                if f.name == name:
                    return f
            return None

    def create_field(self, field: Field) -> Field:
        """Register a field and persist it in the ``.meta`` protobuf.
        Idempotent when the (name, min, max) triple matches; a schema
        CHANGE for an existing name is an error (the stored planes
        would silently decode against the wrong base/depth)."""
        with self._mu:
            existing = self.field(field.name)
            if existing is not None:
                if (existing.min, existing.max) != (field.min, field.max):
                    raise PilosaError(
                        f"field already exists with different range:"
                        f" {field.name}")
                return existing
            if self.options.fields is None:
                self.options.fields = []
            self.options.fields.append(field)
            self._save_meta()
            return field

    def _field_view(self, field: Field) -> View:
        return self.create_view_if_not_exists(field.view_name)

    def set_field_value(self, field_name: str, column_id: int,
                        value: int) -> bool:
        """Point write of one column's integer value: existence bit +
        per-plane set/clear (a re-set value clears stale 1-planes).
        Returns whether any bit changed."""
        field = self.field(field_name)
        if field is None:
            raise PilosaError(f"field not found: {field_name}")
        if not field.min <= value <= field.max:
            raise PilosaError(
                f"value {value} out of range for field {field_name}"
                f" [{field.min}, {field.max}]")
        view = self._field_view(field)
        u = value - field.min
        changed = view.set_bit(bsi.EXISTS_ROW, column_id)
        for i in range(field.bit_depth):
            row = bsi.PLANE_ROW_OFFSET + i
            if (u >> i) & 1:
                if view.set_bit(row, column_id):
                    changed = True
            else:
                if view.clear_bit(row, column_id):
                    changed = True
        return changed

    def field_value(self, field_name: str, column_id: int
                    ) -> tuple[int, bool]:
        """(value, exists) readback of one column (debug/tests; queries
        go through the executor's bit-plane circuits)."""
        field = self.field(field_name)
        if field is None:
            raise PilosaError(f"field not found: {field_name}")
        view = self.view(field.view_name)
        if view is None:
            return 0, False
        frag = view.fragment(column_id // SLICE_WIDTH)
        if frag is None:
            return 0, False
        col = np.uint64(column_id)
        if col not in frag.row(bsi.EXISTS_ROW).bits():
            return 0, False
        u = 0
        for i in range(field.bit_depth):
            if col in frag.row(bsi.PLANE_ROW_OFFSET + i).bits():
                u |= 1 << i
        return u + field.min, True

    def import_field_values(self, field_name: str, column_ids,
                            values) -> None:
        """Bulk value import: group columns by slice, then per fragment
        batch-clear the zero planes of re-imported columns and bulk-add
        the existence row plus the one planes (an import is an absolute
        assignment, like SetFieldValue, not an OR)."""
        field = self.field(field_name)
        if field is None:
            raise PilosaError(f"field not found: {field_name}")
        cols = np.asarray(column_ids, dtype=np.uint64)
        vals = np.asarray(values, dtype=np.int64)
        if len(cols) != len(vals):
            raise ValueError("column/value length mismatch")
        if not len(cols):
            return
        if (int(vals.min()) < field.min
                or int(vals.max()) > field.max):
            raise PilosaError(
                f"value out of range for field {field_name}"
                f" [{field.min}, {field.max}]")
        # Duplicate columns: last occurrence wins (assignment
        # semantics) — np.unique keeps the FIRST, so reverse first.
        if len(cols) > 1:
            _, first_of_rev = np.unique(cols[::-1], return_index=True)
            keep = np.sort(len(cols) - 1 - first_of_rev)
            cols, vals = cols[keep], vals[keep]
        u = (vals - field.min).astype(np.uint64)
        depth = field.bit_depth
        view = self._field_view(field)
        W = np.uint64(SLICE_WIDTH)
        for slice, cs, us in group_by_key(cols // W, cols, u):
            frag = view.create_fragment_if_not_exists(slice)
            local = cs % W
            set_parts = [np.uint64(bsi.EXISTS_ROW) * W + local]
            clear_parts = []
            for i in range(depth):
                row = np.uint64(bsi.PLANE_ROW_OFFSET + i)
                on = (us >> np.uint64(i)) & np.uint64(1) == 1
                set_parts.append(row * W + local[on])
                clear_parts.append(row * W + local[~on])
            if clear_parts:
                clear = np.concatenate(clear_parts)
                if len(clear):
                    # Clear BEFORE the bulk add: import_positions ends
                    # with a snapshot, which then captures the clears.
                    frag.clear_positions(clear)
            frag.import_positions(
                sort_dedupe(np.concatenate(set_parts)))

    # -- views ---------------------------------------------------------------

    def _new_view(self, name: str) -> View:
        return View(os.path.join(self.views_path(), name), self.index,
                    self.name, name, cache_type=self.options.cache_type,
                    cache_size=self.options.cache_size,
                    row_attr_store=self.row_attr_store,
                    on_create_slice=self._announce_slice(name),
                    stats=self.stats.with_tags(f"view:{name}"),
                    logger=self.logger, quarantine=self.quarantine)

    def _announce_slice(self, view_name: str):
        if self.on_create_slice is None:
            return None
        inverse = is_inverse_view(view_name)

        def announce(slice: int):
            self.on_create_slice(slice, inverse)
        return announce

    def view(self, name: str) -> Optional[View]:
        return self.views.get(name)

    def create_view_if_not_exists(self, name: str) -> View:
        with self._mu:
            if not self.inverse_enabled and is_inverse_view(name):
                raise PilosaError("inverse views not enabled for frame")
            v = self.views.get(name)
            if v is None:
                v = self._new_view(name)
                v.open()
                self.views[name] = v
            return v

    def max_slice(self) -> int:
        # Field views are column-sharded like standard, and a pure
        # integer frame may hold bits ONLY there — the query slice
        # enumeration must cover both. (Time views fan out alongside
        # standard, so the standard view already bounds them.)
        best = 0
        # Snapshot: concurrent writers insert views under _mu and a
        # live dict iteration here would raise RuntimeError mid-query.
        for name, v in list(self.views.items()):
            if name == VIEW_STANDARD or is_field_view(name):
                best = max(best, v.max_slice())
        return best

    def max_inverse_slice(self) -> int:
        v = self.views.get(VIEW_INVERSE)
        return v.max_slice() if v else 0

    # -- bit ops (frame.go:446-527) ------------------------------------------

    def set_bit(self, view_name: str, row_id: int, col_id: int,
                t: Optional[dt.datetime] = None) -> bool:
        return self._mutate(view_name, row_id, col_id, t, set=True)

    def clear_bit(self, view_name: str, row_id: int, col_id: int,
                  t: Optional[dt.datetime] = None) -> bool:
        return self._mutate(view_name, row_id, col_id, t, set=False)

    def mutate_bits(self, view_name: str, row_ids, col_ids,
                    set: bool) -> "np.ndarray":
        """Batched timestamp-free set/clear on one view (the executor's
        SetBit-run fast path; timestamped ops stay per-op because the
        time-view fan-out is per-quantum). Returns per-op changed
        bools."""
        if not is_valid_view(view_name):
            raise PilosaError(f"invalid view: {view_name!r}")
        view = self.create_view_if_not_exists(view_name)
        return view.mutate_bits(row_ids, col_ids, set)

    def _mutate(self, view_name: str, row_id: int, col_id: int,
                t: Optional[dt.datetime], set: bool) -> bool:
        if not is_valid_view(view_name):
            raise PilosaError(f"invalid view: {view_name!r}")
        changed = False
        view = self.create_view_if_not_exists(view_name)
        op = view.set_bit if set else view.clear_bit
        if op(row_id, col_id):
            changed = True
        if t is None:
            return changed
        for subname in tq.views_by_time(view_name, t, self.time_quantum()):
            sub = self.create_view_if_not_exists(subname)
            op = sub.set_bit if set else sub.clear_bit
            if op(row_id, col_id):
                changed = True
        return changed

    # -- bulk import (frame.go:530-606) --------------------------------------

    def import_bits(self, row_ids, column_ids, timestamps=None,
                    views: str = None) -> None:
        """Group bits by (view, slice) — including time views and the
        inverse transpose — then bulk-import each fragment.

        ``views`` filters the fan-out: None = all, "standard" =
        standard + time views only, "inverse" = inverse views only.
        Pod-internal import legs use the filter because standard and
        inverse views of the same bit live on different pod processes
        (column-slice vs row-slice placement, parallel.pod)."""
        from .. import SLICE_WIDTH
        rows = np.asarray(row_ids, dtype=np.uint64)
        cols = np.asarray(column_ids, dtype=np.uint64)
        if len(rows) != len(cols):
            raise ValueError("row/column length mismatch")
        if timestamps is not None:
            timestamps = list(timestamps)
            if len(timestamps) != len(rows):
                raise ValueError("timestamp length mismatch")

        q = self.time_quantum()
        # data[(view, slice)] = list of (rows, cols) array chunks
        data: dict[tuple[str, int], list] = {}
        do_standard = views in (None, "standard")
        do_inverse = self.inverse_enabled and views in (None, "inverse")

        def put_arrays(view_names, rids_a, cids_a):
            # The bulk-import hot lane, shared by every view name that
            # receives the arrays (time fan-out sends the same bits to
            # up to 5 views). Fast path: pack (slice, position) into
            # one u64 key — ONE np.sort + dedupe then orders every
            # fragment's positions at once, so neither a group argsort
            # here nor a per-fragment re-sort in add_many happens.
            # Applies whenever rows fit 24 bits and slices 20 bits
            # (position < 2^44); wider ids take the generic group-by.
            if not len(rids_a):
                return
            W = np.uint64(SLICE_WIDTH)
            slices_a = cids_a // W
            if (int(rids_a.max()) < (1 << 24)
                    and int(slices_a.max()) < (1 << 20)):
                packed = sort_dedupe((slices_a << np.uint64(44))
                                     | (rids_a * W + cids_a % W))
                positions_all = packed & np.uint64((1 << 44) - 1)
                sl = packed >> np.uint64(44)
                b = np.flatnonzero(sl[1:] != sl[:-1]) + 1
                for s, e in zip(
                        np.concatenate(([0], b)).tolist(),
                        np.concatenate((b, [len(sl)])).tolist()):
                    pos_v = positions_all[s:e]
                    for vn in view_names:
                        data.setdefault((vn, int(sl[s])), []).append(
                            pos_v)
                return
            for slice, rs, cs in group_by_key(slices_a, rids_a, cids_a):
                pos_v = rs * W + cs % W
                for vn in view_names:
                    data.setdefault((vn, slice), []).append(pos_v)

        if timestamps is None:
            plain = np.ones(len(rows), dtype=bool)
        else:
            plain = np.array([t is None for t in timestamps], dtype=bool)
        if plain.any():
            r0, c0 = rows[plain], cols[plain]
            if do_standard:
                put_arrays([VIEW_STANDARD], r0, c0)
            if do_inverse:
                put_arrays([VIEW_INVERSE], c0, r0)  # transpose

        if not plain.all():
            # Timestamped bits fan out to per-quantum time views
            # (frame.go:538-573). View membership depends only on the
            # timestamp VALUE, so group by unique timestamp and fan
            # each group out array-at-a-time — time-series imports
            # carry few distinct timestamps across many bits, and the
            # old per-bit loop was the bulk-import long pole for them.
            by_ts: dict = {}
            for i in np.flatnonzero(~plain).tolist():
                ts = timestamps[i]
                # View names come from LOCAL datetime fields
                # (strftime in views_by_time), so group by those —
                # equal-instant aware datetimes in different zones
                # belong to different time views.
                key = (ts.replace(tzinfo=None)
                       if isinstance(ts, dt.datetime) else ts)
                by_ts.setdefault(key, []).append(i)
            for ts, ii in by_ts.items():
                idx = np.asarray(ii)
                r_ts, c_ts = rows[idx], cols[idx]
                if do_standard:
                    put_arrays(
                        tq.views_by_time(VIEW_STANDARD, ts, q)
                        + [VIEW_STANDARD], r_ts, c_ts)
                if do_inverse:
                    put_arrays(
                        tq.views_by_time(VIEW_INVERSE, ts, q)
                        + [VIEW_INVERSE], c_ts, r_ts)  # transpose

        for (view_name, slice), chunks in sorted(data.items()):
            view = self.create_view_if_not_exists(view_name)
            frag = view.create_fragment_if_not_exists(slice)
            frag.import_positions(
                chunks[0] if len(chunks) == 1 else np.concatenate(chunks))

    def import_slice_positions(self, slice: int,
                               positions: np.ndarray) -> None:
        """Standard-view bulk import of ONE slice's pre-sorted
        slice-local positions — the rawimport-v2 wire lane. The caller
        owns the sort/dedupe and the no-inverse/no-timestamp
        preconditions (the handler reconstructs (row, col) pairs and
        calls import_bits when the frame needs the transpose)."""
        view = self.create_view_if_not_exists(VIEW_STANDARD)
        frag = view.create_fragment_if_not_exists(slice)
        frag.import_positions(positions)
