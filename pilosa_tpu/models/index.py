"""Index: a named database of frames with a column attribute store.

Reference: index.go. Persists ``.meta`` (columnLabel, default timeQuantum);
``max_slice`` is the max over frames' standard views joined with the
``remote_max_slice`` learned from peers (index.go:251-297); CreateFrame
applies option defaulting (index.go:378-432).
"""

from __future__ import annotations

import os
import shutil
import threading
from dataclasses import dataclass
from typing import Optional

from ..errors import (FrameExistsError, PilosaError, validate_label,
                      validate_name)
from ..proto import internal_pb2 as pb
from ..storage.attrs import AttrStore
from ..utils import logger as logger_mod
from ..utils import timequantum as tq
from ..utils.stats import NOP
from .frame import Frame, FrameOptions

DEFAULT_COLUMN_LABEL = "columnID"


@dataclass
class IndexOptions:
    column_label: str = DEFAULT_COLUMN_LABEL
    time_quantum: str = ""

    def encode(self) -> pb.IndexMeta:
        return pb.IndexMeta(ColumnLabel=self.column_label,
                            TimeQuantum=self.time_quantum)

    @staticmethod
    def decode(meta: pb.IndexMeta) -> "IndexOptions":
        return IndexOptions(
            column_label=meta.ColumnLabel or DEFAULT_COLUMN_LABEL,
            time_quantum=meta.TimeQuantum)


class Index:
    def __init__(self, path: str, name: str,
                 options: Optional[IndexOptions] = None,
                 on_create_slice=None, stats=NOP, logger=logger_mod.NOP,
                 quarantine=None):
        validate_name(name)
        self.logger = logger
        self.path = path
        self.name = name
        self.options = options or IndexOptions()
        self.quarantine = quarantine  # holder's QuarantineRegistry
        self.frames: dict[str, Frame] = {}
        self.column_attr_store = AttrStore(os.path.join(path, ".data"))
        self.on_create_slice = on_create_slice
        self.stats = stats
        self.remote_max_slice = 0
        self.remote_max_inverse_slice = 0
        self._mu = threading.RLock()

    # -- lifecycle

    @property
    def meta_path(self) -> str:
        return os.path.join(self.path, ".meta")

    def open(self) -> None:
        with self._mu:
            os.makedirs(self.path, exist_ok=True)
            self._load_meta()
            self._save_meta()
            self.column_attr_store.open()
            for entry in sorted(os.listdir(self.path)):
                full = os.path.join(self.path, entry)
                if not os.path.isdir(full):
                    continue
                frame = self._new_frame(entry, FrameOptions())
                frame.open()
                self.frames[entry] = frame
            self.stats.gauge("frameN", len(self.frames))

    def close(self) -> None:
        with self._mu:
            for f in self.frames.values():
                f.close()
            self.frames.clear()
            self.column_attr_store.close()

    def _load_meta(self) -> None:
        try:
            with open(self.meta_path, "rb") as f:
                self.options = IndexOptions.decode(
                    pb.IndexMeta.FromString(f.read()))
        except FileNotFoundError:
            pass

    def _save_meta(self) -> None:
        tmp = self.meta_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(self.options.encode().SerializeToString())
        os.replace(tmp, self.meta_path)

    # -- options

    @property
    def column_label(self) -> str:
        return self.options.column_label

    def time_quantum(self) -> str:
        return self.options.time_quantum

    def set_time_quantum(self, q: str) -> None:
        with self._mu:
            self.options.time_quantum = tq.parse_time_quantum(q)
            self._save_meta()

    # -- slices

    def max_slice(self) -> int:
        with self._mu:
            local = max((f.max_slice() for f in self.frames.values()),
                        default=0)
            return max(local, self.remote_max_slice)

    def max_inverse_slice(self) -> int:
        with self._mu:
            local = max((f.max_inverse_slice() for f in self.frames.values()),
                        default=0)
            return max(local, self.remote_max_inverse_slice)

    def set_remote_max_slice(self, n: int) -> None:
        with self._mu:
            self.remote_max_slice = max(self.remote_max_slice, n)

    def set_remote_max_inverse_slice(self, n: int) -> None:
        with self._mu:
            self.remote_max_inverse_slice = max(
                self.remote_max_inverse_slice, n)

    # -- frames

    def frame(self, name: str) -> Optional[Frame]:
        return self.frames.get(name)

    def frame_path(self, name: str) -> str:
        return os.path.join(self.path, name)

    def _new_frame(self, name: str, options: FrameOptions) -> Frame:
        return Frame(self.frame_path(name), self.name, name, options=options,
                     on_create_slice=self.on_create_slice,
                     stats=self.stats.with_tags(f"frame:{name}"),
                     logger=self.logger, quarantine=self.quarantine)

    def create_frame(self, name: str, options: Optional[FrameOptions] = None
                     ) -> Frame:
        with self._mu:
            if name in self.frames:
                raise FrameExistsError(name)
            return self._create_frame(name, options)

    def create_frame_if_not_exists(self, name: str,
                                   options: Optional[FrameOptions] = None
                                   ) -> Frame:
        with self._mu:
            f = self.frames.get(name)
            if f is not None:
                return f
            return self._create_frame(name, options)

    def _create_frame(self, name: str, options: Optional[FrameOptions]
                      ) -> Frame:
        validate_name(name)
        options = options or FrameOptions()
        validate_label(options.row_label)
        # Default the frame's time quantum from the index (index.go:419-427).
        if not options.time_quantum and self.time_quantum():
            options.time_quantum = self.time_quantum()
        tq.parse_time_quantum(options.time_quantum)
        if options.cache_type not in ("lru", "ranked"):
            raise PilosaError(f"invalid cache type: {options.cache_type!r}")
        frame = self._new_frame(name, options)
        frame.open()
        self.frames[name] = frame
        self.stats.count("frameN", 1)
        return frame

    def delete_frame(self, name: str) -> None:
        with self._mu:
            f = self.frames.pop(name, None)
            if f is not None:
                f.close()
            shutil.rmtree(self.frame_path(name), ignore_errors=True)
