"""Holder: root container for all indexes under one data directory.

Reference: holder.go. Scans the data dir on open, exposes
Index/Frame/View/Fragment navigation (holder.go:177-322), the schema
summary (holder.go:154-171), and cache flushing (the server runtime runs
the 1-minute flush loop; holder.go:324-358).
"""

from __future__ import annotations

import os
import shutil
import threading
from typing import Optional

from ..errors import IndexExistsError, validate_name
from ..storage.integrity import QuarantineRegistry
from ..utils import logger as logger_mod
from ..utils.stats import NOP
from .index import Index, IndexOptions


class Holder:
    def __init__(self, path: str, on_create_slice=None, stats=NOP,
                 logger=logger_mod.NOP):
        self.path = path
        self.indexes: dict[str, Index] = {}
        self.on_create_slice = on_create_slice  # fn(index, slice, inverse)
        self.stats = stats
        self.logger = logger
        # Storage integrity (storage.integrity): per-holder registry of
        # quarantined fragments — the executor's read path consults it,
        # /debug/integrity lists it, and the repairer drains it.
        self.quarantine = QuarantineRegistry()
        # Tiered storage (pilosa_tpu.tier): the TierManager when the
        # [tier] config enables it (server.open wires it), else None.
        # The executor consults tier_blocked alongside the quarantine
        # registry when deciding whether to serve a slice locally.
        self.tier = None
        self._mu = threading.RLock()

    # -- lifecycle

    def open(self) -> None:
        with self._mu:
            self.logger.printf("open holder path: %s", self.path)
            os.makedirs(self.path, exist_ok=True)
            for entry in sorted(os.listdir(self.path)):
                full = os.path.join(self.path, entry)
                if not os.path.isdir(full):
                    continue
                try:
                    validate_name(entry)
                except Exception:
                    continue
                self.logger.printf("opening index: %s", entry)
                idx = self._new_index(entry, IndexOptions())
                idx.open()
                self.indexes[entry] = idx
            self.stats.gauge("indexN", len(self.indexes))

    def close(self) -> None:
        with self._mu:
            for idx in self.indexes.values():
                idx.close()
            self.indexes.clear()

    # -- index CRUD

    def index_path(self, name: str) -> str:
        return os.path.join(self.path, name)

    def _new_index(self, name: str, options: IndexOptions) -> Index:
        announce = None
        if self.on_create_slice is not None:
            holder = self

            def announce(slice, inverse, _name=name):
                holder.on_create_slice(_name, slice, inverse)
        return Index(self.index_path(name), name, options=options,
                     on_create_slice=announce,
                     stats=self.stats.with_tags(f"index:{name}"),
                     logger=self.logger, quarantine=self.quarantine)

    def index(self, name: str) -> Optional[Index]:
        return self.indexes.get(name)

    def create_index(self, name: str,
                     options: Optional[IndexOptions] = None) -> Index:
        with self._mu:
            if name in self.indexes:
                raise IndexExistsError(name)
            return self._create_index(name, options)

    def create_index_if_not_exists(self, name: str,
                                   options: Optional[IndexOptions] = None
                                   ) -> Index:
        with self._mu:
            idx = self.indexes.get(name)
            if idx is not None:
                return idx
            return self._create_index(name, options)

    def _create_index(self, name: str, options) -> Index:
        validate_name(name)
        idx = self._new_index(name, options or IndexOptions())
        idx.open()
        self.indexes[name] = idx
        self.stats.count("indexN", 1)
        return idx

    def delete_index(self, name: str) -> None:
        with self._mu:
            idx = self.indexes.pop(name, None)
            if idx is not None:
                idx.close()
            shutil.rmtree(self.index_path(name), ignore_errors=True)

    # -- navigation (holder.go:177-322)

    def frame(self, index: str, name: str):
        idx = self.index(index)
        return idx.frame(name) if idx else None

    def view(self, index: str, frame: str, name: str):
        f = self.frame(index, frame)
        return f.view(name) if f else None

    def fragment(self, index: str, frame: str, view: str, slice: int):
        v = self.view(index, frame, view)
        return v.fragment(slice) if v else None

    # -- schema (holder.go:154-171)

    def schema(self) -> list[dict]:
        with self._mu:
            out = []
            for name in sorted(self.indexes):
                idx = self.indexes[name]
                frames = []
                for fname in sorted(idx.frames):
                    frame = idx.frames[fname]
                    entry = {
                        "name": fname,
                        "views": [{"name": vn}
                                  for vn in sorted(frame.views)],
                    }
                    fields = frame.fields()
                    if fields:
                        entry["fields"] = [f.to_json() for f in fields]
                    frames.append(entry)
                out.append({"name": name, "frames": frames})
            return out

    def max_slices(self) -> dict[str, int]:
        return {name: idx.max_slice()
                for name, idx in self.indexes.items()}

    def max_inverse_slices(self) -> dict[str, int]:
        return {name: idx.max_inverse_slice()
                for name, idx in self.indexes.items()}

    def flush_caches(self) -> None:
        """Flush all fragment TopN caches (holder.go:324-358)."""
        with self._mu:
            for idx in self.indexes.values():
                for frame in idx.frames.values():
                    for view in frame.views.values():
                        for frag in view.fragments.values():
                            frag.flush_cache()

    def tier_blocked(self, index: str, slice: int) -> bool:
        """True when a blob-tier fragment of (index, slice) cannot be
        fetched back from the blob store — reads must not be served
        locally (the tier-side analogue of quarantine.slice_blocked;
        the executor consults both)."""
        tier = self.tier
        return tier is not None and tier.slice_blocked(index, slice)

    def iter_fragments(self) -> list:
        """A point-in-time list of every open fragment — the scrub
        walk's snapshot (storage.scrub) and the integrity coverage
        summary's (/debug/integrity). A list, not a generator: the
        walker must not hold the holder lock for a whole paced pass."""
        with self._mu:
            return [frag
                    for idx in self.indexes.values()
                    for frame in idx.frames.values()
                    for view in frame.views.values()
                    for frag in view.fragments.values()]
