"""Cold-start warmup: background-compile the serving program catalogue.

The first real device query otherwise pays the whole cold chain —
backend init through the tunnel, mesh construction, and the
trace+compile of each serving program — measured at 5.4 s on the
canonical pass (VERDICT weak #2). At server start this lane compiles
the **unified program catalogue** (parallel.programs.CATALOGUE — the
count fold, the batched multi-Count form, TopN exact + filtered, the
materializing fold, the BSI comparison circuit, and the fused
multi-op-tree program) against dummy all-zero slabs on a daemon
thread.

Shapes are keyed by the holder's ACTUAL max-slice bucket at fragment
load (parallel.programs.slice_bucket over the open indexes), not a
hardcoded device-count shape: every query whose slice count lands in
the same bucket — which is every query until the index doubles past
it — hits the warmed compilation. Combined with the persistent XLA
compile cache (mesh.arm_compile_cache, defaulted under the holder
data dir by the server) the warm path is a disk read, and the first
device query after restart stops paying seconds.

XLA compiles are shape-keyed, so an unusual query shape (an unseen
candidate-row count, a new expression structure) can still compile
later — the warmup removes the dominant cold cost, not every possible
trace.

State is exposed at ``/status`` (``pending → running → done``;
``disabled`` when the mesh is off or unavailable, ``failed`` carries
the error) including per-program coverage: which catalogue programs
compiled, against which bucket. Gated by PILOSA_TPU_WARMUP (default
on; tests disable it the way they disable the cost model).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional


def warmup_enabled() -> bool:
    return os.environ.get("PILOSA_TPU_WARMUP", "1") != "0"


class Warmup:
    """Compile the serving program catalogue on a background thread."""

    def __init__(self, executor, logger=None):
        from ..utils import logger as logger_mod
        self.executor = executor
        self.logger = logger or logger_mod.NOP
        self.state = "pending"
        self.error = ""
        self.compiled: list[str] = []
        self.bucket: Optional[int] = None
        self.elapsed_s: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="pilosa-warmup",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def wait(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def to_json(self) -> dict:
        from ..parallel import programs
        catalogue = list(programs.CATALOGUE)
        return {"state": self.state, "compiled": list(self.compiled),
                "error": self.error or None,
                "bucket": self.bucket,
                "coverage": {
                    "warmed": len(self.compiled),
                    "programs": len(catalogue),
                    "missing": [p for p in catalogue
                                if p not in self.compiled]},
                "elapsedS": (round(self.elapsed_s, 3)
                             if self.elapsed_s is not None else None)}

    def _holder_max_slices(self) -> int:
        """Slice count the open holder actually serves (max over
        indexes of max_slice+1) — what the first real queries will
        fan out over."""
        n = 0
        holder = getattr(self.executor, "holder", None)
        if holder is None:
            return n
        try:
            for idx in dict(holder.indexes).values():
                n = max(n, idx.max_slice() + 1)
        except Exception:  # noqa: BLE001 - holder may be mid-open
            pass
        return n

    def _prime_planner(self) -> None:
        """Hand the planner its cost constants before the first query:
        the persisted per-machine calibration when one exists, the
        committed defaults otherwise. Without this the planner's
        placement decisions sit out until the first _device_pays call
        builds the calibrated model."""
        planner = getattr(self.executor, "planner", None)
        if planner is None or planner.calibration is not None:
            return
        try:
            from ..parallel import costmodel
            planner.calibration = costmodel.default_calibration()
        except Exception:  # noqa: BLE001 - placement hints are optional
            pass

    # -- worker --------------------------------------------------------------

    def _run(self) -> None:
        t0 = time.monotonic()
        self.state = "running"
        self._prime_planner()
        try:
            mesh = self.executor._mesh_or_none()
            if mesh is None:
                self.state = "disabled"
                return
            import numpy as np

            from ..ops.packed import WORDS_PER_SLICE
            from ..parallel import mesh as mesh_mod
            from ..parallel import programs
            n_dev = mesh.shape[mesh_mod.AXIS_SLICES]
            self.bucket = programs.slice_bucket(
                self._holder_max_slices(), n_dev)
            S = self.bucket

            def slab():
                return mesh_mod.shard_slices(
                    mesh, np.zeros((S, WORDS_PER_SLICE), np.uint32))

            a, b = slab(), slab()
            rows = None

            def rows_block():
                nonlocal rows
                if rows is None:
                    rows = mesh_mod.shard_slices(
                        mesh, np.zeros((S, 4, WORDS_PER_SLICE),
                                       np.uint32))
                return rows

            steps = {
                "count_fold": lambda: mesh_mod.count_expr_sharded(
                    mesh, ("and", ("leaf", 0), ("leaf", 1)), [a, b]),
                "count_batch": lambda: mesh_mod.count_exprs_sharded(
                    mesh, (("leaf", 0),
                           ("and", ("leaf", 0), ("leaf", 1))), [a, b]),
                "topn_exact": lambda: mesh_mod.topn_exact_sharded(
                    mesh, ("leaf", 0), rows_block(), [a]),
                "topn_filtered": lambda: mesh_mod.topn_filtered_sharded(
                    mesh, ("leaf", 0), rows_block(), [a], threshold=2),
                "topn_topk": lambda: mesh_mod.topn_topk_sharded(
                    mesh, None, rows_block(), [], k=2),
                "materialize": lambda: mesh_mod.materialize_expr_sharded(
                    mesh, ("or", ("leaf", 0), ("leaf", 1)), [a, b]),
                "bsi_compare_select": lambda: mesh_mod.bsi_range_sharded(
                    mesh, "<", 5, 8,
                    [a] + [slab() for _ in range(8)]),
                "fused_tree": lambda: mesh_mod.fused_tree_sharded(
                    mesh, (("and", ("leaf", 0), ("leaf", 1)),),
                    [(("leaf", 0), 4)], [a, b], [rows_block()]),
            }
            for name in programs.CATALOGUE:
                if self._stop.is_set():
                    break
                step = steps.get(name)
                if step is None:
                    continue
                step()
                self.compiled.append(name)
            self.state = "done"
            self.elapsed_s = time.monotonic() - t0
            self.logger.printf(
                "warmup: compiled %s at bucket %d in %.2fs",
                ",".join(self.compiled), S, self.elapsed_s)
        except Exception as e:  # noqa: BLE001 - warmup must never kill serving
            self.state = "failed"
            self.error = f"{type(e).__name__}: {e}"
            self.elapsed_s = time.monotonic() - t0
            self.logger.printf("warmup failed: %s", self.error)
