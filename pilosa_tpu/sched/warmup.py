"""Cold-start warmup: background-compile the hot XLA programs.

The first real device query otherwise pays the whole cold chain —
backend init through the tunnel, mesh construction, and the
trace+compile of each serving program — measured in seconds (round-5
VERDICT standing complaint). At server start this lane compiles the
three hot programs against dummy (all-zero) slabs on a daemon thread:

- the fused count fold (``mesh.count_expr_sharded`` — Count and the
  batched multi-Count lane share its cache),
- the TopN exact-count program (``mesh.topn_exact_sharded``), and
- the BSI comparison circuit (``mesh.bsi_range_sharded`` over
  ``ops.kernels.bsi_compare_select``).

XLA compiles are shape-keyed, so an unusual query shape can still
compile later — the warmup removes the dominant cold cost (backend +
mesh init + the base program set), not every possible trace.

State is exposed at ``/status`` (``pending → running → done``;
``disabled`` when the mesh is off or unavailable, ``failed`` carries
the error). Gated by PILOSA_TPU_WARMUP (default on; tests disable it
the way they disable the cost model).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional


def warmup_enabled() -> bool:
    return os.environ.get("PILOSA_TPU_WARMUP", "1") != "0"


class Warmup:
    """Compile the hot serving programs on a background thread."""

    PROGRAMS = ("count_fold", "topn_exact", "bsi_compare_select")

    def __init__(self, executor, logger=None):
        from ..utils import logger as logger_mod
        self.executor = executor
        self.logger = logger or logger_mod.NOP
        self.state = "pending"
        self.error = ""
        self.compiled: list[str] = []
        self.elapsed_s: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="pilosa-warmup",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def wait(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def to_json(self) -> dict:
        return {"state": self.state, "compiled": list(self.compiled),
                "error": self.error or None,
                "elapsedS": (round(self.elapsed_s, 3)
                             if self.elapsed_s is not None else None)}

    # -- worker --------------------------------------------------------------

    def _run(self) -> None:
        t0 = time.monotonic()
        self.state = "running"
        try:
            mesh = self.executor._mesh_or_none()
            if mesh is None:
                self.state = "disabled"
                return
            import numpy as np

            from ..ops.packed import WORDS_PER_SLICE
            from ..parallel import mesh as mesh_mod
            n_dev = mesh.shape[mesh_mod.AXIS_SLICES]

            def slab():
                return mesh_mod.shard_slices(
                    mesh, np.zeros((n_dev, WORDS_PER_SLICE), np.uint32))

            a, b = slab(), slab()
            if not self._stop.is_set():
                mesh_mod.count_expr_sharded(
                    mesh, ("and", ("leaf", 0), ("leaf", 1)), [a, b])
                self.compiled.append("count_fold")
            if not self._stop.is_set():
                rows = mesh_mod.shard_slices(
                    mesh, np.zeros((n_dev, 4, WORDS_PER_SLICE),
                                   np.uint32))
                mesh_mod.topn_exact_sharded(mesh, ("leaf", 0), rows,
                                            [a])
                self.compiled.append("topn_exact")
            if not self._stop.is_set():
                depth = 8  # exists row + 8 value planes
                planes = [a] + [slab() for _ in range(depth)]
                mesh_mod.bsi_range_sharded(mesh, "<", 5, depth, planes)
                self.compiled.append("bsi_compare_select")
            self.state = "done"
            self.elapsed_s = time.monotonic() - t0
            self.logger.printf(
                "warmup: compiled %s in %.2fs",
                ",".join(self.compiled), self.elapsed_s)
        except Exception as e:  # noqa: BLE001 - warmup must never kill serving
            self.state = "failed"
            self.error = f"{type(e).__name__}: {e}"
            self.elapsed_s = time.monotonic() - t0
            self.logger.printf("warmup failed: %s", self.error)
