"""Multi-tenant QoS: the tenant as a first-class scheduling and
accounting principal (ROADMAP item 5's remaining half).

A **tenant is an index**: the natural isolation boundary in this data
model — every query, import, and cache entry already names one. This
module turns that name into policy, threaded through every serving
layer:

- **Weighted lanes** (sched.admission): within each read/write/admin
  lane, tenants schedule by stride over their configured weight — a
  second stride level under the lane one, so an aggressive tenant's
  backlog cannot starve a quiet tenant's queue position. Per-tenant
  concurrency caps bound how many slots one tenant may hold; per-
  tenant queue quotas bound its waiters, and overflow 429s (with a
  per-tenant-lane Retry-After) only the offending tenant.
- **Slow-query kill policy** (``TenantRegistry.install`` →
  ``ctx.cost_policy``): per-tenant ceilings over the LIVE cost ledger
  (container-op units, device bytes, wall ms — obs.accounting, the
  per-(op, operand-kind) currency of arXiv:1709.07821) are checked at
  every cooperative checkpoint (``ctx.check()`` — the stage
  boundaries). A breach cancels the query with ``killed_by`` set, so
  every layer raises QueryKilledError (HTTP 402 +
  ``X-Pilosa-Killed-By: cost-policy``), and broadcasts the existing
  CancelQueryMessage so remote legs die cluster-wide.
- **Penalty box**: each kill adds 1 to a decaying score (half-life
  ``penalty_half_life_s``); the tenant's effective stride weight is
  demoted by ``2^-score`` — repeat offenders drain to a trickle and
  recover automatically as the score decays. No operator action, no
  permanent state.
- **Chargeback**: per-tenant roll-ups of the cost ledger and latency
  histograms (``pilosa_tenant_*``, bounded label set), per-tenant SLO
  burn (obs.slo.TenantSLOTracker), and ``GET /debug/tenants``.

Tenant identity rides cluster fan-out legs as ``X-Pilosa-Tenant``
(the X-Pilosa-Deadline pattern): forwarded legs bypass admission but
schedule their device work, account their costs, and enforce their
ceilings under the same principal.

Configured via the ``[tenants]`` TOML table / ``PILOSA_TENANTS`` /
``--tenants`` (utils.config.parse_tenant_table — loud validation;
the ``default`` entry is what unknown tenants ride).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Optional

from ..errors import QueryKilledError
from ..obs import metrics as obs_metrics
from ..utils.config import DEFAULT_TENANT  # noqa: F401  (re-export)

KILLED_BY_HEADER = "X-Pilosa-Killed-By"
KILL_POLICY = "cost-policy"

# Built-in default policy: weight 1, no caps, no ceilings — exactly
# the pre-tenant behavior for every tenant until an operator says
# otherwise.
_DEFAULTS = {"weight": 1.0, "concurrency": 0, "queue_depth": 0,
             "max_container_ops": 0, "max_device_bytes": 0,
             "max_wall_s": 0.0, "cache_share": 1.0}

DEFAULT_PENALTY_HALF_LIFE_S = 30.0
# Scores below this read as "out of the box" (full weight restored,
# state dropped): 2^-0.05 demotes weight by ~3%, i.e. noise.
_PENALTY_FLOOR = 0.05

# TOML-form key aliases, so a registry built straight from a raw
# table (tests, embedders) means the same thing as one built from
# parse_tenant_table output. Unknown keys raise — a silently-ignored
# quota is an isolation hole, not a default.
_KEY_ALIASES = {"queue-depth": "queue_depth",
                "max-container-ops": "max_container_ops",
                "max-device-bytes": "max_device_bytes",
                "max-wall": "max_wall_s",
                "cache-share": "cache_share"}


class TenantPolicy:
    """One tenant's immutable QoS knobs (see _DEFAULTS for units:
    0 = unlimited everywhere; cache_share is the fraction of each
    result-cache budget this tenant may occupy)."""

    __slots__ = ("name", "weight", "concurrency", "queue_depth",
                 "max_container_ops", "max_device_bytes", "max_wall_s",
                 "cache_share")

    def __init__(self, name: str, entry: Optional[dict] = None,
                 base: Optional["TenantPolicy"] = None):
        self.name = name
        src = dict(_DEFAULTS)
        if base is not None:
            for k in _DEFAULTS:
                src[k] = getattr(base, k)
        for k, v in (entry or {}).items():
            k = _KEY_ALIASES.get(k, k)
            if k not in _DEFAULTS:
                raise ValueError(
                    f"tenant {name}: unknown policy key {k!r}")
            if k == "max_wall_s" and isinstance(v, str):
                from ..utils.config import parse_duration
                v = parse_duration(v)
            src[k] = v
        for k in _DEFAULTS:
            setattr(self, k, src[k])

    def has_ceilings(self) -> bool:
        return bool(self.max_container_ops or self.max_device_bytes
                    or self.max_wall_s)

    def to_json(self) -> dict:
        return {k: getattr(self, k) for k in _DEFAULTS}


class _TenantState:
    __slots__ = ("score", "stamp", "kills", "sheds")

    def __init__(self):
        self.score = 0.0
        self.stamp = time.monotonic()
        self.kills = 0
        self.sheds = 0


class TenantRegistry:
    """Tenant → policy resolution + penalty box + kill policy.

    ``table`` is utils.config.parse_tenant_table output ({name:
    normalized entry}); named tenants inherit unset knobs from the
    ``default`` entry, unknown tenants ride the default policy
    wholesale — but every tenant schedules as its OWN stride
    principal (two quiet tenants on the default policy still get
    separate queue positions and separate chargeback rows).

    ``kill_broadcast`` (set by the server once its broadcaster is up)
    fans a cost-policy kill cluster-wide via CancelQueryMessage.
    """

    def __init__(self, table: Optional[dict] = None,
                 penalty_half_life_s: float = DEFAULT_PENALTY_HALF_LIFE_S,
                 node: str = ""):
        table = dict(table or {})
        self._default = TenantPolicy(DEFAULT_TENANT,
                                     table.pop(DEFAULT_TENANT, None))
        self._policies = {name: TenantPolicy(name, entry,
                                             base=self._default)
                          for name, entry in table.items()}
        self.penalty_half_life_s = max(0.001, penalty_half_life_s)
        self.node = node
        self.kill_broadcast: Optional[Callable[[str], None]] = None
        self._mu = threading.Lock()
        self._state: dict[str, _TenantState] = {}

    # -- resolution ----------------------------------------------------------

    def resolve(self, tenant: str) -> str:
        return tenant or DEFAULT_TENANT

    def policy(self, tenant: str) -> TenantPolicy:
        return self._policies.get(self.resolve(tenant), self._default)

    def known(self) -> list[str]:
        return sorted([DEFAULT_TENANT, *self._policies])

    # -- penalty box ---------------------------------------------------------

    def _decayed_locked(self, st: _TenantState,
                        now: float) -> float:
        dt = now - st.stamp
        if dt > 0 and st.score:
            st.score *= math.pow(0.5, dt / self.penalty_half_life_s)
            st.stamp = now
            if st.score < _PENALTY_FLOOR:
                st.score = 0.0
        return st.score

    def penalty_score(self, tenant: str) -> float:
        tenant = self.resolve(tenant)
        now = time.monotonic()
        with self._mu:
            st = self._state.get(tenant)
            return self._decayed_locked(st, now) if st else 0.0

    def effective_weight(self, tenant: str) -> float:
        """The stride weight admission schedules this tenant at: the
        configured weight demoted by the decaying penalty score —
        2^-score, so one kill halves it and recovery is automatic."""
        base = self.policy(tenant).weight
        score = self.penalty_score(tenant)
        return base * math.pow(0.5, score) if score else base

    def note_kill(self, tenant: str) -> None:
        tenant = self.resolve(tenant)
        now = time.monotonic()
        with self._mu:
            st = self._state.setdefault(tenant, _TenantState())
            self._decayed_locked(st, now)
            st.score += 1.0
            st.kills += 1
            score = st.score
        obs_metrics.TENANT_KILLS.labels(tenant).inc()
        obs_metrics.TENANT_PENALTY.labels(tenant).set(round(score, 4))

    def note_shed(self, tenant: str, lane: str) -> None:
        tenant = self.resolve(tenant)
        with self._mu:
            self._state.setdefault(tenant, _TenantState()).sheds += 1
        obs_metrics.TENANT_SHED.labels(tenant, lane).inc()

    # -- slow-query kill policy ----------------------------------------------

    def install(self, ctx) -> None:
        """Bind this registry's cost policy to a QueryContext: resolve
        the tenant and, when its policy has ceilings, attach the
        stage-boundary checker. Cheap for the common (no-ceiling)
        tenant: nothing is attached and ctx.check() stays two
        attribute reads."""
        ctx.tenant = self.resolve(getattr(ctx, "tenant", ""))
        if self.policy(ctx.tenant).has_ceilings():
            ctx.cost_policy = self._check_cost

    def _breach(self, ctx) -> str:
        """The ceiling this query is past, or ''. Wall is checked
        against elapsed (distinct from the client deadline: the
        POLICY's bound, not the caller's patience); the ledger
        ceilings read the live per-node QueryCost."""
        pol = self.policy(getattr(ctx, "tenant", ""))
        if pol.max_wall_s and ctx.elapsed() > pol.max_wall_s:
            return (f"wall {ctx.elapsed() * 1e3:.0f}ms >"
                    f" {pol.max_wall_s * 1e3:.0f}ms")
        cost = getattr(ctx, "cost", None)
        if cost is None:
            return ""
        if pol.max_container_ops:
            ops = sum(cost.container_ops.values())
            if ops > pol.max_container_ops:
                return (f"container ops {ops} >"
                        f" {pol.max_container_ops}")
        if (pol.max_device_bytes
                and cost.device_bytes > pol.max_device_bytes):
            return (f"device bytes {cost.device_bytes} >"
                    f" {pol.max_device_bytes}")
        return ""

    def _check_cost(self, ctx) -> None:
        detail = self._breach(ctx)
        if not detail:
            return
        tenant = getattr(ctx, "tenant", "") or DEFAULT_TENANT
        # Kill: mark BEFORE cancel so every other thread's check()
        # already raises the killed (not plain-cancelled) form.
        ctx.killed_by = KILL_POLICY
        ctx.cancel(reason=f"{KILL_POLICY}: tenant {tenant} {detail}")
        self.note_kill(tenant)
        # Cluster-wide: the same CancelQueryMessage an operator
        # DELETE rides — peers cancel the legs registered under this
        # id. Best-effort (a dead broadcaster must not mask the
        # kill); fired from whichever node detects the breach first,
        # coordinator or forwarded leg.
        fan = self.kill_broadcast
        if fan is not None:
            try:
                fan(ctx.id)
            except Exception:  # noqa: BLE001 - best-effort fan-out
                pass
        raise QueryKilledError(
            f"query {ctx.id} killed by {KILL_POLICY}:"
            f" tenant {tenant} {detail}")

    # -- exposition ----------------------------------------------------------

    def snapshot(self) -> dict:
        """Per-tenant policy + penalty state for /debug/tenants.
        Covers every CONFIGURED tenant plus any tenant with live
        penalty state (an unknown tenant that got itself killed must
        not vanish from the report)."""
        now = time.monotonic()
        with self._mu:
            names = set(self._policies) | set(self._state) \
                | {DEFAULT_TENANT}
            out = {}
            for name in sorted(names):
                pol = self._policies.get(name, self._default)
                st = self._state.get(name)
                score = self._decayed_locked(st, now) if st else 0.0
                out[name] = {
                    "policy": pol.to_json(),
                    "effectiveWeight": round(
                        pol.weight * math.pow(0.5, score)
                        if score else pol.weight, 4),
                    "penaltyScore": round(score, 4),
                    "inPenaltyBox": score > 0.0,
                    "killed": st.kills if st else 0,
                    "shed": st.sheds if st else 0,
                }
        return out
