"""QueryContext: per-query identity, deadline budget, and cancellation.

One QueryContext rides a query from the HTTP front door through the
executor's map-reduce fan-out, the device dispatch layer
(parallel.mesh), and the cluster client's remote legs. It carries

- an **id** (propagated to peers as ``X-Pilosa-Query-Id``, so every
  node's /debug/queries lists the same query and a cluster-wide cancel
  can find its legs),
- a **deadline** parsed from ``X-Pilosa-Deadline`` (remaining seconds —
  the fan-out form: peers inherit the *remaining* budget, not the
  original) or ``?timeout=`` (Go-style duration on the entry request),
- a **cancel flag** set by DELETE /debug/queries/{id} (locally or via
  the cluster broadcast), and
- **stage timings** (parse/admission/execute/encode) for the
  slow-query log.

Checks are cooperative: every layer that can block or loop calls
``ctx.check()`` (or module-level ``check_current()`` from code that
does not take a ctx argument, e.g. the mesh dispatch functions) and
gets a QueryDeadlineError / QueryCancelledError the moment the budget
is gone. The context travels between executor worker threads via
``use()``'s thread-local, set by the executor around each leg.
"""

from __future__ import annotations

import threading
import time
import uuid
from contextlib import contextmanager
from typing import Optional

from ..errors import (QueryCancelledError, QueryDeadlineError,
                      QueryKilledError)

# Lanes the admission controller schedules between. LANES is the
# canonical display order (the `pilosa-tpu top` per-lane table and
# any other lane-enumerating consumer read it from here instead of
# hardcoding the strings).
LANE_READ = "read"
LANE_WRITE = "write"
LANE_ADMIN = "admin"
LANES = (LANE_READ, LANE_WRITE, LANE_ADMIN)

# Wire headers for cluster fan-out propagation. The tenant header
# carries the scheduling/accounting principal (= index, today) onto
# remote legs — same pattern as the deadline: a peer inherits the
# coordinator's principal, so per-tenant cost ceilings and chargeback
# roll-ups hold cluster-wide even though forwarded legs bypass
# admission.
DEADLINE_HEADER = "X-Pilosa-Deadline"
QUERY_ID_HEADER = "X-Pilosa-Query-Id"
TENANT_HEADER = "X-Pilosa-Tenant"


class QueryContext:
    """Lifecycle state of one in-flight query."""

    def __init__(self, pql: str = "", index: str = "",
                 lane: str = LANE_READ,
                 timeout_s: Optional[float] = None,
                 id: Optional[str] = None, remote: bool = False,
                 node: str = "", tenant: str = ""):
        self.id = id or uuid.uuid4().hex[:16]
        self.pql = pql
        self.index = index
        self.lane = lane
        # Scheduling/accounting principal (sched.tenants): the index
        # by default, the X-Pilosa-Tenant header on forwarded legs.
        # Empty = the default tenant (bare contexts in tests).
        self.tenant = tenant or index
        self.remote = remote
        self.node = node
        self.started = time.monotonic()
        self.started_wall = time.time()
        self.deadline = (self.started + timeout_s
                         if timeout_s else None)
        self.state = "queued"
        self.cancel_reason = ""
        self._cancelled = threading.Event()
        self._mu = threading.Lock()
        self.stages: dict[str, float] = {}
        self.legs: list[dict] = []
        # Distributed-tracing attachment (obs.trace.Trace), bound by
        # the tracer when tracing is on. None (the default) is the
        # no-allocation fast path: stage() and span_current() check it
        # and record nothing.
        self.trace = None
        # Resource-accounting attachment (obs.accounting.QueryCost),
        # bound by the serving layer when accounting is on. Same
        # contract as trace: None means every note_* site records
        # nothing.
        self.cost = None
        # Per-tenant cost policy (sched.tenants.TenantRegistry.install):
        # a callable check() consults at every cooperative checkpoint —
        # the stage boundaries — and which raises QueryKilledError the
        # moment the ledger crosses a ceiling. None (the default) costs
        # one attribute read per check.
        self.cost_policy = None
        # Set by the cost policy when it kills this query: check()
        # then raises QueryKilledError (not the plain cancel) from
        # EVERY thread touching this context, so the HTTP layer maps
        # the distinct status deterministically whichever leg
        # surfaces first.
        self.killed_by = ""
        # Fault-event flags the tail sampler's keep decision reads at
        # query end ("breaker", "failover", "failpoint", "partial"):
        # set by the choke points that observe the event (client
        # circuit-open, executor failover, failpoints.hit). Set.add is
        # GIL-atomic; no lock needed.
        self.flags: set[str] = set()
        # Filled at query end by the serving layer: whether this
        # query's trace was kept and why — the slow log cross-links on
        # these so /debug/queries/slow points at the persisted trace.
        self.trace_kept = False
        self.keep_reason = ""
        # Query-plan attachment (plan.record.PlanRecord), bound by the
        # executor when the planner handles this query. Same contract
        # as trace/cost: None means the planner sat this one out.
        # ``profile`` is the ?profile=1 flag — it asks the executor to
        # pay for exact per-node actual cardinalities (ANALYZE).
        self.plan = None
        self.profile = False
        # Workload-capture cross-links (obs.capture), filled by the
        # serving layer at query end: the canonical result digest
        # (the X-Pilosa-Result-Digest value) and the capture-record id
        # — a slow-log line names the exact replayable record.
        self.result_digest = ""
        self.capture_id = 0

    def note_flag(self, name: str) -> None:
        """Record a fault-event flag for the tail sampler (no-op
        semantics: flags only widen the keep decision)."""
        self.flags.add(name)

    # -- budget --------------------------------------------------------------

    def remaining(self) -> Optional[float]:
        """Seconds of budget left; None means no deadline. Can go
        negative once expired (callers clamp as needed)."""
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def expired(self) -> bool:
        return (self.deadline is not None
                and time.monotonic() >= self.deadline)

    def elapsed(self) -> float:
        return time.monotonic() - self.started

    # -- cancellation --------------------------------------------------------

    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def cancel(self, reason: str = "cancelled") -> None:
        with self._mu:
            if not self._cancelled.is_set():
                self.cancel_reason = reason
                self.state = "cancelled"
            self._cancelled.set()

    def check(self) -> None:
        """Raise if this query must stop. The single cooperative
        cancellation point every lifecycle-aware layer calls — which
        makes it the per-tenant cost policy's stage-boundary hook
        too (the policy kills by cancelling, so a killed query stops
        at exactly the same points a cancelled one does)."""
        if self._cancelled.is_set():
            if self.killed_by:
                raise QueryKilledError(
                    f"query {self.id} killed by {self.killed_by}"
                    + (f": {self.cancel_reason}" if self.cancel_reason
                       else ""))
            raise QueryCancelledError(
                f"query {self.id} cancelled"
                + (f": {self.cancel_reason}" if self.cancel_reason
                   else ""))
        if self.expired():
            self.state = "expired"
            raise QueryDeadlineError(
                f"query {self.id}: deadline exceeded after"
                f" {self.elapsed():.3f}s")
        if self.cost_policy is not None:
            self.cost_policy(self)

    # -- bookkeeping ---------------------------------------------------------

    @contextmanager
    def stage(self, name: str):
        """Record wall time of one pipeline stage (accumulating —
        a stage may run more than once, e.g. per-leg encode). When a
        trace is attached, the stage doubles as a span."""
        t0 = time.perf_counter()
        t0_wall = time.time() if self.trace is not None else 0.0
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._mu:
                self.stages[name] = self.stages.get(name, 0.0) + dt
            if self.trace is not None:
                self.trace.add_span(name, t0_wall, dt)

    def add_leg(self, host: str, n_slices: int) -> None:
        """Record a map-reduce leg (node host + slice count) for
        /debug/queries visibility."""
        with self._mu:
            self.legs.append({"host": host, "slices": n_slices})

    def to_json(self) -> dict:
        rem = self.remaining()
        with self._mu:
            legs = list(self.legs)
            stages = dict(self.stages)
        out = {
            "id": self.id,
            "pql": self.pql[:200],
            "index": self.index,
            "tenant": self.tenant,
            "lane": self.lane,
            "state": self.state,
            "remote": self.remote,
            "node": self.node,
            "startedAt": self.started_wall,
            "elapsedS": round(self.elapsed(), 4),
            "remainingS": None if rem is None else round(rem, 4),
            "legs": legs,
            "stages": {k: round(v, 4) for k, v in stages.items()},
        }
        if self.cost is not None:
            # The accounting roll-up rides /debug/queries and the slow
            # log (obs.accounting.QueryCost.summary — totals only).
            out["cost"] = self.cost.summary()
        if self.plan is not None:
            # Cross-link only (the traceKept pattern): the fingerprint
            # keys into /debug/plans for the full tree; the decision
            # roll-up makes the slow log self-describing.
            out["planFingerprint"] = self.plan.fingerprint
            decisions = self.plan.decision_summary()
            if decisions:
                out["planDecisions"] = decisions
        if self.result_digest:
            # Replay cross-link (obs.capture): the digest is the
            # shadow-diff comparison key; captureId names the record
            # in /debug/capture/records that re-issues this query.
            out["resultDigest"] = self.result_digest
        if self.capture_id:
            out["captureId"] = self.capture_id
        return out


# -- thread-local propagation ------------------------------------------------

_tls = threading.local()

# Cross-thread view of the same bindings, for samplers that inspect
# OTHER threads (the continuous profiler tags each sampled stack with
# the query id bound to that thread — a thread-local is invisible from
# the sampler thread). Plain dict ops are atomic under the GIL.
_by_thread: dict[int, QueryContext] = {}


def current() -> Optional[QueryContext]:
    """The QueryContext bound to this thread, or None."""
    return getattr(_tls, "ctx", None)


def by_thread() -> dict[int, QueryContext]:
    """Snapshot of thread-id -> bound QueryContext, for cross-thread
    samplers (obs.profile)."""
    return dict(_by_thread)


@contextmanager
def use(ctx: Optional[QueryContext]):
    """Bind ``ctx`` as this thread's current query for the duration.
    Used by the executor around each worker leg so layers without a
    ctx argument (mesh dispatch) can still check the budget. ``None``
    is allowed (binds nothing-current, e.g. internal maintenance
    queries)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    tid = threading.get_ident()
    if ctx is not None:
        _by_thread[tid] = ctx
    else:
        _by_thread.pop(tid, None)
    try:
        yield ctx
    finally:
        _tls.ctx = prev
        if prev is not None:
            _by_thread[tid] = prev
        else:
            _by_thread.pop(tid, None)


def check_current() -> None:
    """check() on the thread's current query; no-op when none bound.
    The hook the device dispatch layer calls before compiling or
    dispatching a program on behalf of a query."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        ctx.check()
