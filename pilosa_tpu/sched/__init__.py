"""Query lifecycle subsystem: admission control, deadlines + budgets,
and cluster-wide cancellation + visibility.

- ``sched.context`` — QueryContext (id, deadline, cancel flag, stage
  timings) and its thread-local propagation into layers that do not
  take a ctx argument (the mesh device dispatch).
- ``sched.admission`` — the weighted (read/write/admin) bounded queue
  in front of the executor with a second, per-tenant stride level;
  overflow surfaces as HTTP 429 (tenant-scoped when a tenant's own
  quota overflowed).
- ``sched.tenants`` — the tenant (= index) as a scheduling and
  accounting principal: weights, concurrency caps, queue quotas,
  slow-query cost ceilings with a decaying penalty box.
- ``sched.registry`` — in-flight query visibility (/debug/queries),
  cancellation, and the slow-query log.
- ``sched.warmup`` — cold-start compilation of the hot XLA programs.

See docs/SCHEDULING.md for the lifecycle diagram and wire contract.
"""

from .admission import (AdmissionController, AdmissionFullError,  # noqa: F401
                        Slot)
from .context import (DEADLINE_HEADER, LANE_ADMIN, LANE_READ,  # noqa: F401
                      LANE_WRITE, LANES, QUERY_ID_HEADER, TENANT_HEADER,
                      QueryContext, check_current, current, use)
from .registry import QueryRegistry  # noqa: F401
from .tenants import (DEFAULT_TENANT, KILL_POLICY,  # noqa: F401
                      KILLED_BY_HEADER, TenantPolicy, TenantRegistry)
from .warmup import Warmup, warmup_enabled  # noqa: F401
