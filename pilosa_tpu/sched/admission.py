"""Admission control: a weighted queue in front of the executor.

The serving-quality contract under overload: a bounded number of
queries execute concurrently (``concurrency``), a bounded number wait
(``queue_depth``), and everything past that is rejected **immediately**
with enough information for the client to back off (AdmissionFullError
carries a Retry-After estimate) — the HTTP layer renders it as
``429 Too Many Requests`` instead of queueing unboundedly.

Waiting queries are scheduled between three lanes — ``read``,
``write``, ``admin`` — by stride scheduling (each lane has a virtual
clock advancing at 1/weight per grant), so a write burst cannot starve
reads and admin traffic always trickles through. Within a lane, FIFO.

Deadlines compose: a waiter whose QueryContext expires or is cancelled
while queued leaves the queue with the matching error — a query that
died waiting never occupies an execution slot.

Remote (forwarded) legs bypass admission at the receiving node: they
were admitted once at their coordinator, and admitting them again
could deadlock a saturated cluster (every node holding a slot while
waiting for a peer's slot). Cluster-wide concurrency is therefore
bounded by the sum of coordinator caps.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Optional

from ..errors import PilosaError

DEFAULT_CONCURRENCY = 16
DEFAULT_QUEUE_DEPTH = 64
# Lane weights: reads dominate a healthy serving mix, writes matter,
# admin must never starve. Overridable per controller.
DEFAULT_WEIGHTS = {"read": 4, "write": 2, "admin": 1}

# Poll tick while queued: bounds how stale a cancel/deadline can go
# unnoticed without a dedicated timer thread per waiter.
_WAIT_TICK_S = 0.05


class AdmissionFullError(PilosaError):
    """Queue depth exhausted; ``retry_after_s`` is the server's own
    estimate of when capacity frees (rendered as Retry-After)."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class _Waiter:
    __slots__ = ("granted",)

    def __init__(self):
        self.granted = False


class Slot:
    """An execution slot; release() is idempotent (also a context
    manager, releasing on exit)."""

    __slots__ = ("_ac", "lane", "_t0", "_released")

    def __init__(self, ac: "AdmissionController", lane: str):
        self._ac = ac
        self.lane = lane
        self._t0 = time.monotonic()
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._ac._release(self.lane, time.monotonic() - self._t0)

    def __enter__(self) -> "Slot":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class AdmissionController:
    def __init__(self, concurrency: int = DEFAULT_CONCURRENCY,
                 queue_depth: int = DEFAULT_QUEUE_DEPTH,
                 weights: Optional[dict[str, int]] = None):
        self.concurrency = max(1, int(concurrency))
        self.queue_depth = max(0, int(queue_depth))
        self.weights = dict(weights or DEFAULT_WEIGHTS)
        self._mu = threading.Lock()
        self._cond = threading.Condition(self._mu)
        self._in_flight = 0
        self._queues: dict[str, list[_Waiter]] = {}
        # Stride scheduling state: lane virtual clocks.
        self._vtime: dict[str, float] = {}
        self._served: dict[str, int] = {}
        self._rejected = 0
        # EWMA of slot hold seconds, feeding the Retry-After estimate.
        self._hold_ewma = 0.05
        # Stall/shed observability (obs.watchdog, obs.sampler): when
        # the last slot was granted, when the wait queue last became
        # non-empty, and per-lane when the last 429 was issued.
        self._last_grant = time.monotonic()
        self._queue_since = 0.0
        self._last_reject: dict[str, float] = {}

    # -- acquire / release ---------------------------------------------------

    def acquire(self, lane: str, ctx=None) -> Slot:
        """Block until a slot frees (respecting ``ctx``'s deadline and
        cancellation), or raise AdmissionFullError when the wait queue
        is already at depth."""
        with self._cond:
            queued = sum(len(q) for q in self._queues.values())
            if self._in_flight < self.concurrency and queued == 0:
                self._grant_locked(lane)
                return Slot(self, lane)
            if queued >= self.queue_depth:
                self._rejected += 1
                self._last_reject[lane] = time.monotonic()
                raise AdmissionFullError(
                    f"admission queue full ({queued} waiting,"
                    f" {self._in_flight} in flight)",
                    retry_after_s=self._retry_after_locked())
            w = _Waiter()
            if queued == 0:
                # The queue just became non-empty: the watchdog's
                # stall clock starts HERE, not at the last grant — a
                # fresh waiter behind legitimately long-running slot
                # holders is not a stall.
                self._queue_since = time.monotonic()
            self._queues.setdefault(lane, []).append(w)
            try:
                while not w.granted:
                    if ctx is not None:
                        ctx.check()  # raises on cancel/expiry
                    self._cond.wait(_WAIT_TICK_S)
            except BaseException:
                # Left the queue without the slot: if a grant raced in,
                # hand it to the next waiter instead of leaking it.
                if w.granted:
                    self._in_flight -= 1
                    self._wake_locked()
                else:
                    self._queues[lane].remove(w)
                raise
            return Slot(self, lane)

    def _release(self, lane: str, held_s: float) -> None:
        with self._cond:
            self._in_flight -= 1
            self._hold_ewma = 0.8 * self._hold_ewma + 0.2 * held_s
            self._wake_locked()

    def _grant_locked(self, lane: str) -> None:
        self._in_flight += 1
        self._last_grant = time.monotonic()
        self._served[lane] = self._served.get(lane, 0) + 1
        w = self.weights.get(lane, 1) or 1
        # A lane idle for a while re-enters near the current clock
        # rather than spending banked credit starving everyone else.
        base = max(self._vtime.values(), default=0.0)
        self._vtime[lane] = max(self._vtime.get(lane, 0.0), base - 1.0) \
            + 1.0 / w

    def _wake_locked(self) -> None:
        """Grant freed capacity to waiters, picking the nonempty lane
        with the smallest virtual time (stride scheduling)."""
        granted = False
        while self._in_flight < self.concurrency:
            lanes = [ln for ln, q in self._queues.items() if q]
            if not lanes:
                break
            lane = min(lanes, key=lambda ln: self._vtime.get(ln, 0.0))
            waiter = self._queues[lane].pop(0)
            waiter.granted = True
            self._grant_locked(lane)
            granted = True
        if granted:
            self._cond.notify_all()

    # -- introspection -------------------------------------------------------

    def _retry_after_locked(self) -> float:
        """Seconds until the backlog likely drains enough to admit one
        more query: backlog size × EWMA hold time / parallelism."""
        backlog = self._in_flight + sum(
            len(q) for q in self._queues.values())
        est = self._hold_ewma * backlog / self.concurrency
        return float(max(1, math.ceil(est)))

    @property
    def in_flight(self) -> int:
        with self._mu:
            return self._in_flight

    def recent_rejection(self, lane: str, window_s: float) -> bool:
        """Did this lane answer a 429 within the last ``window_s``?
        The tail sampler's shed-lane signal: a query that finished in
        a lane that was actively shedding is evidence worth keeping."""
        with self._mu:
            t = self._last_reject.get(lane)
        return t is not None and time.monotonic() - t <= window_s

    def stall_state(self) -> tuple[int, float]:
        """(queued, stall age) for the watchdog's non-draining-queue
        detector: the age is since the LATER of the last grant and
        the moment the queue became non-empty — grants draining the
        queue reset it, and so does an empty queue refilling."""
        with self._mu:
            queued = sum(len(q) for q in self._queues.values())
            if queued == 0:
                return 0, 0.0
            return queued, time.monotonic() - max(self._last_grant,
                                                  self._queue_since)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "concurrency": self.concurrency,
                "queueDepth": self.queue_depth,
                "inFlight": self._in_flight,
                "queued": {ln: len(q)
                           for ln, q in self._queues.items() if q},
                "served": dict(self._served),
                "rejected": self._rejected,
                "weights": dict(self.weights),
                "holdEwmaS": round(self._hold_ewma, 4),
            }
