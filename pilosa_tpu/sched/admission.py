"""Admission control: a weighted queue in front of the executor.

The serving-quality contract under overload: a bounded number of
queries execute concurrently (``concurrency``), a bounded number wait
(``queue_depth``), and everything past that is rejected **immediately**
with enough information for the client to back off (AdmissionFullError
carries a Retry-After estimate) — the HTTP layer renders it as
``429 Too Many Requests`` instead of queueing unboundedly.

Waiting queries are scheduled by **two levels of stride scheduling**:

- between the three lanes — ``read``, ``write``, ``admin`` — by lane
  weight (each lane's virtual clock advances at 1/weight per grant),
  so a write burst cannot starve reads and admin always trickles
  through;
- **within a lane, between tenants** (sched.tenants) by the tenant's
  effective weight (configured weight, demoted while the tenant sits
  in the penalty box), so an aggressive tenant's backlog cannot starve
  a quiet tenant's queue position. Within a (lane, tenant) queue, FIFO.

Per-tenant **concurrency caps** bound how many slots one tenant may
hold (a capped tenant queues even while global slots are free); per-
tenant **queue quotas** bound its waiters — quota overflow 429s ONLY
the offending tenant, with a Retry-After computed from that
tenant-lane's own hold/backlog estimate. The per-lane hold EWMAs keep
a shed write burst from inflating the Retry-After handed to read
traffic. Without a tenant registry every caller rides one implicit
tenant and the controller behaves exactly as the single-level one did.

Deadlines compose: a waiter whose QueryContext expires or is cancelled
while queued leaves the queue with the matching error — a query that
died waiting never occupies an execution slot.

Remote (forwarded) legs bypass admission at the receiving node: they
were admitted once at their coordinator, and admitting them again
could deadlock a saturated cluster (every node holding a slot while
waiting for a peer's slot). Cluster-wide concurrency is therefore
bounded by the sum of coordinator caps.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Optional

from ..errors import PilosaError
# The implicit tenant when no registry / principal is wired — one
# bucket, so the second stride level degenerates to the old behavior.
# The ONE definition lives in utils.config (the [tenants] table's
# mandatory entry), so the implicit bucket can never drift from the
# policy the registry resolves unknown tenants to.
from ..utils.config import DEFAULT_TENANT  # noqa: F401

DEFAULT_CONCURRENCY = 16
DEFAULT_QUEUE_DEPTH = 64
# Lane weights: reads dominate a healthy serving mix, writes matter,
# admin must never starve. Overridable per controller.
DEFAULT_WEIGHTS = {"read": 4, "write": 2, "admin": 1}

# Poll tick while queued: bounds how stale a cancel/deadline can go
# unnoticed without a dedicated timer thread per waiter.
_WAIT_TICK_S = 0.05

# Seed hold estimate before any slot has released (seconds).
_HOLD_SEED_S = 0.05


class AdmissionFullError(PilosaError):
    """Queue depth exhausted; ``retry_after_s`` is the server's own
    estimate of when capacity frees (rendered as Retry-After).
    ``tenant`` names the principal when the rejection was that
    tenant's own quota (not the global backstop) — the HTTP layer's
    per-tenant shed counters key on it."""

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 tenant: str = ""):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.tenant = tenant


class _Waiter:
    __slots__ = ("granted", "tenant")

    def __init__(self, tenant: str):
        self.granted = False
        self.tenant = tenant


class Slot:
    """An execution slot; release() is idempotent (also a context
    manager, releasing on exit)."""

    __slots__ = ("_ac", "lane", "tenant", "_t0", "_released")

    def __init__(self, ac: "AdmissionController", lane: str,
                 tenant: str = DEFAULT_TENANT):
        self._ac = ac
        self.lane = lane
        self.tenant = tenant
        self._t0 = time.monotonic()
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._ac._release(self.lane, self.tenant,
                              time.monotonic() - self._t0)

    def __enter__(self) -> "Slot":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class AdmissionController:
    def __init__(self, concurrency: int = DEFAULT_CONCURRENCY,
                 queue_depth: int = DEFAULT_QUEUE_DEPTH,
                 weights: Optional[dict[str, int]] = None,
                 tenants=None):
        self.concurrency = max(1, int(concurrency))
        self.queue_depth = max(0, int(queue_depth))
        self.weights = dict(weights or DEFAULT_WEIGHTS)
        # sched.tenants.TenantRegistry (or None): per-tenant weights,
        # caps, quotas. Its lock is a leaf under this controller's.
        self.tenants = tenants
        self._mu = threading.Lock()
        self._cond = threading.Condition(self._mu)
        self._in_flight = 0
        # lane -> tenant -> FIFO of waiters.
        self._queues: dict[str, dict[str, list[_Waiter]]] = {}
        # Stride scheduling state: lane virtual clocks, and per-lane
        # tenant virtual clocks (the second level).
        self._vtime: dict[str, float] = {}
        self._tvtime: dict[str, dict[str, float]] = {}
        self._served: dict[str, int] = {}
        self._tenant_served: dict[str, int] = {}
        self._lane_inflight: dict[str, int] = {}
        self._tenant_inflight: dict[str, int] = {}
        self._rejected = 0
        self._tenant_rejected: dict[str, int] = {}
        # Hold-seconds EWMAs feeding the Retry-After estimates:
        # per lane (a shed write burst must not inflate read
        # Retry-Afters) and per (lane, tenant) for quota rejections.
        self._hold_ewma: dict[str, float] = {}
        self._tenant_hold: dict[tuple[str, str], float] = {}
        # Stall/shed observability (obs.watchdog, obs.sampler): when
        # the last slot was granted, when the wait queue last became
        # non-empty, and per-lane when the last 429 was issued.
        self._last_grant = time.monotonic()
        self._queue_since = 0.0
        self._last_reject: dict[str, float] = {}

    # -- tenant policy plumbing ----------------------------------------------

    def _tenant_of(self, ctx, tenant: Optional[str]) -> str:
        if tenant:
            return tenant
        t = getattr(ctx, "tenant", "") if ctx is not None else ""
        return t or DEFAULT_TENANT

    def _tenant_caps(self, tenant: str) -> tuple[int, int]:
        """(concurrency cap, queue quota) for this tenant; 0 = none."""
        if self.tenants is None:
            return 0, 0
        pol = self.tenants.policy(tenant)
        return pol.concurrency, pol.queue_depth

    def _tenant_weight(self, tenant: str) -> float:
        if self.tenants is None:
            return 1.0
        return max(self.tenants.effective_weight(tenant), 1e-6)

    def _under_cap_locked(self, tenant: str) -> bool:
        cap, _ = self._tenant_caps(tenant)
        return cap <= 0 or self._tenant_inflight.get(tenant, 0) < cap

    # -- acquire / release ---------------------------------------------------

    def acquire(self, lane: str, ctx=None,
                tenant: Optional[str] = None) -> Slot:
        """Block until a slot frees (respecting ``ctx``'s deadline and
        cancellation), or raise AdmissionFullError when the wait queue
        is already at depth — or this tenant's own quota is. The
        tenant defaults to the context's principal."""
        tenant = self._tenant_of(ctx, tenant)
        with self._cond:
            queued = self._queued_locked()
            if (self._in_flight < self.concurrency and queued == 0
                    and self._under_cap_locked(tenant)):
                self._grant_locked(lane, tenant)
                return Slot(self, lane, tenant)
            tq = len(self._queues.get(lane, {}).get(tenant, ()))
            _, quota = self._tenant_caps(tenant)
            if quota > 0 and tq >= quota:
                # The tenant's own quota: only IT sheds — everyone
                # else's queue positions are untouched, and the
                # Retry-After is computed from ITS backlog, not the
                # aggregate's.
                self._rejected += 1
                self._tenant_rejected[tenant] = \
                    self._tenant_rejected.get(tenant, 0) + 1
                self._last_reject[lane] = time.monotonic()
                raise AdmissionFullError(
                    f"tenant {tenant} over queue quota ({tq} waiting"
                    f" in {lane}, quota {quota})",
                    retry_after_s=self._retry_after_locked(
                        lane, tenant=tenant),
                    tenant=tenant)
            if queued >= self.queue_depth:
                self._rejected += 1
                self._last_reject[lane] = time.monotonic()
                raise AdmissionFullError(
                    f"admission queue full ({queued} waiting,"
                    f" {self._in_flight} in flight)",
                    retry_after_s=self._retry_after_locked(lane))
            w = _Waiter(tenant)
            if queued == 0:
                # The queue just became non-empty: the watchdog's
                # stall clock starts HERE, not at the last grant — a
                # fresh waiter behind legitimately long-running slot
                # holders is not a stall.
                self._queue_since = time.monotonic()
            self._queues.setdefault(lane, {}).setdefault(
                tenant, []).append(w)
            # Capacity may be grantable RIGHT NOW (e.g. slots free but
            # some other tenant's waiters are cap-blocked): the wake
            # pass keeps the controller work-conserving.
            self._wake_locked()
            try:
                while not w.granted:
                    if ctx is not None:
                        ctx.check()  # raises on cancel/expiry/kill
                    self._cond.wait(_WAIT_TICK_S)
            except BaseException:
                # Left the queue without the slot: if a grant raced in,
                # hand it to the next waiter instead of leaking it.
                if w.granted:
                    self._in_flight -= 1
                    self._lane_dec(self._lane_inflight, lane)
                    self._lane_dec(self._tenant_inflight, tenant)
                    self._wake_locked()
                else:
                    self._queues[lane][tenant].remove(w)
                    if not self._queues[lane][tenant]:
                        del self._queues[lane][tenant]
                raise
            return Slot(self, lane, tenant)

    @staticmethod
    def _lane_dec(d: dict, key: str) -> None:
        n = d.get(key, 0) - 1
        if n > 0:
            d[key] = n
        else:
            d.pop(key, None)

    def _release(self, lane: str, tenant: str, held_s: float) -> None:
        with self._cond:
            self._in_flight -= 1
            self._lane_dec(self._lane_inflight, lane)
            self._lane_dec(self._tenant_inflight, tenant)
            prev = self._hold_ewma.get(lane, _HOLD_SEED_S)
            self._hold_ewma[lane] = 0.8 * prev + 0.2 * held_s
            tkey = (lane, tenant)
            tprev = self._tenant_hold.get(tkey, _HOLD_SEED_S)
            self._tenant_hold[tkey] = 0.8 * tprev + 0.2 * held_s
            self._wake_locked()

    def _queued_locked(self) -> int:
        return sum(len(q) for tmap in self._queues.values()
                   for q in tmap.values())

    def _grant_locked(self, lane: str, tenant: str) -> None:
        self._in_flight += 1
        self._last_grant = time.monotonic()
        self._served[lane] = self._served.get(lane, 0) + 1
        self._tenant_served[tenant] = \
            self._tenant_served.get(tenant, 0) + 1
        self._lane_inflight[lane] = \
            self._lane_inflight.get(lane, 0) + 1
        self._tenant_inflight[tenant] = \
            self._tenant_inflight.get(tenant, 0) + 1
        w = self.weights.get(lane, 1) or 1
        # A lane idle for a while re-enters near the current clock
        # rather than spending banked credit starving everyone else.
        base = max(self._vtime.values(), default=0.0)
        self._vtime[lane] = max(self._vtime.get(lane, 0.0), base - 1.0) \
            + 1.0 / w
        # Second level: the tenant clock within this lane, advancing
        # at 1/effective-weight — the penalty box demotes a repeat
        # offender here without touching anyone else's schedule.
        tv = self._tvtime.setdefault(lane, {})
        tbase = max(tv.values(), default=0.0)
        tv[tenant] = max(tv.get(tenant, 0.0), tbase - 1.0) \
            + 1.0 / self._tenant_weight(tenant)

    def _pick_locked(self) -> Optional[tuple[str, str]]:
        """The next (lane, tenant) to grant: the backlogged lane with
        the smallest lane clock among lanes holding at least one
        ELIGIBLE (under-cap) tenant; within it, the eligible tenant
        with the smallest tenant clock."""
        best_lane = None
        best_tenants: list[str] = []
        for lane, tmap in self._queues.items():
            eligible = [t for t, q in tmap.items()
                        if q and self._under_cap_locked(t)]
            if not eligible:
                continue
            if (best_lane is None or self._vtime.get(lane, 0.0)
                    < self._vtime.get(best_lane, 0.0)):
                best_lane, best_tenants = lane, eligible
        if best_lane is None:
            return None
        tv = self._tvtime.get(best_lane, {})
        tenant = min(best_tenants, key=lambda t: tv.get(t, 0.0))
        return best_lane, tenant

    def _wake_locked(self) -> None:
        """Grant freed capacity to waiters via the two-level stride
        pick, skipping tenants at their concurrency cap."""
        granted = False
        while self._in_flight < self.concurrency:
            pick = self._pick_locked()
            if pick is None:
                break
            lane, tenant = pick
            q = self._queues[lane][tenant]
            waiter = q.pop(0)
            if not q:
                del self._queues[lane][tenant]
            waiter.granted = True
            self._grant_locked(lane, waiter.tenant)
            granted = True
        if granted:
            self._cond.notify_all()

    # -- introspection -------------------------------------------------------

    def _retry_after_locked(self, lane: str,
                            tenant: Optional[str] = None) -> float:
        """Seconds until the backlog likely drains enough to admit one
        more query. Per-lane: that lane's backlog × ITS hold EWMA /
        parallelism (a shed write burst leaves read Retry-Afters
        alone). Per-tenant: the tenant-lane's own backlog over the
        parallelism its cap actually allows it."""
        if tenant is not None:
            cap, _ = self._tenant_caps(tenant)
            par = min(self.concurrency, cap) if cap > 0 \
                else self.concurrency
            backlog = (self._tenant_inflight.get(tenant, 0)
                       + len(self._queues.get(lane, {})
                             .get(tenant, ())))
            hold = self._tenant_hold.get(
                (lane, tenant), self._hold_ewma.get(lane,
                                                    _HOLD_SEED_S))
        else:
            par = self.concurrency
            backlog = (self._lane_inflight.get(lane, 0)
                       + sum(len(q) for q in
                             self._queues.get(lane, {}).values()))
            hold = self._hold_ewma.get(lane, _HOLD_SEED_S)
        est = hold * max(1, backlog) / max(1, par)
        return float(max(1, math.ceil(est)))

    @property
    def in_flight(self) -> int:
        with self._mu:
            return self._in_flight

    def tenant_in_flight(self) -> dict[str, int]:
        """Slots held per tenant (scrape-time gauge refresh +
        /debug/tenants)."""
        with self._mu:
            return dict(self._tenant_inflight)

    def recent_rejection(self, lane: str, window_s: float) -> bool:
        """Did this lane answer a 429 within the last ``window_s``?
        The tail sampler's shed-lane signal: a query that finished in
        a lane that was actively shedding is evidence worth keeping."""
        with self._mu:
            t = self._last_reject.get(lane)
        return t is not None and time.monotonic() - t <= window_s

    def stall_state(self) -> tuple[int, float]:
        """(queued, stall age) for the watchdog's non-draining-queue
        detector: the age is since the LATER of the last grant and
        the moment the queue became non-empty — grants draining the
        queue reset it, and so does an empty queue refilling."""
        with self._mu:
            queued = self._queued_locked()
            if queued == 0:
                return 0, 0.0
            return queued, time.monotonic() - max(self._last_grant,
                                                  self._queue_since)

    def snapshot(self) -> dict:
        with self._mu:
            lane_queued = {ln: sum(len(q) for q in tmap.values())
                           for ln, tmap in self._queues.items()
                           if any(tmap.values())}
            tenants = {}
            names = (set(self._tenant_inflight)
                     | set(self._tenant_served)
                     | set(self._tenant_rejected)
                     | {t for tmap in self._queues.values()
                        for t, q in tmap.items() if q})
            for t in sorted(names):
                tenants[t] = {
                    "inFlight": self._tenant_inflight.get(t, 0),
                    "queued": sum(
                        len(tmap.get(t, ()))
                        for tmap in self._queues.values()),
                    "served": self._tenant_served.get(t, 0),
                    "rejected": self._tenant_rejected.get(t, 0),
                }
            return {
                "concurrency": self.concurrency,
                "queueDepth": self.queue_depth,
                "inFlight": self._in_flight,
                "queued": lane_queued,
                "served": dict(self._served),
                "rejected": self._rejected,
                "weights": dict(self.weights),
                "holdEwmaS": {ln: round(v, 4) for ln, v
                              in self._hold_ewma.items()},
                "tenants": tenants,
            }
