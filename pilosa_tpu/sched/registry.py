"""QueryRegistry: in-flight query visibility + cancellation + slow log.

Every query (coordinator entry AND forwarded remote legs — legs carry
the coordinator's id via X-Pilosa-Query-Id) registers here for its
lifetime, so

- ``GET /debug/queries`` can list what is actually running on this
  node (id, PQL, elapsed, remaining budget, node legs, state),
- ``DELETE /debug/queries/{id}`` can cancel it — locally by flipping
  the context's cancel flag (every executor layer checks it
  cooperatively), cluster-wide by broadcasting a CancelQueryMessage so
  peers cancel the legs registered under the same id, and
- queries slower than the configured threshold land in a bounded
  slow-query log (PQL + per-stage timings), mirrored into the stats
  pipeline (``slowQueries`` counter + ``slowQueryNs`` timing).

Ids may collide on one node only in pathological cases (a coordinator
never forwards to itself), but the registry keeps a list per id anyway
— cancel-by-id then kills every context in the group.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from ..utils.stats import NOP
from .context import QueryContext


class QueryRegistry:
    def __init__(self, slow_threshold_s: Optional[float] = None,
                 stats=NOP, logger=None, max_slow: int = 64):
        from ..utils import logger as logger_mod
        self.slow_threshold_s = slow_threshold_s or None
        self.stats = stats
        self.logger = logger or logger_mod.NOP
        self._mu = threading.Lock()
        self._active: dict[str, list[QueryContext]] = {}
        self._slow: deque[dict] = deque(maxlen=max_slow)

    # -- lifecycle -----------------------------------------------------------

    def register(self, ctx: QueryContext) -> QueryContext:
        with self._mu:
            self._active.setdefault(ctx.id, []).append(ctx)
        return ctx

    def finish(self, ctx: QueryContext,
               error: Optional[BaseException] = None) -> None:
        with self._mu:
            group = self._active.get(ctx.id)
            if group is not None:
                try:
                    group.remove(ctx)
                except ValueError:
                    pass
                if not group:
                    del self._active[ctx.id]
        if ctx.state not in ("cancelled", "expired"):
            ctx.state = "error" if error is not None else "done"
        elapsed = ctx.elapsed()
        if (self.slow_threshold_s is not None
                and elapsed >= self.slow_threshold_s):
            self._record_slow(ctx, elapsed, error)

    def track(self, ctx: QueryContext):
        """register() as a context manager; finish() records whatever
        exception ends the block."""
        registry = self

        class _Track:
            def __enter__(self):
                registry.register(ctx)
                ctx.state = "running"
                return ctx

            def __exit__(self, exc_type, exc, tb):
                registry.finish(ctx, error=exc)
                return False

        return _Track()

    # -- slow-query log ------------------------------------------------------

    def _record_slow(self, ctx: QueryContext, elapsed: float,
                     error) -> None:
        entry = ctx.to_json()
        entry["elapsedS"] = round(elapsed, 4)
        if error is not None:
            entry["error"] = str(error)[:200]
        # Cross-link to the tail sampler (obs.sampler): when the
        # query's trace was kept, this slow entry points straight at
        # the persisted trace — /debug/traces/{id} (ring) or
        # /debug/traces?source=disk (after a restart).
        entry["traceKept"] = bool(getattr(ctx, "trace_kept", False))
        reason = getattr(ctx, "keep_reason", "")
        if reason:
            entry["traceKeepReason"] = reason
        with self._mu:
            self._slow.append(entry)
        self.stats.count("slowQueries", 1)
        self.stats.timing("slowQueryNs", elapsed * 1e9)
        stages = ", ".join(f"{k}={v:.3f}s"
                           for k, v in entry["stages"].items())
        self.logger.printf(
            "slow query %s (%.3fs%s): index=%s lane=%s pql=%.200s",
            ctx.id, elapsed, f"; {stages}" if stages else "",
            ctx.index, ctx.lane, ctx.pql)

    def slow_queries(self) -> list[dict]:
        with self._mu:
            return list(self._slow)

    # -- visibility + cancellation -------------------------------------------

    def active(self) -> list[dict]:
        return [c.to_json() for c in self.active_contexts()]

    def active_contexts(self) -> list[QueryContext]:
        """The live QueryContext objects, oldest first — the watchdog
        (stuck-leg detection, force-keeping in-flight traces) needs
        the contexts themselves, not their JSON."""
        with self._mu:
            ctxs = [c for group in self._active.values() for c in group]
        ctxs.sort(key=lambda c: c.started)
        return ctxs

    def __len__(self) -> int:
        with self._mu:
            return sum(len(g) for g in self._active.values())

    def get(self, qid: str) -> Optional[QueryContext]:
        with self._mu:
            group = self._active.get(qid)
            return group[0] if group else None

    def cancel_local(self, qid: str,
                     reason: str = "cancelled via API") -> int:
        """Cancel every in-flight context registered under ``qid`` on
        THIS node; returns how many were cancelled."""
        with self._mu:
            group = list(self._active.get(qid, ()))
        for ctx in group:
            ctx.cancel(reason)
        if group:
            self.stats.count("queriesCancelled", len(group))
        return len(group)
