"""Sentinel errors and name/label validation.

Reference: pilosa.go:25-122 (sentinel errors, name/label regexes, time format).
"""

import re


class PilosaError(Exception):
    """Base class for all framework errors."""


class IndexExistsError(PilosaError):
    pass


class IndexNotFoundError(PilosaError):
    pass


class FrameExistsError(PilosaError):
    pass


class FrameNotFoundError(PilosaError):
    pass


class InverseNotEnabledError(PilosaError):
    pass


class FragmentNotFoundError(PilosaError):
    pass


class QueryRequiredError(PilosaError):
    pass


class SliceUnavailableError(PilosaError):
    """Raised when a slice cannot be mapped to any available node
    (reference: executor.go:1239)."""


class QueryDeadlineError(PilosaError):
    """Raised when a query's deadline budget is exhausted — by the
    executor's fan-out loops, the device dispatch layer, or the
    cluster client's socket/retry machinery (sched.context). Maps to
    HTTP 504; never triggers replica re-mapping (the query is dead,
    not the node)."""


class QueryCancelledError(PilosaError):
    """Raised when a query is cancelled through the lifecycle API
    (DELETE /debug/queries/{id}, propagated cluster-wide). Maps to
    HTTP 409; never triggers replica re-mapping."""


class QueryKilledError(QueryCancelledError):
    """A query killed by the per-tenant slow-query cost policy
    (sched.tenants): its ledger crossed a configured ceiling at a
    stage boundary. Subclasses QueryCancelledError so every
    cancellation-aware layer (executor legs, admission waits, mesh
    dispatch) treats it as a cancel; the HTTP layer maps it to a
    DISTINCT status (402 + ``X-Pilosa-Killed-By: cost-policy``) so
    clients can tell a budget kill from an operator cancel."""


# Name/label rules (reference: pilosa.go:50-53).
_NAME_RE = re.compile(r"^[a-z][a-z0-9_-]{0,64}$")
_LABEL_RE = re.compile(r"^[A-Za-z][A-Za-z0-9_-]{0,64}$")

# TimeFormat is the canonical PQL timestamp layout
# (reference: pilosa.go:106, Go layout "2006-01-02T15:04").
TIME_FORMAT = "%Y-%m-%dT%H:%M"


def validate_name(name: str) -> None:
    """Validate an index/frame/view name (reference: pilosa.go:109-114)."""
    if not isinstance(name, str) or not _NAME_RE.match(name):
        raise PilosaError(f"invalid name: {name!r}")


def validate_label(label: str) -> None:
    """Validate a row/column label (reference: pilosa.go:116-122)."""
    if not isinstance(label, str) or not _LABEL_RE.match(label):
        raise PilosaError(f"invalid label: {label!r}")
