"""Executor: per-call PQL dispatch + cluster map-reduce over slices.

Reference: executor.go. Each call type maps/reduces over the slice axis:
Count sums per-slice counts, TopN merges per-slice pair lists (then
re-queries exact counts for the candidate ids, executor.go:273-310), bitmap
expressions fold per slice and the result segments stay sharded. Writes
route to every replica owner of the target slice and forward to remote
owners unless the query already carries the Remote flag
(executor.go:664-797).

Map-reduce (executor.go:1103-1236): slices group by owning node
(jump-hash placement, cluster.topology), one worker per node; a failed
node is filtered out and its slices re-mapped onto remaining replicas
until none are left. Local legs fan out slice-parallel.

TPU-first departure: the per-slice hot work (row materialization, set
algebra, counts) already runs through the device kernel layer inside
Fragment; the executor's local fan-out additionally batches whole-index
Count/TopN onto the device mesh via pilosa_tpu.parallel.mesh when the
expression shape allows it — same reduction tree, but the slice axis is a
mesh axis and the reduce is an XLA psum instead of a Python loop.
"""

from __future__ import annotations

import datetime as dt
import os
import threading
from collections import OrderedDict
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from .cluster.topology import Cluster, Node, new_cluster
from .errors import (TIME_FORMAT, FrameNotFoundError, IndexNotFoundError,
                     PilosaError, QueryCancelledError, QueryDeadlineError,
                     QueryRequiredError, SliceUnavailableError)
from .obs import accounting as obs_accounting
from .obs import metrics as obs_metrics
from .obs import trace as obs_trace
from .plan import planner as plan_planner
from .plan import record as plan_record
from .plan import store as plan_store
from .sched import context as sched_context
from . import SLICE_WIDTH
from .models.view import VIEW_INVERSE, VIEW_STANDARD
from .pql.ast import Call, Query
from .pql.parser import _POINT_MUTATE_RE
from .pql.parser import parse as parse_pql
from .storage import bsi
from .storage.bitmap import Bitmap, BitmapSegment
from .storage.cache import Pair, pairs_sort
from .storage.fragment import TopOptions
from .utils import timequantum as tq

# Frame used when a call does not specify one (executor.go:35).
DEFAULT_FRAME = "general"


def _attach_plan_nodes(call: Call, node) -> None:
    """Pair the planner's PlanNode tree onto the cloned Call tree via a
    ``_plan_node`` attribute — the per-slice hooks run on mapper-pool
    threads where no request context is visible, so the hint has to
    travel with the call itself. The planner clones calls 1:1 with the
    nodes it emits (drops happen to both sides together), so a zip is
    exact; a length mismatch would mean a planner bug and we stop
    attaching rather than mis-pair hints."""
    call._plan_node = node
    if len(call.children) != len(node.children):
        return
    for ch_call, ch_node in zip(call.children, node.children):
        _attach_plan_nodes(ch_call, ch_node)

# Lowest count used in a TopN when no threshold is given (executor.go:39).
MIN_THRESHOLD = 1

_WRITE_CALLS = ("SetBit", "ClearBit", "SetFieldValue", "SetRowAttrs",
                "SetColumnAttrs")


def _ctx_span(ctx, name: str, **tags):
    """A span on ``ctx``'s trace, or the shared no-op when the query
    is untraced (the default): the fan-out layers instrument through
    this so an untraced query allocates no Span objects."""
    trace = getattr(ctx, "trace", None) if ctx is not None else None
    if trace is None:
        return obs_trace.NOP_SPAN
    return trace.span(name, **tags)


@dataclass
class ExecOptions:
    """Remote=True marks a query forwarded by another node: process only
    local slices and don't re-forward (executor.go:1290-1292).
    pod_local=True marks a pod-internal leg (parallel.pod): run the
    plain local path over the given slices — no pod dispatch, no
    pod-global collectives. ctx carries the query's lifecycle state
    (sched.context.QueryContext: deadline budget + cancel flag) — every
    fan-out layer checks it, remote legs inherit the REMAINING budget,
    and None (internal/maintenance callers) means unbounded.

    partial=True (the ``?partial=1`` degraded-read contract, fault
    subsystem): slices with NO reachable replica are skipped instead
    of failing the whole query; their ids accumulate in
    ``missing_slices`` (the handler reports them as the
    ``X-Pilosa-Partial`` response header)."""
    remote: bool = False
    pod_local: bool = False
    ctx: Optional[object] = None
    partial: bool = False
    missing_slices: Optional[list] = None


def _needs_slices(calls: list[Call]) -> bool:
    # executor.go:1273-1289
    if not calls:
        return False
    return any(c.name not in _WRITE_CALLS for c in calls)


def _has_only_set_row_attrs(calls: list[Call]) -> bool:
    return bool(calls) and all(c.name == "SetRowAttrs" for c in calls)


def _parse_timestamp(c: Call, key: str = "timestamp"
                     ) -> Optional[dt.datetime]:
    v = c.args.get(key)
    if v is None:
        return None
    if isinstance(v, dt.datetime):
        return v
    try:
        return dt.datetime.strptime(v, TIME_FORMAT)
    except (TypeError, ValueError):
        raise PilosaError(f"invalid date: {v}")


class Executor:
    """Executes PQL queries against a Holder, fanning out across a Cluster.

    ``client`` is the node-to-node transport (cluster.client.Client); any
    object with ``execute_query(node, index, query, slices, remote)`` works
    — tests inject scripted fakes exactly like the reference's mock
    executor seam (handler.go:60-62).
    """

    def __init__(self, holder, host: str = "",
                 cluster: Optional[Cluster] = None, client=None,
                 max_workers: int = 16, use_mesh: Optional[bool] = None,
                 mesh_min_slices: Optional[int] = None, pod=None,
                 fault=None, gens=None,
                 result_cache_entries: Optional[int] = None,
                 result_cache_bits: Optional[int] = None,
                 cluster_cache_entries: Optional[int] = None,
                 gen_staleness_s: Optional[float] = None,
                 tenants=None):
        self.holder = holder
        self.host = host
        self.cluster = cluster or new_cluster([host])
        self.client = client
        # Cluster-wide generation knowledge (cluster.generations
        # GenerationMap, shared with every pooled Client): lets the
        # result caches key and validate slices owned ELSEWHERE. None
        # (bare executors, single node) keeps those paths local-only.
        self.gens = gens
        # Tenant policy (sched.tenants.TenantRegistry): partitions the
        # result-cache budgets per tenant (= index, both cache keys
        # lead with it) via each tenant's cache-share, so one tenant's
        # working set cannot evict everyone else's. None = the
        # pre-tenant single-pool behavior.
        self.tenants = tenants
        if gen_staleness_s is None:
            raw = os.environ.get("PILOSA_CLUSTER_GEN_STALENESS")
            if raw:
                try:
                    gen_staleness_s = float(raw)
                except ValueError:
                    from .utils.config import parse_duration
                    gen_staleness_s = parse_duration(raw)
        self._gen_staleness_s = gen_staleness_s  # None = map default
        # Fault-tolerance state (fault.FaultManager): _slices_by_node
        # orders replica owners by health and sinks open circuits, the
        # re-map path consults it instead of rediscovering a dead peer
        # per query, and remote legs hedge when configured. None keeps
        # the plain jump-hash-primary placement.
        self.fault = fault
        # Multi-host pod membership (parallel.pod.Pod) — None in the
        # ordinary single-process server. On the pod coordinator the
        # local leg fans out pod-wide (collectives for device-batched
        # Count/TopN, podLocal HTTP legs for everything else).
        self.pod = pod
        self.max_workers = max_workers
        if use_mesh is None:
            use_mesh = os.environ.get("PILOSA_TPU_MESH", "1") != "0"
        self.use_mesh = use_mesh
        if mesh_min_slices is None:
            mesh_min_slices = int(os.environ.get(
                "PILOSA_TPU_MESH_MIN_SLICES", "8"))
        # Below this many local slices the per-slice host path wins: one
        # device dispatch costs a host↔device sync (~65 ms through the
        # TPU tunnel) that only pays for itself on wide fan-outs.
        self.mesh_min_slices = mesh_min_slices
        # Materializing bitmap calls engage the device only past this
        # many leaf rows (config 2's wide-Union form); below it the
        # per-slice roaring merges win.
        self.mesh_min_leaves = int(os.environ.get(
            "PILOSA_TPU_MESH_MIN_LEAVES", "8"))
        # Calibrated device/host routing (parallel.costmodel): above the
        # static floor, a measured cost model can still veto the device
        # when the host path is a clear predicted win on this hardware.
        # Injectable for tests; PILOSA_TPU_COST_MODEL=0 disables.
        self.cost_model = None
        self._cost_model_enabled = os.environ.get(
            "PILOSA_TPU_COST_MODEL", "1") != "0"
        self._cost_margin = float(os.environ.get(
            "PILOSA_TPU_COST_MARGIN", "0.5"))
        # Deliberate host routings by the cost model (observability —
        # distinct from device_fallbacks, which count failures).
        self.cost_vetoes = 0
        self._mesh = None  # lazy: built on first device-batched call
        self._mesh_failed_until = None  # backoff after backend failure
        # Device-fallback observability (a real kernel bug would
        # otherwise silently demote every query to the host path):
        # counted per executor, surfaced via stats + one-shot warning.
        self.device_fallbacks = 0
        self._fallback_warned = False
        # Persistent worker pools (created lazily on first fan-out).
        # Spawning a ThreadPoolExecutor per query cost more than the
        # whole host-side map at small fan-outs. Three tiers because a
        # task in one tier blocks on the tier below (node mapper →
        # pod legs → slice map); a single shared pool could deadlock.
        self._pools: dict[str, ThreadPoolExecutor] = {}
        self._pools_mu = threading.Lock()
        # Materialized bitmap-result residency (see _bitmap_result_key).
        # Bounds are configurable ([query] result-cache-* /
        # PILOSA_QUERY_RESULT_CACHE_*); the class attrs stay the
        # defaults for bare executors.
        self._bitmap_results: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._bitmap_results_mu = threading.Lock()
        if result_cache_entries is None:
            result_cache_entries = int(os.environ.get(
                "PILOSA_QUERY_RESULT_CACHE_ENTRIES",
                str(self._RESULT_CACHE_ENTRIES)))
        if result_cache_bits is None:
            result_cache_bits = int(os.environ.get(
                "PILOSA_QUERY_RESULT_CACHE_BITS",
                str(self._RESULT_CACHE_BITS)))
        self._result_cache_entries = result_cache_entries
        self._result_cache_bits = result_cache_bits
        # Coordinator hot-query result cache (the first cluster-wide
        # reuse of the generation machinery): merged read-query
        # results keyed by (index, PQL, slice set), validated on hit
        # by a /generations token round-trip per involved peer — so a
        # repeated resident chain over remote slices serves at ~RTT
        # floor instead of re-running the fan-out + fold. 0 disables.
        if cluster_cache_entries is None:
            cluster_cache_entries = int(os.environ.get(
                "PILOSA_QUERY_CLUSTER_CACHE_ENTRIES", "64"))
        self._cluster_cache_entries = cluster_cache_entries
        self._cluster_cache: "OrderedDict[tuple, dict]" = OrderedDict()
        self._cluster_cache_mu = threading.Lock()
        # Hit-validation probe budget (seconds): a probe is an
        # optimization, so a slow/stalled peer costs at most this
        # before the entry drops and the real fan-out (with its
        # failover machinery) answers.
        self._CLUSTER_PROBE_TIMEOUT_S = 1.0
        # Distributed TopN pushdown (ROADMAP item 3): remote legs run
        # the single-pass TopN over their own slices and the
        # coordinator merges partials per the reference two-phase
        # semantics. Off => the plain candidate fan-out path.
        self._topn_pushdown = os.environ.get(
            "PILOSA_TPU_TOPN_PUSHDOWN", "1") != "0"
        # Speculative hint memo: (index, frame) -> the last merged
        # candidate union (bounded), dispatched with pushdown legs so
        # the steady state needs ONE overlapped round trip. Purely
        # advisory — a stale or missing entry costs an extra round,
        # never correctness.
        self._topn_hint_memo: "OrderedDict[tuple, tuple]" = \
            OrderedDict()
        # Per-op write fast lane (see _execute_mutate_bit): (index,
        # frame, slice) -> (frame_obj, Fragment), validated per op by
        # identity of the CURRENT frame object and the fragment's
        # _open flag — a deleted or recreated frame closes its
        # fragments, which forces re-resolution.
        self._wfast_frag: dict[tuple, tuple] = {}
        # Cost-based planner (pilosa_tpu.plan): consulted once per
        # read query before the cluster-cache key — reorder,
        # short-circuit, CSE via the token-keyed subresult cache, and
        # per-subtree placement. The per-executor flag plus the module
        # switch (plan.record.set_enabled / PILOSA_TPU_PLANNER=0)
        # restore the unplanned dispatcher for A/B measurement.
        self.planner = plan_planner.Planner(holder,
                                            margin=self._cost_margin)
        self.planner_enabled = True
        # Per-fingerprint plan store behind GET /debug/plans (the
        # handler records finished coordinator queries into it).
        self.plan_store = plan_store.PlanStore()

    def _pool(self, tier: str) -> ThreadPoolExecutor:
        with self._pools_mu:
            pool = self._pools.get(tier)
            size = self.max_workers
            if tier == "hedge":
                # Primaries AND their hedge legs share this tier, and
                # every node-tier remote leg parks one primary here —
                # at 1× the node tier's size, hedge legs would queue
                # behind the very primaries they are racing (and a
                # queued primary's wait(hedge_s) would expire on queue
                # delay alone, firing spurious hedges under load).
                size *= 2
            if tier == "pod" and self.pod is not None:
                # Pod legs must all run concurrently — latency is
                # the max over legs, not the sum (the old per-query
                # pool sized itself to the leg count). If the peer set
                # has grown since the pool was built, grow with it: a
                # too-small pool serializes legs (no deadlock — pod
                # legs only block on the tier below — just latency).
                size = max(size, len(self.pod.peers))
            if pool is not None and pool._max_workers < size:
                # Don't shutdown(): a concurrent query may still hold a
                # reference and submit to it — shutdown would fail that
                # query with RuntimeError. Dropped pools drain naturally
                # (their idle threads exit when the pool is collected).
                self._pools.pop(tier)
                pool = None
            if pool is None:
                pool = self._pools[tier] = ThreadPoolExecutor(
                    max_workers=size,
                    thread_name_prefix=f"pilosa-exec-{tier}")
            return pool

    def close(self) -> None:
        """Shut down the worker pools (idempotent; the executor remains
        usable afterwards — pools are recreated on demand)."""
        with self._pools_mu:
            pools, self._pools = dict(self._pools), {}
        for pool in pools.values():
            pool.shutdown(wait=False, cancel_futures=True)

    def __del__(self):
        # Idle pool threads also exit when the executor is collected
        # (worker threads hold only a weakref to their pool), but bare
        # Executors that stay referenced would otherwise pin threads
        # for process lifetime — reclaim eagerly. Swallow everything:
        # at interpreter shutdown, pool internals may be torn down.
        try:
            self.close()
        except Exception:
            pass

    def _note_device_fallback(self, where: str, exc: Exception) -> None:
        self.device_fallbacks += 1
        stats = getattr(self.holder, "stats", None)
        if stats is not None:
            stats.count("deviceFallback", 1)
        if not self._fallback_warned:
            self._fallback_warned = True
            import logging
            logging.getLogger("pilosa_tpu.executor").warning(
                "device mesh path failed in %s (%s: %s); falling back to "
                "the host per-slice path — further fallbacks are counted "
                "but not logged", where, type(exc).__name__, exc)

    # Seconds to serve host-side before re-probing a failed device
    # backend (tunnel/pool outages are transient; a server started
    # during one should pick the device back up without a restart).
    _MESH_RETRY_S = 300.0

    def _mesh_backoff_active(self) -> bool:
        """True inside the backoff window after a device-backend
        failure. Device-eligible gates consult this before compiling a
        device expr so a meshless host serves the backoff window with
        zero discarded work (the compile is cheap, but it is pure waste
        when _mesh_or_none is known to return None)."""
        if self._mesh is not None or self._mesh_failed_until is None:
            return False
        return time.monotonic() < self._mesh_failed_until

    def _mesh_or_none(self):
        if not self.use_mesh:
            return None
        if self._mesh is None:
            if self._mesh_backoff_active():
                return None  # inside the backoff window: host path
            try:
                from .parallel import mesh as mesh_mod
                self._mesh = mesh_mod.make_mesh()
                self._mesh_failed_until = None
                # Failure is cyclic under retry (outage → recovery →
                # outage); re-arm the one-shot log for the next one.
                self._fallback_warned = False
            except Exception as e:  # noqa: BLE001 - backend unavailable
                self._mesh_failed_until = (time.monotonic()
                                           + self._MESH_RETRY_S)
                self._note_device_fallback("make_mesh", e)
                return None
        return self._mesh

    # -- entry point (executor.go:62-143) ------------------------------------

    def execute_partial(self, index: str, query,
                        slices: Optional[list[int]] = None,
                        opt: Optional[ExecOptions] = None
                        ) -> tuple[list, Optional[Exception]]:
        """Like execute(), but an exception mid-query returns
        (results-so-far, error) instead of raising — callers that
        combine independent call streams (the HTTP pipelined batch
        lane) can then map the prefix faithfully: calls before the
        error were durably applied, calls after it never ran. An
        all-SetRowAttrs query is refused (its bulk path applies
        non-positionally, so a prefix would be meaningless)."""
        if isinstance(query, str):
            query = parse_pql(query)
        if _has_only_set_row_attrs(query.calls):
            raise PilosaError("execute_partial: bulk attrs unsupported")
        results: list = []
        try:
            self.execute(index, query, slices, opt, _partial_out=results)
        except Exception as e:  # noqa: BLE001 - contract: return it
            return results, e
        return results, None

    def execute(self, index: str, query, slices: Optional[list[int]] = None,
                opt: Optional[ExecOptions] = None,
                _partial_out: Optional[list] = None) -> list:
        opt = opt or ExecOptions()
        if opt.ctx is None:
            return self._execute(index, query, slices, opt, _partial_out)
        # Lifecycle-bound query: check the budget up front and bind the
        # context to this thread so layers without a ctx argument (the
        # mesh device dispatch reached from this thread, e.g. the
        # batched-Count lane) can check it too.
        opt.ctx.check()
        with sched_context.use(opt.ctx):
            return self._execute(index, query, slices, opt, _partial_out)

    def _execute(self, index: str, query, slices: Optional[list[int]],
                 opt: ExecOptions,
                 _partial_out: Optional[list] = None) -> list:
        if not index:
            raise PilosaError("index required")
        if isinstance(query, str):
            # Fused point-mutation lane (ISSUE 8): the per-op serving
            # string goes regex -> cached fragment -> set_bit in one
            # step, skipping AST construction and call dispatch —
            # those cost ~3x the mutate itself at per-op rates. Any
            # miss (cold cache, unusual frame/cluster shape) falls
            # through to the identical generic path, which also
            # populates the cache.
            if _partial_out is None and self.pod is None:
                m = _POINT_MUTATE_RE.match(query)
                if m is not None:
                    r = self._point_mutate_fast(index, m, opt)
                    if r is not None:
                        return r
            query = parse_pql(query)
        if not isinstance(query, Query):
            raise QueryRequiredError("query required")

        calls = query.calls
        if (len(calls) == 1 and _partial_out is None
                and calls[0].name in ("SetBit", "ClearBit")):
            # Single point mutation — the per-op serving shape. Skip
            # the multi-call preamble (slice enumeration, batch-run
            # probes): a write call needs no slice list, and
            # _execute_mutate_bit owns its whole contract including
            # its own fast lane.
            if opt.ctx is not None:
                opt.ctx.check()
            return [self._execute_mutate_bit(
                index, calls[0], opt, calls[0].name == "SetBit")]

        needs = _needs_slices(query.calls)
        inverse_slices: list[int] = []
        column_label = "columnID"
        # Inverse-slice substitution happens only when WE computed the
        # slice lists. A forwarded (remote) query arrives with the exact
        # slice ids the coordinator already selected — replacing them
        # would wrongly empty inverse legs.
        computed_slices = not slices
        if not slices and needs:
            idx = self.holder.index(index)
            if idx is None:
                raise IndexNotFoundError(index)
            slices = list(range(idx.max_slice() + 1))
            inverse_slices = list(range(idx.max_inverse_slice() + 1))
            column_label = idx.column_label
        slices = slices or []

        if _has_only_set_row_attrs(query.calls):
            return self._execute_bulk_set_row_attrs(index, query.calls, opt)

        # Cost-based planning (pilosa_tpu.plan): read queries are
        # rewritten BEFORE the cluster-cache key is computed, so the
        # cache keys the planned canonical form. Planning failure is
        # never a query failure — the original tree executes.
        plan_rec = None
        if needs and slices:
            query, plan_rec = self._maybe_plan(index, query, slices,
                                               opt)

        # Coordinator hot-query result cache (cluster.generations):
        # repeated read queries over a distributed slice set serve at
        # ~RTT floor — one /generations token probe per involved peer
        # instead of the full fan-out + fold — with a token mismatch
        # (any replica took a write) invalidating the entry.
        cluster_key = pre_tokens = None
        if _partial_out is None:
            cluster_key = self._cluster_cache_key(index, query, slices,
                                                  opt)
            if cluster_key is not None:
                hit = self._cluster_cache_lookup(cluster_key, index,
                                                 opt)
                if hit is not None:
                    return hit
                pre_tokens = self._cluster_cache_snapshot(index,
                                                          slices)

        results = _partial_out if _partial_out is not None else []
        i = 0
        while i < len(query.calls):
            if opt.ctx is not None:
                opt.ctx.check()  # between calls of a multi-call query
            # Consecutive device-compilable calls (Counts, exact-count
            # TopNs) fuse into ONE device program — the whole multi-op
            # tree pays one dispatch (one sync), not one per call.
            batch = self._device_batch_run(index, query.calls, i,
                                           slices, opt)
            if batch is not None:
                batch_results, n = batch
                results.extend(batch_results)
                i += n
                continue
            # Consecutive SetBit/ClearBit calls batch into one native
            # crossing + WAL group-commit per touched fragment.
            wbatch = self._mutate_batch_run(index, query.calls, i, opt)
            if wbatch is not None:
                bools, n = wbatch
                results.extend(bools)
                i += n
                continue
            call = query.calls[i]
            call_slices = slices
            if call.supports_inverse() and needs and computed_slices:
                frame_name = call.args.get("frame") or DEFAULT_FRAME
                frame = self.holder.frame(index, frame_name)
                if frame is None:
                    raise FrameNotFoundError(frame_name)
                if call.is_inverse(frame.row_label, column_label):
                    call_slices = inverse_slices
            analyze_call = (plan_rec is not None
                            and (plan_rec.sample or plan_rec.analyze))
            if analyze_call:
                t_call = time.perf_counter()
            r = self._execute_call(index, call, call_slices, opt)
            if analyze_call:
                self._plan_record_actual(call, r,
                                         time.perf_counter() - t_call,
                                         plan_rec)
            results.append(r)
            i += 1
        if cluster_key is not None:
            self._cluster_cache_store(cluster_key, index, slices,
                                      results, pre_tokens)
        return results

    def _execute_call(self, index: str, c: Call, slices: list[int],
                      opt: ExecOptions):
        # executor.go:146-170
        if c.name == "ClearBit":
            return self._execute_clear_bit(index, c, opt)
        if c.name == "Count":
            return self._execute_count(index, c, slices, opt)
        if c.name == "SetBit":
            return self._execute_set_bit(index, c, opt)
        if c.name == "SetRowAttrs":
            self._execute_set_row_attrs(index, c, opt)
            return None
        if c.name == "SetColumnAttrs":
            self._execute_set_column_attrs(index, c, opt)
            return None
        if c.name == "TopN":
            return self._execute_top_n(index, c, slices, opt)
        if c.name in ("Sum", "Min", "Max"):
            return self._execute_field_aggregate(index, c, slices, opt)
        if c.name == "SetFieldValue":
            return self._execute_set_field_value(index, c, opt)
        return self._execute_bitmap_call(index, c, slices, opt)

    # -- cost-based planning (pilosa_tpu.plan) -------------------------------

    def _maybe_plan(self, index: str, query: Query, slices: list[int],
                    opt: ExecOptions):
        """Plan a read query: returns (query', PlanRecord) — the
        planned clone when planning applies, the original (query,
        None) otherwise. The plan tree rides each cloned Call as
        ``_plan_node`` (the per-slice hooks read it without a context
        lookup) and the record attaches to ``ctx.plan`` for the
        observability plane."""
        if (self.planner is None or not self.planner_enabled
                or not plan_record.enabled()):
            return query, None
        if query.write_calls():
            return query, None
        try:
            all_local = self._owns_all_slices(index, slices)
        except Exception:  # noqa: BLE001 - locality is advisory here
            all_local = False
        try:
            planned, rec = self.planner.plan_query_cached(
                index, query.calls, slices, all_local=all_local,
                node=self.host)
        except Exception:  # noqa: BLE001 - planning never fails a query
            return query, None
        for call, node in zip(planned, rec.roots):
            # Memo hits return calls already carrying their plan node.
            if getattr(call, "_plan_node", None) is not node:
                _attach_plan_nodes(call, node)
        ctx = opt.ctx
        if ctx is not None:
            rec.analyze = bool(getattr(ctx, "profile", False))
            ctx.plan = rec
        return Query(planned), rec

    def _plan_record_actual(self, call: Call, result, elapsed_s: float,
                            rec: plan_record.PlanRecord) -> None:
        """ANALYZE half: stamp per-call wall time always, and actual
        cardinality where it is free (Count results) or requested
        (?profile=1 pays one count() walk of the result)."""
        node = getattr(call, "_plan_node", None)
        if node is None:
            return
        node.actual_s = elapsed_s
        try:
            if isinstance(result, int) and not isinstance(result, bool):
                plan_planner._observe_misestimate(node, result)
            elif rec.analyze and hasattr(result, "count"):
                plan_planner._observe_misestimate(node, result.count())
        except Exception:  # noqa: BLE001 - observability only
            pass

    def explain(self, index: str, query,
                slices: Optional[list[int]] = None) -> dict:
        """EXPLAIN-only (?plan=1): plan the query without executing
        and return the plan tree."""
        if isinstance(query, str):
            query = parse_pql(query)
        if not isinstance(query, Query):
            raise QueryRequiredError("query required")
        if query.write_calls():
            raise PilosaError("cannot EXPLAIN a write query")
        if not slices:
            idx = self.holder.index(index)
            if idx is None:
                raise IndexNotFoundError(index)
            slices = list(range(idx.max_slice() + 1))
        try:
            all_local = self._owns_all_slices(index, slices)
        except Exception:  # noqa: BLE001
            all_local = False
        return self.planner.explain(index, query.calls, slices,
                                    all_local=all_local)

    def _owns_all_slices(self, index: str, slices: list[int]) -> bool:
        """True when THIS node holds a replica of every slice the query
        touches — the ownership gate that keeps the single-node fast
        paths (materialized-result residency, the fused device count
        fold, single-pass TopN) live on multi-node clusters for
        locally-owned work (round-5 VERDICT: the old ``nodes != 1``
        gates disabled them the moment a second node joined, even with
        replica_n covering everything). Correctness rests on the write
        path: every SetBit/import/anti-entropy leg applies to EVERY
        replica owner, so an owned slice's local fragment (and its
        mutation generation, for the residency keys) tracks all
        writes. During an elastic resize this is READ authority, not
        the write-accept union — a stream target's copy is incomplete
        until the flip, so it must not claim local fast paths for a
        moving slice (cluster.topology.read_allowed)."""
        q = getattr(self.holder, "quarantine", None)
        if q is not None and len(q) and any(
                q.slice_blocked(index, s) for s in slices):
            # Storage integrity: a quarantined local copy must never
            # claim a fast path — its bytes (or its fresh post-reset
            # replacement) cannot be trusted to answer.
            return False
        tier = getattr(self.holder, "tier", None)
        if tier is not None and any(
                tier.slice_blocked(index, s) for s in slices):
            # Tiered storage: a blob-tier fragment whose cold fetch
            # keeps failing has NO local bytes — the slice must fail
            # over (or degrade per the partial contract), same as a
            # quarantine.
            return False
        if (len(self.cluster.nodes) == 1
                and self.cluster.resize is None):
            return True
        host = self.host
        allowed = self.cluster.read_allowed
        return all(allowed(host, index, s) for s in slices)

    # -- coordinator hot-query result cache (cluster.generations) -----------

    def _share_cached(self, r):
        """COW/shallow handout of one cached query result."""
        if isinstance(r, Bitmap):
            return self._share_result(r)
        if isinstance(r, list):
            return list(r)
        return r

    def _cluster_cache_key(self, index: str, query: Query,
                           slices: list[int],
                           opt: ExecOptions) -> Optional[tuple]:
        """(index, PQL, slice set) when this query is cluster-cache
        eligible: a coordinator-side read over a slice set NOT fully
        owned here (the covered case belongs to the local fast
        paths), with a generation map + probe-capable client to
        validate against. Declines: top-level Bitmap calls (their
        results carry row/column ATTRS, and attribute writes don't
        bump fragment generations — a cached copy could serve stale
        attrs) and attr-filtered TopN forms (same blind spot), and
        anything inverse-shaped at the top level (it swaps in the
        inverse slice list, which the per-slice token snapshot
        doesn't span)."""
        if (self._cluster_cache_entries <= 0 or self.gens is None
                or self.client is None
                or not hasattr(self.client, "generations")
                or self.pod is not None or opt.remote or opt.partial
                or not slices or len(self.cluster.nodes) < 2
                or self.cluster.resize is not None):
            # An in-flight resize declines caching outright: moving
            # slices' serving peers are in flux and a token snapshot
            # cannot attribute results to one placement.
            return None
        for call in query.calls:
            if (call.name in _WRITE_CALLS or call.name == "Bitmap"
                    or call.args.get("filters")):
                return None
        if self._owns_all_slices(index, slices):
            return None
        # The placement epoch is part of the key: after a resize flips,
        # a moved slice is served by a DIFFERENT peer whose fresh uid
        # must never validate an entry cached under the old owner's
        # tokens (the old owner's copy freezes and would validate
        # forever).
        return (index, str(query), tuple(slices), self.cluster.epoch)

    def _cluster_cache_lookup(self, key: tuple, index: str,
                              opt: ExecOptions) -> Optional[list]:
        with self._cluster_cache_mu:
            ent = self._cluster_cache.get(key)
        if ent is None:
            obs_metrics.CLUSTER_CACHE_REQUESTS.labels("miss").inc()
            return None
        if self._cluster_cache_validate(ent, index, opt):
            with self._cluster_cache_mu:
                if key in self._cluster_cache:
                    self._cluster_cache.move_to_end(key)
            obs_metrics.CLUSTER_CACHE_REQUESTS.labels("hit").inc()
            obs_accounting.note_result_cache_hit(opt.ctx)
            return [self._share_cached(r) for r in ent["results"]]
        with self._cluster_cache_mu:
            self._cluster_cache.pop(key, None)
        obs_metrics.CLUSTER_CACHE_REQUESTS.labels("invalidated").inc()
        return None

    def _cluster_cache_validate(self, ent: dict, index: str,
                                opt: ExecOptions) -> bool:
        """True iff every generation token the entry was cached under
        still holds: local slices against live fragments, remote
        slices against a fresh /generations probe of the peer that
        served them (~RTT, the whole point). Any mismatch or
        unreachable peer reads as invalid — never a stale answer."""
        from .cluster import generations as gens_mod
        for s, toks in ent["local"].items():
            if gens_mod.slice_tokens(self.holder, index, s) != toks:
                return False
        remote: dict = ent["remote"]
        if not remote:
            return True
        ctx = opt.ctx
        # The probe is an OPTIMIZATION: bound it tightly, far below
        # the query budget — a stalled peer must cost at most this
        # before the real fan-out (which owns failover) takes over. A
        # probe timing out is a failed validation, NOT the query's
        # deadline; ctx.check() below re-raises only when the query
        # itself is actually dead.
        timeout = self._CLUSTER_PROBE_TIMEOUT_S
        if ctx is not None:
            remaining = ctx.remaining()
            if remaining is not None:
                timeout = min(timeout, remaining)

        def probe(peer, entry):
            got = self.client.generations(index, sorted(entry),
                                          host=peer,
                                          deadline_s=timeout)
            return all(got.get(s) == toks
                       for s, toks in entry.items())

        try:
            items = list(remote.items())
            if len(items) == 1:
                return probe(*items[0])
            pool = self._pool("node")
            futs = [pool.submit(probe, p, e) for p, e in items]
            ok = True
            try:
                for f in futs:
                    if not f.result():
                        ok = False
            finally:
                pending = [f for f in futs if not f.cancel()]
                if pending:
                    wait(pending)
            return ok
        except (QueryDeadlineError, QueryCancelledError):
            if ctx is not None:
                ctx.check()  # the QUERY is dead → propagate
            return False  # only the bounded probe expired: recompute
        except Exception:  # noqa: BLE001 - unreachable peer: recompute
            return False

    def _cluster_cache_snapshot(self, index: str,
                                slices: list[int]) -> Optional[dict]:
        """Pre-execution token snapshot: live fragment tokens for
        locally-owned slices, and for remote slices the map's
        freshest-known (peer, tokens) — from the PREVIOUS exchange
        with the peer. A remote slice the map has never seen returns
        None (the query can't be cached this round; its own legs
        populate the map for the next one)."""
        from .cluster import generations as gens_mod
        # READ authority (see _bitmap_result_key): a moved slice this
        # node merely write-accepts during the post-resize grace must
        # snapshot the SERVING owner's tokens, never the local frozen
        # copy's.
        owns = self.cluster.read_allowed
        local: dict = {}
        remote: dict = {}
        for s in slices:
            if owns(self.host, index, s):
                local[s] = gens_mod.slice_tokens(self.holder, index, s)
                continue
            got = self.gens.newest(index, s)
            if got is None:
                return None
            peer, toks, _ts = got
            # The freshest map entry can belong to a peer that no
            # longer SERVES the slice (an old owner whose copy froze
            # at a resize finalize): an entry snapshotted under its
            # tokens would validate forever. Only read-authoritative
            # peers key cache entries; otherwise the query stays
            # uncached this round.
            if not self.cluster.read_allowed(peer, index, s):
                return None
            remote.setdefault(peer, {})[s] = dict(toks)
        return {"local": local, "remote": remote}

    def _cluster_cache_store(self, key: tuple, index: str,
                             slices: list[int], results: list,
                             pre: Optional[dict]) -> None:
        """Cache a completed read's merged results under the
        PRE-EXECUTION token snapshot, and only when the tokens are
        STABLE across the query (post-execution state identical): a
        generation that moved mid-query — a write racing the legs'
        reads, whichever side of them it landed on — means the
        results can't be attributed to one token state, so they stay
        uncached rather than risk a snapshot that validates forever
        against data the legs never saw (review finding). The stable
        case is exactly the one where the legs' reads provably fall
        inside an unchanged-generation window."""
        if pre is None:
            return
        bits = 0
        for r in results:
            if isinstance(r, Bitmap):
                bits += r.count()
        if bits > self._result_cache_bits:
            return
        post = self._cluster_cache_snapshot(index, slices)
        if post != pre:
            return
        ent = {"results": [self._share_cached(r) for r in results],
               "local": pre["local"], "remote": pre["remote"]}
        with self._cluster_cache_mu:
            cache = self._cluster_cache
            cache[key] = ent
            cache.move_to_end(key)
            # Per-tenant quota first (tenant = key[0], the index):
            # a tenant past its share evicts ITS OWN oldest entries,
            # never another tenant's.
            share = self._cache_share(key[0])
            if share < 1.0:
                cap = max(1, int(self._cluster_cache_entries * share))
                mine = [k for k in cache if k[0] == key[0]]
                for k in mine[:max(0, len(mine) - cap)]:
                    cache.pop(k, None)
            while len(cache) > self._cluster_cache_entries:
                cache.popitem(last=False)

    def on_resize_change(self, moved_fn=None) -> None:
        """Called on every resize transition this node observes
        (prepare / flip / finalize / abort — server.receive_message).
        Drops cached artifacts whose placement assumptions a resize
        breaks: the write fast-lane fragment cache (its single-node
        precondition), and — given ``moved_fn(index, slice) -> bool``
        — every result-residency and cluster-cache entry touching a
        moved slice (ISSUE 12 satellite: a moved slice served by a
        new peer with a fresh uid must never validate a stale entry;
        the epoch baked into both key shapes is the backstop, this is
        the eager flush)."""
        self._wfast_frag.clear()
        if moved_fn is None:
            return
        with self._bitmap_results_mu:
            for key in [k for k in self._bitmap_results
                        if any(moved_fn(k[0], s) for s in k[2])]:
                self._bitmap_results.pop(key, None)
        with self._cluster_cache_mu:
            for key in [k for k in self._cluster_cache
                        if any(moved_fn(k[0], s) for s in k[2])]:
                self._cluster_cache.pop(key, None)

    # -- bitmap expressions (executor.go:192-570) ----------------------------

    # Materialized-result residency (VERDICT r4 item 5): completed
    # Union/Intersect/Difference results stay cached keyed by
    # (expression, per-fragment generations), so a repeated chain pays
    # zero re-fold and zero repack — the reference's own
    # lazy-materialization trick is its COW segments (bitmap.go:384-392);
    # this is the same idea one level up. Bounded by entries AND total
    # cached bits.
    _RESULT_CACHE_ENTRIES = 8
    _RESULT_CACHE_BITS = 32 << 20

    def _primary_owner_host(self, index: str, slice: int
                            ) -> Optional[str]:
        """The replica owner _slices_by_node would consult first for
        this slice (fault-ordered when a fault manager is attached) —
        the peer whose generation tokens a remote-slice cache key
        should embed, since it is the peer most likely to serve the
        recompute."""
        owners = self.cluster.fragment_nodes(index, slice)
        if not owners:
            return None
        if self.fault is not None and len(owners) > 1:
            owners = self.fault.order_nodes(owners, local=self.host)
        return owners[0].host

    def _bitmap_result_key(self, index: str, c: Call,
                           slices: list[int],
                           compiled_out: Optional[list] = None):
        """Cache key embedding every input fragment's mutation
        generation, or None when the call/topology isn't cacheable.
        Locally-owned slices key on the live fragment's (uid,
        generation) (every replica-fanned write bumps it); slices
        owned ELSEWHERE key on the owner's tokens from the coordinator
        generation map (cluster.generations) within the bounded
        staleness window — the map refreshes on every exchange with
        the peer (query legs, import acks, probes), so a write routed
        through this coordinator invalidates on its own response and
        only out-of-band writes ride the staleness bound. An unknown
        or stale token means uncached, never a guess. The compiled
        (expr, leaves) is appended to ``compiled_out`` so the device
        fold reuses it instead of re-walking the call tree
        (1000-child Unions pay the walk once, review r5)."""
        if c.name not in ("Union", "Intersect", "Difference"):
            return None
        if self.pod is not None:
            return None
        if self.cluster.resize is not None:
            # In-flight resize: moving slices' serving peers are in
            # flux (double-reads, mid-flip ownership) — uncached until
            # the epoch settles.
            return None
        owner_of: dict[int, str] = {}
        if len(self.cluster.nodes) > 1:
            # READ authority, not the write-accept union: inside the
            # post-resize grace window an old owner still write-
            # ACCEPTS a moved slice, but its copy no longer receives
            # single-path writes — keying on the frozen local fragment
            # would validate stale results forever (caught by the
            # resize verify drive).
            owns = self.cluster.read_allowed
            host = self.host
            for s in slices:
                if owns(host, index, s):
                    continue
                if self.gens is None:
                    return None  # invisible generations: uncached
                peer = self._primary_owner_host(index, s)
                if peer is None or peer == host:
                    return None
                owner_of[s] = peer
        leaves: list[tuple] = []
        expr = self._compile_device_expr(index, c, leaves)
        if expr is None or not leaves:
            return None
        if compiled_out is not None:
            compiled_out.append((expr, leaves))
        if len(leaves) * len(slices) > (1 << 16):
            return None  # key construction would outweigh the win
        gens = []
        for frame, view, _row in leaves:
            for s in slices:
                peer = owner_of.get(s)
                if peer is not None:
                    tok = self.gens.token(
                        peer, index, frame, view, s,
                        max_age_s=self._gen_staleness_s)
                    if tok is None:
                        return None  # unknown/stale: uncached
                    gens.append((peer, tok[0], tok[1]))
                    continue
                f = self.holder.fragment(index, frame, view, s)
                gens.append(("", f.device.uid, f.device.generation)
                            if f is not None else ("", 0, 0))
        # Epoch in the key: a slice that moved in a resize is served
        # by a different peer afterwards — entries keyed under the old
        # epoch's owners must never match post-flip lookups.
        return (index, expr, tuple(slices), tuple(gens),
                self.cluster.epoch)

    def _share_result(self, bm: Bitmap) -> Bitmap:
        """COW handout of a cached result (mutating callers copy,
        never the cached object)."""
        out = Bitmap()
        out.attrs = dict(bm.attrs)
        for seg in bm.segments:
            out.segments.append(BitmapSegment(seg.data.shared(),
                                              seg.slice, False))
        return out

    def _cache_share(self, tenant: str) -> float:
        """The fraction of each cache budget this tenant (= index) may
        occupy — 1.0 without a tenant registry (single pool)."""
        if self.tenants is None:
            return 1.0
        return self.tenants.policy(tenant).cache_share

    def _result_cache_put(self, key, bm: Bitmap) -> None:
        bits = bm.count()
        share = self._cache_share(key[0])
        tenant_budget = int(self._result_cache_bits * share)
        if bits > min(self._result_cache_bits, tenant_budget):
            return
        evicted_n = 0
        with self._bitmap_results_mu:
            cache = self._bitmap_results
            cache[key] = (bm, bits)
            cache.move_to_end(key)
            if share < 1.0:
                # Per-tenant byte quota: the inserting tenant evicts
                # its OWN LRU entries down to its share before the
                # global bound runs — a hot aggressor can fill its
                # slice of the cache, never the whole pool.
                mine = [(k, b) for k, (_, b) in cache.items()
                        if k[0] == key[0]]
                mine_total = sum(b for _, b in mine)
                for k, b in mine:
                    if mine_total <= tenant_budget or k == key:
                        break
                    cache.pop(k, None)
                    mine_total -= b
                    evicted_n += 1
            total = sum(b for _, b in cache.values())
            while (len(cache) > self._result_cache_entries
                   or total > self._result_cache_bits) and len(cache) > 1:
                _, (_, evicted) = cache.popitem(last=False)
                total -= evicted
                evicted_n += 1
        if evicted_n:
            obs_metrics.RESULT_CACHE_EVICTIONS.inc(evicted_n)

    def tenant_cache_usage(self) -> dict:
        """Per-tenant cache residency for /debug/tenants and the
        ``pilosa_tenant_cache_bytes`` scrape refresh: result-residency
        bits (reported as bytes, bits/8) + cluster-cache entry
        counts, keyed by tenant (= index)."""
        out: dict[str, dict] = {}
        with self._bitmap_results_mu:
            for k, (_, bits) in self._bitmap_results.items():
                ent = out.setdefault(k[0], {"resultEntries": 0,
                                            "resultBits": 0,
                                            "clusterEntries": 0})
                ent["resultEntries"] += 1
                ent["resultBits"] += bits
        with self._cluster_cache_mu:
            for k in self._cluster_cache:
                ent = out.setdefault(k[0], {"resultEntries": 0,
                                            "resultBits": 0,
                                            "clusterEntries": 0})
                ent["clusterEntries"] += 1
        for ent in out.values():
            ent["bytes"] = ent["resultBits"] // 8
        return out

    def _execute_bitmap_call(self, index: str, c: Call, slices: list[int],
                             opt: ExecOptions) -> Bitmap:
        pnode = getattr(c, "_plan_node", None)
        if pnode is not None and pnode.short_circuit:
            obs_metrics.PLANNER_DECISIONS.labels("short_circuit_hit").inc()
            return Bitmap()
        compiled: list = []
        key = self._bitmap_result_key(index, c, slices, compiled)
        if key is not None:
            with self._bitmap_results_mu:
                hit = self._bitmap_results.get(key)
                if hit is not None:
                    self._bitmap_results.move_to_end(key)
            if hit is not None:
                obs_metrics.RESULT_CACHE_HITS.inc()
                obs_accounting.note_result_cache_hit(opt.ctx)
                return self._share_result(hit[0])
            obs_metrics.RESULT_CACHE_MISSES.inc()

        def map_fn(slice):
            return self._bitmap_call_slice(index, c, slice)

        def reduce_fn(prev, v):
            if prev is None:
                prev = Bitmap()
            prev.merge(v)
            return prev

        local_fn = self._bitmap_local_device_fn(
            index, c, opt, compiled=compiled[0] if compiled else None)
        bm = self._map_reduce(index, slices, c, opt, map_fn, reduce_fn,
                              local_fn=local_fn)
        if bm is None:
            bm = Bitmap()
        if c.name == "Bitmap":
            self._attach_bitmap_attrs(index, c, bm)
        if key is not None:
            self._result_cache_put(key, bm)
            return self._share_result(bm)
        return bm

    def _attach_bitmap_attrs(self, index: str, c: Call, bm: Bitmap) -> None:
        # executor.go:215-249: column attrs if the column label was used,
        # row attrs otherwise.
        idx = self.holder.index(index)
        if idx is None:
            return
        column_id, col_ok = c.uint_arg(idx.column_label)
        if col_ok:
            bm.attrs = idx.column_attr_store.attrs(column_id)
            return
        frame = idx.frame(c.args.get("frame") or DEFAULT_FRAME)
        if frame is not None:
            row_id, ok = c.uint_arg(frame.row_label)
            if ok:
                bm.attrs = frame.row_attr_store.attrs(row_id)

    def _bitmap_call_slice(self, index: str, c: Call, slice: int) -> Bitmap:
        # Plan consult (when the call was planned): proven-empty
        # subtrees return without touching storage, and CSE-marked
        # interior nodes go through the generation-token-keyed
        # subresult cache. The cache key embeds the (uid, generation)
        # token of every fragment the subtree reads, so a write
        # between queries changes the key — stale entries are never
        # served, they just age out of the LRU.
        node = getattr(c, "_plan_node", None)
        if node is not None:
            if node.short_circuit:
                return Bitmap()
            if node.cache_lookup and self.planner is not None:
                try:
                    key = self.planner.subresult_key(index, node, slice)
                except Exception:  # noqa: BLE001 - cache is best-effort
                    key = None
                if key is not None:
                    hit = self.planner.subresults.get(key)
                    if hit is not None:
                        return self._share_result(hit)
                    r = self._bitmap_slice_dispatch(index, c, slice)
                    if node.cache_store:
                        try:
                            self.planner.subresults.put(
                                key, self._share_result(r), r.count())
                        except Exception:  # noqa: BLE001
                            pass
                    return r
        return self._bitmap_slice_dispatch(index, c, slice)

    def _bitmap_slice_dispatch(self, index: str, c: Call,
                               slice: int) -> Bitmap:
        # executor.go:253-268
        if c.name == "Bitmap":
            return self._bitmap_slice(index, c, slice)
        if c.name == "Difference":
            return self._fold_slice(index, c, slice, "difference",
                                    require_children=True)
        if c.name == "Intersect":
            return self._fold_slice(index, c, slice, "intersect",
                                    require_children=True)
        if c.name == "Range":
            return self._range_slice(index, c, slice)
        if c.name == "Union":
            return self._fold_slice(index, c, slice, "union",
                                    require_children=False)
        raise PilosaError(f"unknown call: {c.name}")

    _HOST_FOLD_OPS = {"union": "or", "intersect": "and",
                      "difference": "andnot"}

    def _fold_slice(self, index: str, c: Call, slice: int, op: str,
                    require_children: bool) -> Bitmap:
        if require_children and not c.children:
            raise PilosaError(f"empty {c.name} query is currently"
                              " not supported")
        # Wide folds whose children are all plain Bitmap rows of one
        # (frame, view) collapse to ONE vectorized pass over the
        # fragment (fold_rows) instead of a roaring merge per child —
        # measured ~10× on the 1000-row config-2 shape. Narrow folds
        # and mixed/nested children keep the per-child merge, which
        # also owns all the error semantics.
        if len(c.children) >= self.mesh_min_leaves:
            plain = self._plain_fold_leaves(index, c)
            if plain is not None:
                frame_name, view, rids = plain
                frag = self.holder.fragment(index, frame_name, view,
                                            slice)
                if frag is None:
                    return Bitmap()
                if frag.fold_scan_pays(rids):
                    from .storage import roaring
                    out = Bitmap()
                    cols = frag.fold_rows(self._HOST_FOLD_OPS[op], rids)
                    if len(cols):
                        base = np.uint64(slice) * np.uint64(SLICE_WIDTH)
                        out.add_segment(
                            roaring.Bitmap.from_sorted(cols + base),
                            slice, writable=True)
                    return out
        out = Bitmap()
        for i, child in enumerate(c.children):
            bm = self._bitmap_call_slice(index, child, slice)
            out = bm if i == 0 else getattr(out, op)(bm)
        return out

    def _plain_fold_leaves(self, index: str, c: Call):
        """(frame, view, row ids) when every child is a plain Bitmap
        leaf of one (frame, view); None otherwise (the per-child path
        owns errors and mixed shapes)."""
        leaves: list[tuple] = []
        frame_view = None
        rids = []
        for child in c.children:
            expr = self._compile_device_expr(index, child, leaves)
            if expr is None or expr[0] != "leaf":
                return None
            frame_name, view, rid = leaves[expr[1]]
            if frame_view is None:
                frame_view = (frame_name, view)
            elif frame_view != (frame_name, view):
                return None
            rids.append(rid)
        if frame_view is None:
            return None
        return frame_view[0], frame_view[1], rids

    def _bitmap_slice(self, index: str, c: Call, slice: int) -> Bitmap:
        # executor.go:420-465: row id → standard view, column id → inverse.
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError(index)
        frame_name = c.args.get("frame") or DEFAULT_FRAME
        frame = idx.frame(frame_name)
        if frame is None:
            raise FrameNotFoundError(frame_name)
        row_id, row_ok = c.uint_arg(frame.row_label)
        col_id, col_ok = c.uint_arg(idx.column_label)
        if row_ok and col_ok:
            raise PilosaError(
                f"Bitmap() cannot specify both {frame.row_label} and"
                f" {idx.column_label} values")
        if not row_ok and not col_ok:
            raise PilosaError(
                f"Bitmap() must specify either {frame.row_label} or"
                f" {idx.column_label} values")
        view, id = VIEW_STANDARD, row_id
        if col_ok:
            view, id = VIEW_INVERSE, col_id
            if not frame.inverse_enabled:
                raise PilosaError("Bitmap() cannot retrieve columns unless"
                                  " inverse storage enabled")
        frag = self.holder.fragment(index, frame_name, view, slice)
        if frag is None:
            return Bitmap()
        return frag.row(id)

    def _range_views(self, index: str, c: Call, strict: bool):
        """Resolve a Range call to ``(frame_name, row_id, view_names)``
        — the minimal time-view cover (executor.go:490-546). The ONE
        parse both the host path and the device compiler use, so their
        semantics can't drift. ``strict`` raises the host path's errors;
        non-strict returns None (device compile declines, host owns the
        error). An empty view list means an empty result, not an error
        (frame without a time quantum, or an out-of-data window)."""
        frame_name = c.args.get("frame") or DEFAULT_FRAME
        frame = self.holder.frame(index, frame_name)
        if frame is None:
            if not strict:
                return None
            raise FrameNotFoundError(frame_name)
        row_id, ok = c.uint_arg(frame.row_label)
        if not ok:
            if not strict:
                return None
            raise PilosaError(
                f"Range() row field '{frame.row_label}' required")
        start = c.args.get("start")
        if start is None:
            if not strict:
                return None
            raise PilosaError("Range() start time required")
        end = c.args.get("end")
        if end is None:
            if not strict:
                return None
            raise PilosaError("Range() end time required")
        try:
            start_t = dt.datetime.strptime(start, TIME_FORMAT)
            end_t = dt.datetime.strptime(end, TIME_FORMAT)
        except (TypeError, ValueError):
            if not strict:
                return None
            raise PilosaError("cannot parse Range() time")
        q = frame.time_quantum()
        if not q:
            return frame_name, row_id, []
        return (frame_name, row_id,
                tq.views_by_time_range(VIEW_STANDARD, start_t, end_t, q))

    # -- BSI field ranges / aggregates (storage.bsi) -------------------------

    def _field_range_parse(self, index: str, c: Call, strict: bool):
        """Resolve a Range call carrying a ``field OP value`` condition
        to ``(frame_name, Field, Condition)``; None when it carries no
        condition or (non-strict) when the frame/field is missing —
        the strict form owns the host path's errors, like
        _range_views."""
        pair = c.condition_arg()
        if pair is None:
            return None
        field_name, cond = pair
        frame_name = c.args.get("frame") or DEFAULT_FRAME
        frame = self.holder.frame(index, frame_name)
        if frame is None:
            if not strict:
                return None
            raise FrameNotFoundError(frame_name)
        field = frame.field(field_name)
        if field is None:
            if not strict:
                return None
            raise PilosaError(f"field not found: {field_name}")
        return frame_name, field, cond

    @staticmethod
    def _bsi_plane_row(plane: int) -> int:
        """Circuit plane index (bsi.EXISTS_PLANE or value-bit i) → the
        field view's row id."""
        if plane == bsi.EXISTS_PLANE:
            return bsi.EXISTS_ROW
        return bsi.PLANE_ROW_OFFSET + plane

    def _field_range_slice(self, index: str, c: Call,
                           slice: int) -> Bitmap:
        """Host leg of Range(field OP value): the O(depth) bit-plane
        circuit over the fragment's rows in roaring algebra."""
        frame_name, field, cond = self._field_range_parse(index, c,
                                                          strict=True)
        frag = self.holder.fragment(index, frame_name, field.view_name,
                                    slice)
        if frag is None:
            return Bitmap()
        bm = bsi.range_bitmap(
            cond.op, cond.value, field.min, field.max,
            lambda plane: frag.row(self._bsi_plane_row(plane)))
        return bm if bm is not None else Bitmap()

    def _execute_field_aggregate(self, index: str, c: Call,
                                 slices: list[int],
                                 opt: ExecOptions) -> bsi.ValCount:
        """Sum / Min / Max over a BSI field, with an optional filter
        bitmap child: per-slice popcount-weighted plane folds, merged
        as (sum, count) addition / min-max combine across slices and
        nodes (the mapReduce partial-aggregate contract)."""
        name = c.name
        frame_name = c.args.get("frame") or DEFAULT_FRAME
        field_name = c.args.get("field")
        if not field_name or not isinstance(field_name, str):
            raise PilosaError(f"{name}() field required")
        frame = self.holder.frame(index, frame_name)
        if frame is None:
            raise FrameNotFoundError(frame_name)
        field = frame.field(field_name)
        if field is None:
            raise PilosaError(f"field not found: {field_name}")
        if len(c.children) > 1:
            raise PilosaError(
                f"{name}() only accepts a single bitmap input")
        child = c.children[0] if c.children else None
        want_min = name == "Min"

        def map_fn(slice):
            frag = self.holder.fragment(index, frame_name,
                                        field.view_name, slice)
            if frag is None:
                return bsi.ValCount(0, 0)
            filt = (self._bitmap_call_slice(index, child, slice)
                    if child is not None else None)

            def row(plane):
                return frag.row(self._bsi_plane_row(plane))
            if name == "Sum":
                return bsi.sum_count(field.min, field.max, row,
                                     filter=filt)
            return bsi.min_max(field.min, field.max, row, filter=filt,
                               want_min=want_min)

        def reduce_fn(prev, v):
            if v is None:
                return prev
            if prev is None:
                return v
            if name == "Sum":
                return bsi.combine_sum(prev, v)
            return bsi.combine_min_max(prev, v, want_min=want_min)

        device_fn = (self._sum_local_device_fn(index, frame_name,
                                               field, child, opt)
                     if name == "Sum" else None)

        def local_host_fn(batch_slices):
            # Whole-owned-slice pushdown (the TopN exact-partial leg
            # shape): the node leg answers ONE (sum,count) / min/max
            # partial computed in a single batched plane fold
            # (bsi.sum_count_many / min_max_many) instead of fanning
            # per-slice map tasks and reducing their ValCounts — on a
            # peer this is what the forwarded leg runs, so remote legs
            # are one partial each end to end.
            if device_fn is not None:
                r = device_fn(batch_slices)
                if r is not NotImplemented:
                    return r
            if (self.pod is not None and self.pod.is_coordinator
                    and not opt.pod_local):
                return NotImplemented  # pod fan-out is not a host leg
            legs = []
            for s in batch_slices:
                frag = self.holder.fragment(index, frame_name,
                                            field.view_name, s)
                if frag is None:
                    continue
                filt = (self._bitmap_call_slice(index, child, s)
                        if child is not None else None)
                legs.append(
                    (lambda plane, f=frag:
                     f.row(self._bsi_plane_row(plane)), filt))
            if name == "Sum":
                return bsi.sum_count_many(field.min, field.max, legs)
            return bsi.min_max_many(field.min, field.max, legs,
                                    want_min=want_min)

        result = self._map_reduce(index, slices, c, opt, map_fn,
                                  reduce_fn, local_fn=local_host_fn)
        return result or bsi.ValCount(0, 0)

    def _sum_local_device_fn(self, index: str, frame_name: str, field,
                             child: Optional[Call], opt: ExecOptions):
        """Device Sum: ONE mesh program computes every plane's
        popcount against the (compiled) filter — K = depth+1 fused
        counts through the existing batched-count machinery
        (mesh.count_exprs_sharded) over residency-cached plane slabs;
        the weighted fold Σ 2^i·count_i happens host-side in Python
        ints (no device overflow at any depth)."""
        if (not self.use_mesh or self.pod is not None
                or self._mesh_backoff_active()):
            return None
        leaves: list[tuple] = []
        filter_expr = None
        if child is not None:
            filter_expr = self._compile_device_expr(index, child, leaves)
            if filter_expr is None:
                return None
        exprs = []
        for plane in range(bsi.EXISTS_PLANE, field.bit_depth):
            leaves.append((frame_name, field.view_name,
                           self._bsi_plane_row(plane)))
            leaf = ("leaf", len(leaves) - 1)
            exprs.append(leaf if filter_expr is None
                         else ("and", leaf, filter_expr))
        exprs = tuple(exprs)

        def local_fn(slices: list[int]):
            if len(slices) < self.mesh_min_slices:
                return NotImplemented
            mesh = self._mesh_or_none()
            if mesh is None:
                return NotImplemented
            from .parallel import mesh as mesh_mod
            if len(slices) > mesh_mod.slice_chunk_bound(
                    mesh.shape[mesh_mod.AXIS_SLICES]):
                return NotImplemented
            shard, budget = self._count_budget(slices)
            if self._leaf_block_bytes(len(leaves), shard) > budget:
                return NotImplemented
            cold = self._cold_leaves(mesh, index, leaves, slices)
            if not self._device_pays(mesh, len(leaves), len(slices),
                                     cold_rows=cold):
                return NotImplemented
            try:
                arrs = [self._leaf_device_array(mesh, index, leaf,
                                                tuple(slices))
                        for leaf in leaves]
                counts = mesh_mod.count_exprs_sharded(mesh, exprs, arrs)
            except Exception as e:  # noqa: BLE001 - device trouble
                self._note_device_fallback("sum_exprs", e)
                return NotImplemented
            count = counts[0]
            total = field.min * count + sum(
                n << i for i, n in enumerate(counts[1:]))
            return bsi.ValCount(total, count)

        return local_fn

    def _execute_set_field_value(self, index: str, c: Call,
                                 opt: ExecOptions) -> bool:
        """SetFieldValue(frame=f, <col>=N, <field>=V): route to every
        replica owner of the column's slice, like SetBit
        (executor.go:664-691); the local apply is the frame's
        per-plane read-modify write."""
        frame_name = c.args.get("frame")
        if not frame_name:
            raise PilosaError("SetFieldValue() frame required")
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError(index)
        frame = idx.frame(frame_name)
        if frame is None:
            raise FrameNotFoundError(frame_name)
        col_id, ok = c.uint_arg(idx.column_label)
        if not ok:
            raise PilosaError(f"SetFieldValue() column field"
                              f" '{idx.column_label}' required")
        pairs = [(k, v) for k, v in c.args.items()
                 if k not in ("frame", idx.column_label)]
        if len(pairs) != 1:
            raise PilosaError(
                "SetFieldValue() requires exactly one field=value")
        field_name, value = pairs[0]
        if isinstance(value, bool) or not isinstance(value, int):
            raise PilosaError(
                f"SetFieldValue() value must be an integer: {value!r}")
        slice = col_id // SLICE_WIDTH
        ret = False
        for node in self.cluster.fragment_nodes(index, slice):
            if node.host == self.host:
                if (self.pod is not None and not opt.pod_local
                        and self.pod.owner_pid(slice) != self.pod.pid):
                    if self._pod_forward_field_value(index, c, slice):
                        ret = True
                    continue
                if frame.set_field_value(field_name, col_id, value):
                    ret = True
                continue
            if opt.remote:
                continue
            res = self._exec_remote(node, index, Query([c]), None, opt)
            if res and res[0]:
                ret = True
        return ret

    def _pod_forward_field_value(self, index: str, c: Call,
                                 slice: int) -> bool:
        """Forward a field-value write to the owning pod process (field
        views are column-sharded, so placement follows the column
        slice like standard views)."""
        pid = self.pod.owner_pid(slice)
        if self.client is None:
            raise SliceUnavailableError(
                f"no client to reach pod process {pid}")
        res = self.client.execute_query(
            Node(self.pod.peers[pid]), index, str(Query([c])), None,
            remote=True, pod_local=True)
        idx = self.holder.index(index)
        if idx is not None:
            idx.set_remote_max_slice(slice)
        return bool(res and res[0])

    def _range_slice(self, index: str, c: Call, slice: int) -> Bitmap:
        # executor.go:490-546: union the minimal time-view cover.
        if c.condition_arg() is not None:
            return self._field_range_slice(index, c, slice)
        frame_name, row_id, views = self._range_views(index, c,
                                                      strict=True)
        bm = Bitmap()
        for view in views:
            frag = self.holder.fragment(index, frame_name, view, slice)
            if frag is None:
                continue
            bm = bm.union(frag.row(row_id))
        return bm

    # -- Count (executor.go:568-597) -----------------------------------------

    def _execute_count(self, index: str, c: Call, slices: list[int],
                       opt: ExecOptions) -> int:
        if len(c.children) == 0:
            raise PilosaError("Count() requires an input bitmap")
        if len(c.children) > 1:
            raise PilosaError("Count() only accepts a single bitmap input")
        pnode = getattr(c, "_plan_node", None)
        if pnode is not None and pnode.short_circuit:
            obs_metrics.PLANNER_DECISIONS.labels("short_circuit_hit").inc()
            return 0

        # Count(Intersect(A, B)) host legs count WITHOUT materializing
        # the intersection — the reference's IntersectionCount shortcut
        # (bitmap.go:69-82, roaring.go:328-343); with the native
        # whole-bitmap count one crossing covers a slice.
        child = c.children[0]
        pairwise = (child.name == "Intersect"
                    and len(child.children) == 2
                    and all(gc.name == "Bitmap"
                            for gc in child.children))

        def map_fn(slice):
            if pairwise:
                a = self._bitmap_call_slice(index, child.children[0],
                                            slice)
                b = self._bitmap_call_slice(index, child.children[1],
                                            slice)
                return a.intersection_count(b)
            return self._bitmap_call_slice(index, c.children[0],
                                           slice).count()

        # Per-query routing note: a vetoed local_fn stamps the
        # predicted host cost here (it runs on a _map_reduce pool
        # worker, so a shared dict — not a threading.local — carries it
        # back); this site closes the loop by recording
        # (predicted, actual) into the cost model.
        note: dict = {}
        local_fn = self._count_local_device_fn(index, c.children[0],
                                               opt, note=note)

        def local_host_fn(batch_slices):
            # Time ONLY the local host batch (advisor r4: charging the
            # whole map-reduce wall — remote fan-out, reduce,
            # scheduling — to a prediction priced for the local leg's
            # bytes inflated host_scale on multi-node setups).
            r = (local_fn(batch_slices) if local_fn is not None
                 else NotImplemented)
            if r is not NotImplemented:
                return r
            if (self.pod is not None and self.pod.is_coordinator
                    and not opt.pod_local):
                return NotImplemented  # pod fan-out is not a host leg
            t0 = time.perf_counter()
            r = self._mapper_local(batch_slices, map_fn,
                                   lambda prev, v: (prev or 0) + v)
            note["host_elapsed"] = (note.get("host_elapsed", 0.0)
                                    + time.perf_counter() - t0)
            return r

        result = self._map_reduce(index, slices, c, opt, map_fn,
                                  lambda prev, v: (prev or 0) + v,
                                  local_fn=local_host_fn)
        if "host_elapsed" in note:
            self._record_host_leg(note, note["host_elapsed"])
        return result or 0

    # -- device-batched Count (TPU fast path) --------------------------------

    def _device_batch_run(self, index: str, calls: list[Call], start: int,
                          slices: list[int], opt: ExecOptions):
        """(results, n_calls) for a maximal run of ≥2 consecutive
        device-lowerable calls starting at ``start`` — Count over any
        compilable bitmap tree (including BSI ``Range`` comparison
        circuits) and the exact-count TopN form (explicit ids + a
        compilable source) — fused into ONE device program over shared
        (deduplicated) leaf slabs, or None to fall back to per-call
        execution. A counts-only run dispatches the batched count
        program; a run carrying TopN blocks dispatches the fused-tree
        program (mesh.fused_tree_sharded): either way the whole tree
        pays one dispatch, one in-program reduction, one host fetch —
        not one crossing per call (VERDICT weak #6's host-merge tax).

        Requires every touched slice to be locally owned (a pod counts
        as one node: its coordinator dispatches the batch as ONE pod
        work item): cluster map-reduce fans out per call, so batching
        a query with remote-only slices would bypass its remote legs —
        but a node owning a replica of everything (the common
        replica_n == nodes shape) answers the whole batch from local
        fragments and keeps the fused device fold. Count and TopN never
        take the inverse slice list (only Bitmap does), so every call
        in the run shares ``slices``. Pod runs and Pallas-kernel meshes
        fuse counts only (their per-kind programs serve TopN).
        """
        if not self.use_mesh or len(slices) < self.mesh_min_slices:
            return None
        if self.pod is not None and (not self.pod.is_coordinator
                                     or opt.pod_local):
            return None
        if self.pod is None and self._mesh_backoff_active():
            return None
        # Cheap necessary condition before any compile work: a run
        # needs ≥2 fusable calls, so a lone Count or TopN (the common
        # query shapes) must not pay a discarded device-expr
        # compilation (or the per-slice ownership walk below) here.
        if (start + 1 >= len(calls)
                or calls[start].name not in ("Count", "TopN")
                or calls[start + 1].name not in ("Count", "TopN")):
            return None
        if not self._owns_all_slices(index, slices):
            return None
        from .parallel import mesh as mesh_mod
        mesh = None
        pallas = False
        if self.pod is None:
            mesh = self._mesh_or_none()
            if mesh is None or len(slices) > mesh_mod.slice_chunk_bound(
                    mesh.shape[mesh_mod.AXIS_SLICES]):
                return None
            pallas = mesh_mod._mesh_pallas_mode(mesh) is not None
        shard, budget = self._count_budget(slices)
        leaves: list[tuple] = []
        leaf_ids: dict[tuple, int] = {}
        plan: list[tuple] = []       # ("count", expr) | ("topn", ...)
        topn_items: list[tuple] = []  # (expr, frame_name, ids)
        host_rows = 0  # per-call leaf rows: the host path's real bytes
        rows_bytes = 0  # accumulated candidate-block bytes in the plan
        j = start

        def absorb(call_leaves: list[tuple], expr):
            """Intern a call's leaves into the shared slab set and
            remap its expr; returns the remapped expr."""
            remap = {}
            for li, leaf in enumerate(call_leaves):
                if leaf not in leaf_ids:
                    leaf_ids[leaf] = len(leaves)
                    leaves.append(leaf)
                remap[li] = leaf_ids[leaf]
            if all(k == v for k, v in remap.items()):
                return expr  # first call / no shared leaves
            return mesh_mod.remap_expr_leaves(expr, remap)

        while j < len(calls) and len(plan) < self._BATCH_MAX_COUNTS:
            c = calls[j]
            if c.name == "Count" and len(c.children) == 1:
                call_leaves: list[tuple] = []
                expr = self._compile_device_expr(index, c.children[0],
                                                 call_leaves)
                if expr is None:
                    break
                new = sum(1 for leaf in call_leaves
                          if leaf not in leaf_ids)
                if (self._leaf_block_bytes(len(leaves) + new, shard)
                        + rows_bytes > budget):
                    break  # fuse the prefix that fits; rest per call
                plan.append(("count", absorb(call_leaves, expr)))
                host_rows += len(call_leaves)
                j += 1
                continue
            if (c.name == "TopN" and self.pod is None and not pallas):
                item = self._topn_fusable(index, c, slices, shard,
                                          budget - rows_bytes, leaves,
                                          leaf_ids)
                if item is None:
                    break
                expr, frame_name, ids, call_leaves = item
                plan.append(("topn", len(topn_items)))
                topn_items.append((absorb(call_leaves, expr),
                                   frame_name, ids))
                host_rows += len(ids) + len(call_leaves)
                # Every accepted candidate block stays live in the ONE
                # fused program — the budget must bound their SUM, not
                # each block alone (review finding: 16 × ~250 MB blocks
                # each passed a per-call check while the fused program
                # held ~4 GB of rows at once).
                rows_bytes += (len(slices) * len(ids)
                               * self._leaf_block_bytes(1, 1))
                j += 1
                continue
            break
        if j - start < 2:
            return None
        count_exprs = tuple(e for kind, e in plan if kind == "count")
        if self.pod is not None:
            if topn_items:
                return None  # unreachable: pod scan breaks at TopN
            try:
                counts = self.pod.count_exprs(index, list(count_exprs),
                                              leaves, slices)
            except Exception as e:  # noqa: BLE001 - per-call pod paths
                self._note_device_fallback("pod.count_exprs", e)
                return None
            return counts, j - start
        # One sync serves the whole tree; the host alternative re-walks
        # each call's leaves (and candidate rows), so its bytes are the
        # per-call sum — priced separately from the deduplicated device
        # block (costmodel host_bytes). A vetoed batch falls to
        # per-call gates that agree, landing everything on the host.
        from .parallel.residency import device_cache
        cold = self._cold_leaves(mesh, index, leaves, slices)
        rows_keys = []
        for expr_t, frame_name, ids in topn_items:
            rk = self._topn_rows_key(mesh, index, frame_name,
                                     tuple(ids), tuple(slices))
            rows_keys.append(rk)
            if not device_cache().contains(rk):
                cold += len(ids)
        device_rows = (len(leaves)
                       + sum(len(ids) for _, _, ids in topn_items))
        if not self._device_pays(mesh, device_rows, len(slices),
                                 cold_rows=cold, host_rows=host_rows):
            return None
        try:
            arrs = [self._leaf_device_array(mesh, index, leaf,
                                            tuple(slices))
                    for leaf in leaves]
            if topn_items:
                from .parallel import residency
                rows_arrays = []
                for (expr_t, frame_name, ids), rk in zip(topn_items,
                                                         rows_keys):
                    frags = [self.holder.fragment(index, frame_name,
                                                  VIEW_STANDARD, s)
                             for s in slices]
                    rows_arrays.append(residency.candidate_block(
                        mesh, rk, frags, tuple(ids)))
                counts, topn_counts = mesh_mod.fused_tree_sharded(
                    mesh, count_exprs,
                    [(expr_t, len(ids))
                     for expr_t, _, ids in topn_items],
                    arrs, rows_arrays)
            else:
                counts = mesh_mod.count_exprs_sharded(
                    mesh, count_exprs, arrs)
                topn_counts = []
        except Exception as e:  # noqa: BLE001 - fall back per call
            self._note_device_fallback(
                "fused_tree" if topn_items else "count_exprs", e)
            return None
        results: list = []
        count_i = 0
        for kind, v in plan:
            if kind == "count":
                results.append(counts[count_i])
                count_i += 1
            else:
                _, _, ids = topn_items[v]
                results.append(pairs_sort(
                    [Pair(rid, cnt) for rid, cnt
                     in zip(ids, topn_counts[v]) if cnt > 0]))
        return results, j - start

    def _topn_fusable(self, index: str, c: Call, slices: list[int],
                      shard: int, budget: int, leaves: list[tuple],
                      leaf_ids: dict):
        """(expr, frame_name, ids, call_leaves) when this TopN call can
        join a fused device tree: the exact-count form (explicit ids +
        one compilable source child), unfiltered (threshold ≤ 1, no
        Tanimoto — the pruning forms need runtime scalars and keep
        their per-kind program), attribute filters applied host-side
        up front (row attrs are frame-global), candidate block within
        the resident byte bounds. None breaks the run (per-call paths
        own every other shape and all error semantics)."""
        (frame_name, _n, field, row_ids, min_threshold, filters,
         tanimoto) = self._topn_args(c)
        if (not row_ids or len(c.children) != 1 or tanimoto > 0
                or min_threshold > 1):
            return None
        call_leaves: list[tuple] = []
        expr = self._compile_device_expr(index, c.children[0],
                                         call_leaves)
        if expr is None:
            return None
        ids = self._attr_filtered_ids(index, frame_name, row_ids,
                                      field, filters)
        if ids is None or not ids:
            # No attr store, or nothing survives the filter: the
            # per-call path owns the (cheap) empty/fallback semantics.
            return None
        from .ops.packed import WORDS_PER_SLICE
        from .parallel import mesh as mesh_mod
        block_bytes = len(slices) * len(ids) * WORDS_PER_SLICE * 4
        new = sum(1 for leaf in call_leaves if leaf not in leaf_ids)
        if (block_bytes > mesh_mod.TOPN_BLOCK_BYTES
                or self._leaf_block_bytes(len(leaves) + new, shard)
                + block_bytes > budget):
            return None
        return expr, frame_name, ids, call_leaves

    _DEVICE_FOLD_OPS = {"Intersect": "and", "Union": "or",
                        "Difference": "andnot"}

    # Largest dense candidate block the TopN mesh path may materialize
    # host-side (slices × candidates × 128 KB); larger sets fall back.
    _TOPN_HOST_BLOCK_BYTES = 2 << 30
    # Max Count calls fused into one program: each distinct expr tuple
    # compiles its own XLA program, so unbounded runs would stall the
    # serving path in compilation (longer runs split into chunks).
    _BATCH_MAX_COUNTS = 16
    # HBM bound for one materializing fold: every leaf slab plus the
    # result are simultaneously live as the program's inputs/output.
    _MATERIALIZE_DEVICE_BYTES = 4 << 30

    @staticmethod
    def _leaf_block_bytes(n_leaves: int, n_slices: int) -> int:
        from .ops.packed import WORDS_PER_SLICE
        return n_leaves * n_slices * WORDS_PER_SLICE * 4

    def _count_budget(self, slices: list[int]) -> tuple[int, int]:
        """(per-shard slice count, byte budget) for one Count program's
        leaf set. Resident single-host programs hold every slab live in
        HBM (_MATERIALIZE_DEVICE_BYTES); the streaming and pod paths
        chunk the device side but build the full numpy pack up-front,
        so the host-block bound applies to the (per-process) shard."""
        if self.pod is not None:
            return (self.pod.max_shard_slices(slices),
                    self._TOPN_HOST_BLOCK_BYTES)
        from .parallel import mesh as mesh_mod
        mesh = self._mesh
        n_dev = (mesh.shape[mesh_mod.AXIS_SLICES] if mesh is not None
                 else 1)
        if len(slices) <= mesh_mod.slice_chunk_bound(n_dev):
            return len(slices), self._MATERIALIZE_DEVICE_BYTES
        return len(slices), self._TOPN_HOST_BLOCK_BYTES

    def _compile_device_expr(self, index: str, c: Call, leaves: list):
        """Compile a pure bitmap call tree into a mesh.count_expr tree.

        Supported: Bitmap leaves (standard or inverse) and Range (an
        or-fold over its minimal time-view cover — a leaf per view,
        executor.go:490-546) combined with Intersect/Union/Difference.
        Returns None when the tree contains anything else (malformed
        args, missing frames, no time quantum) — those run through the
        per-slice path, which owns the error semantics.
        """
        if c.name == "Range":
            if c.condition_arg() is not None:
                return self._compile_field_range_expr(index, c, leaves)
            parsed = self._range_views(index, c, strict=False)
            if parsed is None or not parsed[2]:
                return None  # malformed or empty cover: host path owns it
            frame_name, row_id, views = parsed
            expr = None
            for vn in views:
                leaves.append((frame_name, vn, row_id))
                part = ("leaf", len(leaves) - 1)
                expr = part if expr is None else ("or", expr, part)
            return expr
        if c.name == "Bitmap":
            idx = self.holder.index(index)
            if idx is None:
                return None
            frame = idx.frame(c.args.get("frame") or DEFAULT_FRAME)
            if frame is None:
                return None
            row_id, row_ok = c.uint_arg(frame.row_label)
            col_id, col_ok = c.uint_arg(idx.column_label)
            if row_ok == col_ok:
                return None
            view, id = (VIEW_STANDARD, row_id) if row_ok else \
                (VIEW_INVERSE, col_id)
            if view == VIEW_INVERSE and not frame.inverse_enabled:
                return None
            leaves.append((frame.name, view, id))
            return ("leaf", len(leaves) - 1)
        op = self._DEVICE_FOLD_OPS.get(c.name)
        if op is None or not c.children:
            return None
        parts = [self._compile_device_expr(index, ch, leaves)
                 for ch in c.children]
        if any(p is None for p in parts):
            return None
        expr = parts[0]
        for p in parts[1:]:  # n-ary folds left-to-right, like _fold_slice
            expr = (op, expr, p)
        return expr

    def _compile_field_range_expr(self, index: str, c: Call,
                                  leaves: list):
        """Compile Range(field OP value) into the comparison circuit
        over bit-plane leaves (storage.bsi.compare_expr — the SAME
        circuit the host path evaluates in roaring algebra), so field
        ranges compose with Count fusion, fold materialization, and
        plane-slab residency exactly like plain Bitmap leaves. Trivial
        clamps and provably-empty circuits decline (None): the host
        path computes those without a device round trip."""
        parsed = self._field_range_parse(index, c, strict=False)
        if parsed is None:
            return None
        frame_name, field, cond = parsed
        clamped = bsi.clamp(cond.op, cond.value, field.min, field.max)
        if clamped == "none":
            return None
        leaf_ids: dict[tuple, int] = {}

        def leaf(plane: int):
            key = (frame_name, field.view_name,
                   self._bsi_plane_row(plane))
            if key not in leaf_ids:
                leaves.append(key)
                leaf_ids[key] = len(leaves) - 1
            return ("leaf", leaf_ids[key])

        if clamped == "all":
            return leaf(bsi.EXISTS_PLANE)
        cop, upred = clamped
        return bsi.compare_expr(cop, upred, field.bit_depth, leaf)

    def _bitmap_local_device_fn(self, index: str, c: Call,
                                opt: ExecOptions, compiled=None):
        """Materializing Union/Intersect/Difference on device for WIDE
        fan-outs (BASELINE config 2: Union over 1 K rows): fold the
        packed leaf slabs in one sharded program (the leaf axis reduces
        associatively on device), fetch the dense result words, and
        repack to roaring segments — replacing leaf-count many
        container-walking merges (roaring.go:1270-1558) with one HBM
        pass. Narrow calls keep the host path: below ~mesh_min_leaves
        rows the roaring merges beat the device sync + repack."""
        if (not self.use_mesh or self.pod is not None
                or self._mesh_backoff_active()):
            return None  # pod host legs own pod materialization
        pnode = getattr(c, "_plan_node", None)
        if pnode is not None and pnode.placement == "host":
            return None  # planner priced the subtree cheaper on host
        if c.name == "Range" and c.condition_arg() is not None:
            return self._field_range_local_device_fn(index, c)
        if c.name not in ("Union", "Intersect", "Difference"):
            return None
        if compiled is not None:
            expr, leaves = compiled
        else:
            leaves = []
            expr = self._compile_device_expr(index, c, leaves)
        if expr is None or len(leaves) < self.mesh_min_leaves:
            return None

        def local_fn(slices: list[int]):
            from .ops import packed
            slab = len(slices) * packed.WORDS_PER_SLICE * 4
            # Peak HOST allocation is the dense result block plus one
            # transient leaf slab (slabs pack one at a time before the
            # device_put); all leaf slabs plus the result are live in
            # HBM together as inputs/output of the one fold program.
            if (2 * slab > self._TOPN_HOST_BLOCK_BYTES
                    or (len(leaves) + 1) * slab
                    > self._MATERIALIZE_DEVICE_BYTES):
                return NotImplemented
            mesh = self._mesh_or_none()
            if mesh is None:
                return NotImplemented
            from .parallel import mesh as mesh_mod
            try:
                arrs = [self._leaf_device_array(mesh, index, leaf,
                                                tuple(slices))
                        for leaf in leaves]
                words = mesh_mod.materialize_expr_sharded(mesh, expr,
                                                          arrs)
            except Exception as e:  # noqa: BLE001 - device trouble
                self._note_device_fallback("materialize", e)
                return NotImplemented
            out = Bitmap()
            for si, slice in enumerate(slices):
                w = words[si]
                if not w.any():
                    continue
                data = packed.unpack_to_bitmap(
                    w, base_word=slice * (packed.WORDS_PER_SLICE))
                out.add_segment(data, slice, writable=True)
            return out

        return local_fn

    def _field_range_local_device_fn(self, index: str, c: Call):
        """Materializing device leg of Range(field OP value): the whole
        comparison circuit over stacked bit-plane slabs runs as ONE
        XLA program (parallel.mesh.bsi_range_sharded — exists row plus
        depth value planes, sharded over the slice axis), the dense
        matched words fetch once, and the host repacks to roaring —
        replacing O(depth) per-slice roaring circuit passes with one
        HBM pass. Trivial clamps ("all"/"none") stay host-side."""
        parsed = self._field_range_parse(index, c, strict=False)
        if parsed is None:
            return None
        frame_name, field, cond = parsed
        clamped = bsi.clamp(cond.op, cond.value, field.min, field.max)
        if clamped in ("none", "all"):
            return None
        cop, upred = clamped
        depth = field.bit_depth
        leaves = [(frame_name, field.view_name,
                   self._bsi_plane_row(p))
                  for p in range(bsi.EXISTS_PLANE, depth)]

        def local_fn(slices: list[int]):
            if len(slices) < self.mesh_min_slices:
                return NotImplemented
            from .ops import packed
            slab = len(slices) * packed.WORDS_PER_SLICE * 4
            if (2 * slab > self._TOPN_HOST_BLOCK_BYTES
                    or (len(leaves) + 1) * slab
                    > self._MATERIALIZE_DEVICE_BYTES):
                return NotImplemented
            mesh = self._mesh_or_none()
            if mesh is None:
                return NotImplemented
            from .parallel import mesh as mesh_mod
            try:
                arrs = [self._leaf_device_array(mesh, index, leaf,
                                                tuple(slices))
                        for leaf in leaves]
                words = mesh_mod.bsi_range_sharded(mesh, cop, upred,
                                                   depth, arrs)
            except Exception as e:  # noqa: BLE001 - device trouble
                self._note_device_fallback("bsi_range", e)
                return NotImplemented
            out = Bitmap()
            for si, slice in enumerate(slices):
                w = words[si]
                if not w.any():
                    continue
                data = packed.unpack_to_bitmap(
                    w, base_word=slice * packed.WORDS_PER_SLICE)
                out.add_segment(data, slice, writable=True)
            return out

        return local_fn

    def _count_local_device_fn(self, index: str, child: Call,
                               opt: ExecOptions, note: dict | None = None):
        """Batched local-leg Count: all slices in ONE mesh program.

        Returns a ``local_fn(slices) -> int`` for _map_reduce, or None
        when the expression can't run on device. Leaf rows are packed
        host-side into [n_leaves, n_slices, words] and the whole
        expression + popcount + sum runs as a single psum-reduced SPMD
        call (parallel.mesh.count_expr) — the mesh form of the per-slice
        count map (executor.go:568-597). On a pod coordinator the call
        becomes a pod-wide collective (parallel.pod.Pod.count_expr);
        pod workers and podLocal legs use the host path.
        """
        if not self.use_mesh:
            return None
        if self.pod is None and self._mesh_backoff_active():
            return None
        pnode = getattr(child, "_plan_node", None)
        if pnode is not None and pnode.placement == "host":
            return None  # planner priced the subtree cheaper on host
        leaves: list[tuple] = []
        expr = self._compile_device_expr(index, child, leaves)
        if expr is None:
            return None
        if self.pod is not None:
            if not self.pod.is_coordinator or opt.pod_local:
                return None  # plain local path on pod-internal legs

            def pod_fn(slices: list[int]):
                shard, budget = self._count_budget(slices)
                if (len(slices) < self.mesh_min_slices
                        or self._leaf_block_bytes(len(leaves), shard)
                        > budget):
                    return NotImplemented  # pod host legs win when small
                try:
                    return self.pod.count_expr(index, expr, leaves, slices)
                except Exception as e:  # noqa: BLE001 - pod host fan-out
                    self._note_device_fallback("pod.count_expr", e)
                    return NotImplemented  # correct via _pod_host_mapper
            return pod_fn

        def local_fn(slices: list[int]):
            if len(slices) < self.mesh_min_slices:
                return NotImplemented  # host path wins below the sync cost
            mesh = self._mesh_or_none()  # backend init only past threshold
            if mesh is None:
                return NotImplemented
            cold = self._cold_leaves(mesh, index, leaves, slices)
            if not self._device_pays(mesh, len(leaves), len(slices),
                                     cold_rows=cold, note=note):
                return NotImplemented  # calibrated: host clearly faster
            shard, budget = self._count_budget(slices)
            if self._leaf_block_bytes(len(leaves), shard) > budget:
                return NotImplemented  # oversized leaf set: host path
            from .parallel import mesh as mesh_mod
            try:
                def run():
                    if len(slices) <= mesh_mod.slice_chunk_bound(
                            mesh.shape[mesh_mod.AXIS_SLICES]):
                        # Residency fast path: leaf slabs stay device-
                        # resident across queries (budgeted HBM cache).
                        arrs = [self._leaf_device_array(
                            mesh, index, leaf, tuple(slices))
                            for leaf in leaves]
                        return mesh_mod.count_expr_sharded(mesh, expr,
                                                           arrs)
                    block = self._pack_leaf_block(index, leaves, slices)
                    return mesh_mod.count_expr(mesh, expr, block)
                # Feed the SAME cold-row estimate into the drift
                # prediction — omitting it made every cold query look
                # like drift and inflated device_scale (review finding).
                return self._timed_device_leg(run, len(leaves),
                                              len(slices),
                                              cold_rows=cold)
            except Exception as e:  # noqa: BLE001 - device trouble ≠ node down
                self._note_device_fallback("count_expr", e)
                return NotImplemented

        return local_fn

    def _device_pays(self, mesh, n_rows: int, n_slices: int,
                     cold_rows: int = 0, note: dict | None = None,
                     streaming: bool = False,
                     host_rows: int | None = None) -> bool:
        """Calibrated routing veto: False when the host path clearly
        wins for a block of ``n_rows × n_slices`` packed rows on this
        hardware (round 2's c4 showed the static threshold sending
        128-slice Counts to a path 4× slower through the tunnel).
        ``cold_rows`` of those are not device-resident and must be
        packed + uploaded first — through a tunnel that transfer, not
        the compute, dominates. ``host_rows`` (fused multi-op trees)
        is the PER-CALL leaf-row sum the host alternative would
        actually walk — the device block deduplicates shared leaves
        and pays ONE crossing for the whole tree, so pricing the host
        on the deduplicated bytes over-charged the mesh leg exactly
        when fusion helps most."""
        if not self._cost_model_enabled:
            return True
        if self.cost_model is None:
            from .parallel import costmodel
            try:
                self.cost_model = costmodel.get_model(
                    mesh, margin=self._cost_margin)
            except Exception:  # noqa: BLE001 - never fail a query on this
                self._cost_model_enabled = False
                return True
            # Share the measured constants with the planner so its
            # host/device placement prices match the executor's veto.
            if self.planner is not None:
                self.planner.calibration = self.cost_model.cal
        from .ops.packed import WORDS_PER_SLICE
        row_bytes = n_slices * WORDS_PER_SLICE * 4
        host_bytes = (host_rows * row_bytes if host_rows is not None
                      else None)
        # host_bytes travels only when it differs — injected test
        # models (and the pre-fusion interface) take three args.
        kw = {"host_bytes": host_bytes} if host_bytes is not None else {}
        pays = self.cost_model.device_pays(
            n_rows * row_bytes, cold_bytes=cold_rows * row_bytes,
            streaming=streaming, **kw)
        if not pays:
            self.cost_vetoes += 1
            if note is not None:
                # Stamp the host leg's prediction for this query; the
                # _map_reduce caller records actual-vs-predicted.
                note["host_pred"] = self.cost_model.predict(
                    "host", host_bytes if host_bytes is not None
                    else n_rows * row_bytes)
        return pays

    def _timed_device_leg(self, fn, n_rows: int, n_slices: int,
                          cold_rows: int = 0, streaming: bool = False):
        """Run a device leg and feed (predicted, actual) back into the
        cost model's drift loop (no-op when the model is off).
        Streaming legs (block re-packed every query) record under
        their own leg — the prediction prices the packing via
        pack_bps, so they participate in drift correction instead of
        being excluded (VERDICT r4 item 6)."""
        model = self.cost_model
        if model is None:
            return fn()
        from .ops.packed import WORDS_PER_SLICE
        leg = "device_stream" if streaming else "device"
        row_bytes = n_slices * WORDS_PER_SLICE * 4
        pred = model.predict(leg, n_rows * row_bytes,
                             cold_rows * row_bytes)
        t0 = time.perf_counter()
        out = fn()
        model.record(leg, pred, time.perf_counter() - t0)
        return out

    def _record_host_leg(self, note: dict, elapsed_s: float) -> None:
        """Close the loop for a query the model routed to the host."""
        pred = note.get("host_pred")
        if pred is not None and self.cost_model is not None:
            self.cost_model.record("host", pred, elapsed_s)

    def _leaf_cache_key(self, mesh, index: str, leaf: tuple,
                        slices: tuple[int, ...]) -> tuple:
        from .parallel import mesh as mesh_mod
        frame, view, row_id = leaf
        frags = [self.holder.fragment(index, frame, view, s)
                 for s in slices]
        gens = tuple((f.device.uid, f.device.generation) if f is not None
                     else (0, 0) for f in frags)
        n_dev = mesh.shape[mesh_mod.AXIS_SLICES]
        return ("leaf", id(self.holder), index, frame, view, row_id,
                slices, gens, n_dev)

    def _cold_leaves(self, mesh, index: str, leaves: list[tuple],
                     slices: list[int]) -> int:
        """How many leaf slabs an upcoming dispatch would have to pack
        and upload (i.e. are not in the device residency cache)."""
        from .parallel.residency import device_cache
        cache = device_cache()
        t = tuple(slices)
        return sum(1 for leaf in leaves
                   if not cache.contains(
                       self._leaf_cache_key(mesh, index, leaf, t)))

    def _pack_leaf_block(self, index: str, leaves: list[tuple],
                         slices: list[int]) -> np.ndarray:
        """[n_leaves, n_slices, words] block of packed leaf rows; absent
        fragments stay zero (the identity for every count reduce)."""
        from .ops.packed import WORDS_PER_SLICE
        block = np.zeros((len(leaves), len(slices), WORDS_PER_SLICE),
                         dtype=np.uint32)
        for li, (frame, view, row_id) in enumerate(leaves):
            for si, slice in enumerate(slices):
                frag = self.holder.fragment(index, frame, view, slice)
                if frag is not None:
                    frag.pack_row(row_id, out=block[li, si])
        return block

    def _leaf_device_array(self, mesh, index: str, leaf: tuple,
                           slices: tuple[int, ...]):
        """Device-resident [bucket(n_slices), words] slab for one PQL
        leaf row, held in the budgeted HBM cache
        (parallel.residency.leaf_slab — bucket-padded so the program
        catalogue's compiled shapes stay stable as slice count grows).

        The key embeds every backing fragment's (uid, generation), so
        writes/reopens stop the entry being referenced and it ages out
        of the LRU — repeated Count/TopN over a stable index re-use the
        upload instead of re-packing + re-transferring per query."""
        from .parallel import residency
        frame, view, row_id = leaf
        frags = [self.holder.fragment(index, frame, view, s)
                 for s in slices]
        key = self._leaf_cache_key(mesh, index, leaf, slices)
        return residency.leaf_slab(mesh, key, frags, row_id)

    # -- TopN (executor.go:271-396) ------------------------------------------

    def _execute_top_n(self, index: str, c: Call, slices: list[int],
                       opt: ExecOptions) -> list[Pair]:
        row_ids, _ = c.uint_slice_arg("ids")
        n, _ = c.uint_arg("n")

        if opt.remote and not row_ids and c.args.get("pushdown"):
            # Pushdown leg (ROADMAP item 3): the coordinator asked
            # this node to run the WHOLE TopN algorithm over its own
            # slices — single-pass when the rank caches allow, exact
            # local two-phase otherwise — and return untrimmed exact
            # partials for the two-phase merge.
            return self._topn_exact_partial(index, c, slices, opt)

        fast = self._topn_host_single_pass(index, c, slices, opt)
        if fast is not None:
            return fast

        if not opt.remote and not row_ids:
            dist = self._topn_distributed(index, c, slices, opt, n)
            if dist is not None:
                return dist

        pairs = self._top_n_slices(index, c, slices, opt)
        # Only the originating node refetches exact counts for candidates.
        if not pairs or row_ids or opt.remote:
            return pairs
        other = c.clone()
        other.args["ids"] = sorted({p.id for p in pairs})
        dev = self._topn_device_topk(index, other, slices, n)
        if dev is not None:
            return dev
        trimmed = self._top_n_slices(index, other, slices, opt)
        if n and n < len(trimmed):
            trimmed = trimmed[:n]
        return trimmed

    def _topn_device_topk(self, index: str, c: Call,
                          slices: list[int],
                          n: int) -> Optional[list[Pair]]:
        """The sourceless TopN exact-count refetch as ONE in-program
        device top-k (mesh.topn_topk_sharded): the candidate union from
        phase 1 uploads as a resident block, per-candidate counts
        reduce in-program, and the top-k selection ALSO happens in the
        program, so the host fetch is O(n) instead of O(candidates).
        Plain form only — thresholds > 1, Tanimoto, attribute filters,
        and pod legs keep the host path, which owns those semantics.
        Counts are fresh popcounts, identical to the host refetch's
        row_count recounts; ordering matches pairs_sort (count desc,
        id asc) by the program's tie-break. None = fall back."""
        if not self.use_mesh or self.pod is not None \
                or self._mesh_backoff_active():
            return None
        (frame_name, _n, field, row_ids, min_threshold, filters,
         tanimoto) = self._topn_args(c)
        if (len(c.children) > 0 or (field and filters) or tanimoto > 0
                or min_threshold > 1 or not row_ids
                or len(slices) < self.mesh_min_slices
                or not self._owns_all_slices(index, slices)):
            return None
        mesh = self._mesh_or_none()
        if mesh is None:
            return None
        from .ops.packed import WORDS_PER_SLICE
        from .parallel import mesh as mesh_mod
        from .parallel import residency
        ids = list(row_ids)
        block_bytes = len(slices) * len(ids) * WORDS_PER_SLICE * 4
        if (block_bytes > self._TOPN_HOST_BLOCK_BYTES
                or block_bytes > mesh_mod.TOPN_BLOCK_BYTES
                or len(slices) > mesh_mod.slice_chunk_bound(
                    mesh.shape[mesh_mod.AXIS_SLICES])):
            return None
        rows_key = self._topn_rows_key(mesh, index, frame_name,
                                       tuple(ids), tuple(slices))
        cold = (0 if residency.device_cache().contains(rows_key)
                else len(ids))
        if not self._device_pays(mesh, len(ids), len(slices),
                                 cold_rows=cold, streaming=False):
            return None
        k = min(n, len(ids)) if n else len(ids)
        try:
            def run():
                frags = [self.holder.fragment(index, frame_name,
                                              VIEW_STANDARD, s)
                         for s in slices]
                rows_arr = residency.candidate_block(
                    mesh, rows_key, frags, tuple(ids))
                return mesh_mod.topn_topk_sharded(mesh, None, rows_arr,
                                                  [], k)
            counts, idxs = self._timed_device_leg(
                run, len(ids), len(slices), cold_rows=cold,
                streaming=False)
        except Exception as e:  # noqa: BLE001 - device trouble ≠ node down
            self._note_device_fallback("topn_topk", e)
            return None
        return [Pair(ids[i], cnt)
                for i, cnt in zip(idxs, counts) if cnt > 0]

    def _topn_host_single_pass(self, index: str, c: Call,
                               slices: list[int],
                               opt: ExecOptions,
                               allow_remote: bool = False,
                               trim: bool = True
                               ) -> Optional[list[Pair]]:
        """The plain sourceless TopN form on a single local node in ONE
        pass over the rank caches, or None for the general path.

        The reference runs two per-slice phases: local tops merged into
        a candidate union, then an exact-count refetch of every
        candidate on every slice (executor.go:273-310). With complete
        per-fragment LRU count caches both phases read the SAME arrays,
        so one walk yields both: the ≥floor prefix feeds a dense
        accumulator (the phase-2 exact sums — per-slice floor applied,
        per reference semantics) and its n-trim marks candidates (the
        phase-1 union). At 1024 slices the two-phase path's second walk
        — per-slice locks, id sorts, membership probes, recounts — was
        the whole superlinear term (VERDICT r4 item 3: 282 ms at 1024
        slices vs 21 ms at 256); this leg is ~linear in slices.

        Safety gates: LRU caches only (RankCache rankings are
        rate-limited-stale and threshold-trimmed; the per-slice path
        reads them with its own staleness rules), caches must not have
        evicted (an evicted row's exact count needs the phase-2
        recount), and pod / remote legs keep the fan-out path. On a
        multi-node cluster the gate is OWNERSHIP, not cluster size:
        when this node holds a replica of every slice, its local rank
        caches cover the whole query (writes fan to every replica
        owner) and the single-pass answer stands.

        ``allow_remote`` lifts the coordinator-only gate for pushdown
        legs (the coordinator explicitly requested node-local
        semantics); ``trim=False`` skips the final top-n trim and
        returns EVERY candidate (the union of per-slice n-trims) with
        its exact sum — the partial-set shape the distributed
        two-phase merge consumes, identical to the candidate set a
        single-node single pass would mark."""
        (frame_name, n, field, row_ids, min_threshold, filters,
         tanimoto) = self._topn_args(c)
        if ((opt.remote and not allow_remote) or row_ids
                or len(c.children) > 0
                or (field and filters) or tanimoto > 0
                or self.pod is not None
                or not self._owns_all_slices(index, slices)):
            return None
        from .storage.cache import LRUCache
        floor = max(min_threshold, 1)
        acc_parts: list[tuple[np.ndarray, np.ndarray, int]] = []
        max_id = 0
        for slice in slices:
            frag = self.holder.fragment(index, frame_name,
                                        VIEW_STANDARD, slice)
            if frag is None:
                continue
            cache = frag.cache
            if (not isinstance(cache, LRUCache)
                    or len(cache) >= cache.max_entries
                    or not frag._cache_complete):
                # Incomplete cache (eviction, or a crash-recovered
                # fragment too big to repair on open): exact counts
                # need the recounting two-phase path.
                return None
            with frag._mu:
                ids, counts = cache.top_arrays()
            if not len(ids):
                continue
            # counts are rank-sorted descending: the ≥floor set is a
            # prefix (same binary-search cut as fragment.top).
            cut = len(counts) - int(np.searchsorted(
                counts[::-1], floor, side="left"))
            if not cut:
                continue
            ids, counts = ids[:cut], counts[:cut]
            acc_parts.append((ids, counts, min(n, cut) if n else cut))
            m = int(ids.max())
            if m > max_id:
                max_id = m
        if not acc_parts:
            return []
        if max_id < (1 << 24):
            # Dense accumulate: per-slice ids are unique, so fancy
            # assignment sums safely slice by slice; candidate marks
            # come from each slice's n-trimmed prefix.
            sums = np.zeros(max_id + 1, dtype=np.int64)
            cand_mark = np.zeros(max_id + 1, dtype=bool)
            for ids, counts, marks in acc_parts:
                idx = ids.astype(np.int64)
                sums[idx] += counts
                cand_mark[idx[:marks]] = True
            cand = np.flatnonzero(cand_mark)
            cand_sums = sums[cand]
        else:
            all_ids = np.concatenate([p[0] for p in acc_parts])
            all_counts = np.concatenate([p[1] for p in acc_parts])
            uids, inv = np.unique(all_ids, return_inverse=True)
            usums = np.bincount(inv,
                                weights=all_counts).astype(np.int64)
            cand = np.unique(np.concatenate(
                [p[0][:p[2]] for p in acc_parts]))
            cand_sums = usums[np.searchsorted(uids, cand)]
        order = np.lexsort((cand, -cand_sums))
        cand, cand_sums = cand[order], cand_sums[order]
        if n and trim:
            cand, cand_sums = cand[:n], cand_sums[:n]
        return [Pair(i, cnt) for i, cnt in zip(cand.tolist(),
                                               cand_sums.tolist())]

    # -- distributed TopN pushdown (ROADMAP item 3) --------------------------

    @staticmethod
    def _topn_to_dict(res) -> dict:
        """Normalize a pushdown leg's result (Pair list off the wire,
        dict from a local/hedge-merged leg, None) to id→count."""
        if res is None:
            return {}
        if isinstance(res, dict):
            return dict(res)
        return {p.id: p.count for p in res}

    @staticmethod
    def _topn_merge_reduce(prev, v):
        """id→count merge across disjoint-slice partials (the
        _top_n_slices reduce shape, reused by hedge sub-legs)."""
        m = prev or {}
        if isinstance(v, dict):
            for k, cnt in v.items():
                m[k] = m.get(k, 0) + cnt
        elif v:
            for p in v:
                m[p.id] = m.get(p.id, 0) + p.count
        return m

    def _topn_exact_partial(self, index: str, c: Call,
                            slices: list[int],
                            opt: ExecOptions) -> list[Pair]:
        """EXACT node-local TopN partials over ``slices``: the
        single-pass rank-cache walk when its safety gates hold, else
        the full local two-phase (candidate gather + ids refetch).
        Untrimmed — every candidate from the per-slice n-trims rides
        back with its exact sum over these slices, so the coordinator
        merge's candidate union equals what a single node spanning
        all slices would mark.

        ``hints`` (an internal arg the coordinator stamps on pushdown
        legs: the candidate ids it already knew when dispatching)
        additionally come back exact-counted in the SAME response — a
        hinted row this node's own trims missed is refetched locally
        here, so a 2-node cluster answers TopN in ONE remote round
        trip instead of two (the round-trip was the measured tax, not
        the compute). A hinted row with no ≥floor count on these
        slices is simply not reported (it contributes zero)."""
        fast = self._topn_host_single_pass(index, c, slices, opt,
                                           allow_remote=True,
                                           trim=False)
        if fast is not None:
            pairs = fast
        else:
            pairs = self._top_n_slices(index, c, slices, opt)
            if pairs:
                other = c.clone()
                other.args.pop("pushdown", None)
                other.args.pop("hints", None)
                other.args["ids"] = sorted({p.id for p in pairs})
                pairs = self._top_n_slices(index, other, slices, opt)
        hints, _ = c.uint_slice_arg("hints")
        if hints:
            have = {p.id for p in pairs}
            missing = sorted(i for i in set(hints) if i not in have)
            if missing:
                other = c.clone()
                other.args.pop("pushdown", None)
                other.args.pop("hints", None)
                other.args["ids"] = missing
                pairs = list(pairs) + self._top_n_slices(
                    index, other, slices, opt)
        return pairs

    def _topn_distributed(self, index: str, c: Call, slices: list[int],
                          opt: ExecOptions,
                          n: int) -> Optional[list[Pair]]:
        """Coordinator side of TopN pushdown, for the plain sourceless
        form on a genuinely distributed index (some slice owned
        elsewhere — locally-covered queries already have the
        single-pass). Each owner runs the single-pass TopN over its
        own slices (``pushdown=true`` legs) and returns untrimmed
        exact (row, count) partials; the coordinator merges per the
        reference two-phase semantics (executor.go:273-310): candidate
        union, then an exact-count refetch ONLY for (node, rows the
        node didn't report) — not all rows on all slices. Failed legs
        re-map onto replicas; hedging composes per leg (the winner's
        partial AND generation tokens count, fault subsystem). Any
        non-lifecycle failure degrades to the fan-out path (None) —
        reads are idempotent, so a partial pushdown attempt is only
        spent work, never a wrong answer."""
        if (not self._topn_pushdown or self.pod is not None
                or opt.partial or self.client is None or not slices
                or len(self.cluster.nodes) < 2
                or not getattr(self.client, "generation_aware", False)
                or self._owns_all_slices(index, slices)):
            return None
        (frame_name, _n, field, row_ids, _thresh, filters,
         tanimoto) = self._topn_args(c)
        if row_ids or c.children or (field and filters) or tanimoto > 0:
            return None
        try:
            with _ctx_span(opt.ctx, "topn_pushdown",
                           slices=len(slices)):
                legs = self._topn_pushdown_gather(index, c, slices, opt)
                merged = self._topn_pushdown_merge(index, c, legs, opt)
        except (QueryDeadlineError, QueryCancelledError):
            raise
        except Exception:  # noqa: BLE001 - fan-out path owns failures
            obs_metrics.TOPN_PUSHDOWN.labels("fallback").inc()
            return None
        obs_metrics.TOPN_PUSHDOWN.labels("merged").inc()
        out = pairs_sort([Pair(i, cnt) for i, cnt in merged.items()
                          if cnt > 0])
        # Remember the merged candidate union (top slice of it) as the
        # next query's speculative hints — bounded per entry and per
        # memo so hot frames stay one-round.
        memo = self._topn_hint_memo
        memo[(index, frame_name)] = tuple(p.id for p in out[:1024])
        memo.move_to_end((index, frame_name))
        while len(memo) > 64:
            memo.popitem(last=False)
        if n and n < len(out):
            out = out[:n]
        return out

    def _topn_pushdown_gather(self, index: str, c: Call,
                              slices: list[int],
                              opt: ExecOptions) -> list[tuple]:
        """Dispatch one pushdown leg per owning node; returns
        [(node, group_slices, id→count, hinted_ids)] with
        _map_reduce's failover semantics (a failed leg's slices re-map
        onto surviving replicas through the breaker-ordered
        placement).

        Remote legs dispatch IMMEDIATELY with SPECULATIVE hints — the
        last merged candidate union for this (index, frame), kept in
        a small memo — and the local partial computes concurrently on
        this thread. Hints are only hints: a hinted row the leg
        doesn't hold reads as zero, and a candidate the speculation
        missed is refetched in a second round — so a cold or stale
        memo costs one extra round trip, never a wrong answer. Warm
        (the repeated-query steady state), the whole distributed TopN
        is ONE remote round trip fully overlapped with local work —
        the round-trip is the measured cluster tax, not the
        compute."""
        nodes = list(self.cluster.nodes)
        ctx = opt.ctx
        pool = self._pool("node")
        futures: dict = {}
        legs: list[tuple] = []
        processed = 0
        groups = self._slices_by_node(nodes, index, slices)
        local_groups: list[tuple] = []
        remote_slices: list[int] = []
        for node, group in groups:
            if node.host == self.host:
                local_groups.append((node, group))
            else:
                remote_slices.extend(group)
        if not remote_slices:
            for node, group in local_groups:
                m = self._topn_to_dict(
                    self._topn_exact_partial(index, c, group, opt))
                legs.append((node, group, m, frozenset()))
            return legs
        frame_name = self._topn_args(c)[0]
        hints = sorted(self._topn_hint_memo.get((index, frame_name),
                                                ()))
        c_pd = c.clone()
        c_pd.args["pushdown"] = True
        if hints:
            c_pd.args["hints"] = hints
        hinted = frozenset(hints)

        def submit(nodes, slices):
            for node, group in self._slices_by_node(nodes, index,
                                                    slices):
                fut = pool.submit(self._topn_pushdown_node, node,
                                  index, c, c_pd, group, opt)
                futures[fut] = (node, group)
                if ctx is not None:
                    ctx.add_leg(node.host, len(group))

        try:
            submit(nodes, remote_slices)
            # Local partials overlap the in-flight remote legs.
            for node, group in local_groups:
                m = self._topn_to_dict(
                    self._topn_exact_partial(index, c, group, opt))
                legs.append((node, group, m, frozenset()))
            while processed < len(remote_slices):
                if ctx is None:
                    done, _ = wait(list(futures),
                                   return_when=FIRST_COMPLETED)
                else:
                    ctx.check()
                    done, _ = wait(list(futures),
                                   timeout=self._CTX_POLL_S,
                                   return_when=FIRST_COMPLETED)
                for fut in done:
                    node, group = futures.pop(fut)
                    try:
                        r = fut.result()
                    except (QueryDeadlineError, QueryCancelledError):
                        raise
                    except Exception as e:  # noqa: BLE001 - re-map
                        nodes = [x for x in nodes if x is not node]
                        obs_metrics.FAILOVER_SLICES.labels(
                            node.host or "local").inc(len(group))
                        if ctx is not None:
                            # Tail sampling: a failover leg is keep-
                            # worthy evidence (obs.sampler "breaker").
                            ctx.note_flag("failover")
                        with _ctx_span(ctx, "failover", peer=node.host,
                                       slices=len(group),
                                       error=type(e).__name__):
                            pass
                        try:
                            submit(nodes, group)
                        except SliceUnavailableError:
                            raise e
                        continue
                    legs.append((node, group, r, hinted))
                    processed += len(group)
        finally:
            pending = [f for f in futures if not f.cancel()]
            if pending:
                if ctx is not None and (ctx.cancelled()
                                        or ctx.expired()):
                    wait(pending, timeout=self._DEAD_DRAIN_S)
                else:
                    wait(pending)
        return legs

    def _topn_pushdown_node(self, node: Node, index: str, c: Call,
                            c_pd: Call, group: list[int],
                            opt: ExecOptions) -> dict:
        """One node's exact partial. Remote legs forward ``c_pd``
        (the call with the ``pushdown`` marker + the coordinator's
        candidate hints), which makes the peer run the whole TopN
        algorithm over its own slices and answer exact untrimmed
        partials INCLUDING the hinted rows (the leg contract the
        merge relies on). A leg re-mapped onto the local replica runs
        the same c_pd semantics in-process, so the hinted-coverage
        bookkeeping stays uniform. Hedging composes: the hedge race
        duplicates the pushdown leg at surviving replicas, first
        response wins, and only the winner's generation tokens reach
        the map."""
        with sched_context.use(opt.ctx):
            if opt.ctx is not None:
                opt.ctx.check()
            if node.host == self.host:
                with _ctx_span(opt.ctx, "leg",
                               host=node.host or "local",
                               slices=len(group)):
                    return self._topn_to_dict(
                        self._topn_exact_partial(index, c_pd, group,
                                                 opt))
            hedge_s = (self.fault.hedge_delay_s(node.host)
                       if self.fault is not None else None)
            if hedge_s:
                res = self._exec_remote_hedged(
                    node, index, c_pd, group, opt, None,
                    self._topn_merge_reduce, hedge_s,
                    local_fn=lambda sl: self._topn_exact_partial(
                        index, c_pd, sl, opt))
            else:
                rs = self._exec_remote(node, index, Query([c_pd]),
                                       group, opt)
                res = rs[0] if rs else None
            return self._topn_to_dict(res)

    def _topn_pushdown_merge(self, index: str, c: Call,
                             legs: list[tuple],
                             opt: ExecOptions) -> dict:
        """Two-phase merge of per-node partials: sum what every node
        reported, then refetch exact counts ONLY for (node, rows in
        the union that node didn't report AND wasn't hinted about) — a
        row trimmed out (or absent) on one node still collects its
        counts there before the global trim. Hinted rows are already
        covered by the leg's own response (zero if unreported), so on
        a 2-node cluster the refetch set is empty by construction —
        except the coordinator's own leg, whose refetch is in-process
        and pays no round trip."""
        union: set = set()
        for _node, _group, m, _hinted in legs:
            union.update(m)
        total: dict = {}
        for _node, _group, m, _hinted in legs:
            for k, cnt in m.items():
                total[k] = total.get(k, 0) + cnt
        jobs = []
        for node, group, m, hinted in legs:
            missing = sorted(i for i in union
                             if i not in m and i not in hinted)
            if missing:
                jobs.append((node, group, missing))
        if not jobs:
            return total
        pool = self._pool("node")
        futs = [pool.submit(self._topn_refetch_leg, node, index, c,
                            group, missing, opt)
                for node, group, missing in jobs]
        try:
            for fut in futs:
                m = fut.result()
                for k, cnt in m.items():
                    total[k] = total.get(k, 0) + cnt
        finally:
            pending = [f for f in futs if not f.cancel()]
            if pending:
                wait(pending)
        return total

    def _topn_refetch_leg(self, node: Node, index: str, c: Call,
                          group: list[int], ids: list[int],
                          opt: ExecOptions) -> dict:
        """Exact counts for ``ids`` over one node's slices (the
        reference phase-2 shape, restricted to the rows that node is
        missing)."""
        with sched_context.use(opt.ctx):
            if opt.ctx is not None:
                opt.ctx.check()
            other = c.clone()
            other.args.pop("pushdown", None)
            other.args["ids"] = [int(i) for i in ids]
            if node.host == self.host:
                return self._topn_to_dict(
                    self._top_n_slices(index, other, group, opt))
            rs = self._exec_remote(node, index, Query([other]), group,
                                   opt)
            return self._topn_to_dict(rs[0] if rs else None)

    def _top_n_slices(self, index: str, c: Call, slices: list[int],
                      opt: ExecOptions) -> list[Pair]:
        def map_fn(slice):
            return self._top_n_slice(index, c, slice)

        def reduce_fn(prev, v):
            # Accumulate id→count in a plain dict across the whole
            # reduce chain and materialize Pairs ONCE at the end —
            # pairs_add's rebuild-a-Pair-list-per-merge costs O(total)
            # per step, which at 256 slices × ~200 candidates was a
            # third of the query (cache.go:343-361 semantics kept).
            # prev is always None or a prior return of this function;
            # v is a dict (pre-reduced group) or a leg's Pair list.
            m = prev or {}
            if isinstance(v, dict):
                for k, cnt in v.items():
                    m[k] = m.get(k, 0) + cnt
            elif v:
                for p in v:
                    m[p.id] = m.get(p.id, 0) + p.count
            return m

        device_fn = self._topn_local_device_fn(index, c, opt)
        host_fn = self._topn_local_host_fn(index, c)

        def local_fn(batch: list[int]):
            if device_fn is not None:
                out = device_fn(batch)
                if out is not NotImplemented:
                    return out
            if host_fn is not None:
                return host_fn(batch)
            return NotImplemented

        merged = self._map_reduce(index, slices, c, opt, map_fn,
                                  reduce_fn, local_fn=local_fn)
        if isinstance(merged, dict):
            merged = [Pair(i, cnt) for i, cnt in merged.items()]
        return pairs_sort(merged or [])

    def _topn_local_device_fn(self, index: str, c: Call, opt: ExecOptions):
        """Batched local-leg TopN exact-count phase: ALL candidate rows ×
        ALL slices in one psum-reduced mesh program.

        Eligible for the with-source exact-count forms — explicit
        candidate ids plus a device-compilable source bitmap. The plain
        form is a mesh reduction (parallel.mesh.topn_exact); threshold>1
        and Tanimoto run the per-slice pruning on device
        (mesh.topn_filtered_sharded, fragment.go:560-614 semantics);
        attribute filters drop candidates host-side first (row attrs
        are frame-global, so pre-filtering ids is exactly the per-slice
        filter). The ids-without-source form stays host-side on
        purpose: there the per-slice path answers from RankCache
        counts, and the device's fresh popcounts could disagree with a
        stale cache entry. Everything else keeps the per-slice path,
        which owns the full semantics.
        """
        if not self.use_mesh:
            return None
        if self.pod is None and self._mesh_backoff_active():
            return None
        row_ids, _ = c.uint_slice_arg("ids")
        if not row_ids:
            return None  # candidate-selection phase reads rank caches
        min_threshold, _ = c.uint_arg("threshold")
        tanimoto, _ = c.uint_arg("tanimotoThreshold")
        if tanimoto > 100:
            return None  # host path owns the error semantics
        field = c.args.get("field")
        filters = c.args.get("filters")
        if len(c.children) != 1:
            return None
        frame_name = c.args.get("frame") or DEFAULT_FRAME
        leaves: list[tuple] = []
        expr = self._compile_device_expr(index, c.children[0], leaves)
        if expr is None:
            return None
        threshold = max(min_threshold, MIN_THRESHOLD)
        if self.pod is not None:
            if not self.pod.is_coordinator or opt.pod_local:
                return None  # plain local path on pod-internal legs

            def pod_fn(slices: list[int]):
                ids = self._attr_filtered_ids(index, frame_name, row_ids,
                                              field, filters)
                if ids is None:
                    return NotImplemented
                if not ids:
                    return []
                from .ops.packed import WORDS_PER_SLICE
                # Same host-allocation guard as the single-process path,
                # per pod process (every process densifies its shard).
                if (len(slices) < self.mesh_min_slices
                        or self.pod.max_shard_slices(slices) * len(ids)
                        * WORDS_PER_SLICE * 4 > self._TOPN_HOST_BLOCK_BYTES):
                    return NotImplemented
                try:
                    counts = self.pod.topn_exact(
                        index, frame_name, expr, leaves, ids, slices,
                        threshold=threshold, tanimoto=tanimoto)
                except Exception as e:  # noqa: BLE001 - pod host fan-out
                    self._note_device_fallback("pod.topn_exact", e)
                    return NotImplemented  # correct via _pod_host_mapper
                return [Pair(rid, cnt)
                        for rid, cnt in zip(ids, counts) if cnt > 0]
            return pod_fn

        def local_fn(slices: list[int]):
            if len(slices) < self.mesh_min_slices:
                return NotImplemented
            ids = self._attr_filtered_ids(index, frame_name, row_ids,
                                          field, filters)
            if ids is None:
                return NotImplemented
            if not ids:
                return []
            from .ops.packed import WORDS_PER_SLICE
            # Host-allocation guard: huge candidate sets stay on the
            # per-slice path, which never materializes a dense block.
            block_bytes = len(slices) * len(ids) * WORDS_PER_SLICE * 4
            if block_bytes > self._TOPN_HOST_BLOCK_BYTES:
                return NotImplemented
            mesh = self._mesh_or_none()
            if mesh is None:
                return NotImplemented
            from .parallel import mesh as mesh_mod
            from .parallel.residency import device_cache
            resident_ok = (len(slices) <= mesh_mod.slice_chunk_bound(
                mesh.shape[mesh_mod.AXIS_SLICES])
                and block_bytes <= mesh_mod.TOPN_BLOCK_BYTES)
            # Cold estimate: the candidate block (the dominant upload)
            # counts as cold unless it is already resident; the
            # streaming form re-packs it every query, so it is always
            # cold there. Leaf slabs add their own cold rows.
            rows_key = self._topn_rows_key(mesh, index, frame_name,
                                           tuple(ids), tuple(slices))
            cold = self._cold_leaves(mesh, index, leaves, slices)
            if not (resident_ok and device_cache().contains(rows_key)):
                cold += len(ids)
            if not self._device_pays(mesh, len(ids) + len(leaves),
                                     len(slices), cold_rows=cold,
                                     streaming=not resident_ok):
                return NotImplemented  # calibrated: host clearly faster
            try:
                def run():
                    if resident_ok:
                        return self._topn_exact_resident(
                            mesh, index, frame_name, expr, leaves,
                            tuple(ids), tuple(slices), threshold,
                            tanimoto, rows_key=rows_key)
                    return mesh_mod.topn_exact(
                        mesh, expr,
                        self._pack_candidate_rows(index, frame_name,
                                                  ids, slices),
                        self._pack_leaf_block(index, leaves, slices),
                        threshold=threshold, tanimoto=tanimoto)
                # Same drift feedback the Count device leg gets — the
                # TopN exact phase is the other big routed surface.
                # The streaming form records under its own leg: the
                # prediction now prices the per-query host-side block
                # packing (Calibration.pack_bps), so its samples feed
                # correction instead of being excluded (r4 review
                # finding superseded by VERDICT r4 item 6).
                counts = self._timed_device_leg(
                    run, len(ids) + len(leaves), len(slices),
                    cold_rows=cold, streaming=not resident_ok)
            except Exception as e:  # noqa: BLE001 - device trouble ≠ node down
                self._note_device_fallback("topn_exact", e)
                return NotImplemented
            return [Pair(rid, cnt)
                    for rid, cnt in zip(ids, counts) if cnt > 0]

        return local_fn

    def _attr_filtered_ids(self, index: str, frame_name: str,
                           row_ids, field, filters) -> Optional[list[int]]:
        """Candidate ids surviving the attribute filter. Row attrs are
        frame-global, so pre-filtering equals the per-slice filter
        (fragment.top). None = no attr store (caller falls back)."""
        if not (field and filters):
            return list(row_ids)
        frame = self.holder.frame(index, frame_name)
        store = frame.row_attr_store if frame else None
        if store is None:
            return None
        fset = set(filters)
        return [rid for rid in row_ids
                if (val := (store.attrs(rid) or {}).get(field))
                is not None and val in fset]

    def _pack_candidate_rows(self, index: str, frame_name: str,
                             row_ids: list[int],
                             slices: list[int]) -> np.ndarray:
        """[n_slices, n_rows, words] dense candidate block, host-side."""
        from .ops.packed import WORDS_PER_SLICE
        rows = np.zeros((len(slices), len(row_ids), WORDS_PER_SLICE),
                        dtype=np.uint32)
        for si, slice in enumerate(slices):
            frag = self.holder.fragment(index, frame_name,
                                        VIEW_STANDARD, slice)
            if frag is None:
                continue
            # Bypass the packed-row LRU when this candidate set
            # exceeds the fragment's own budget (0% hit rate, pure
            # churn against the hot leaf rows).
            cached = len(row_ids) <= frag.device.max_rows
            for ri, rid in enumerate(row_ids):
                frag.pack_row(rid, out=rows[si, ri], cached=cached)
        return rows

    def _topn_rows_key(self, mesh, index: str, frame_name: str,
                       row_ids: tuple[int, ...],
                       slices: tuple[int, ...]) -> tuple:
        from .parallel import mesh as mesh_mod
        frags = [self.holder.fragment(index, frame_name, VIEW_STANDARD, s)
                 for s in slices]
        gens = tuple((f.device.uid, f.device.generation) if f is not None
                     else (0, 0) for f in frags)
        n_dev = mesh.shape[mesh_mod.AXIS_SLICES]
        return ("topnrows", id(self.holder), index, frame_name, row_ids,
                slices, gens, n_dev)

    def _topn_exact_resident(self, mesh, index: str, frame_name: str,
                             expr, leaves: list[tuple],
                             row_ids: tuple[int, ...],
                             slices: tuple[int, ...],
                             threshold: int = 1,
                             tanimoto: int = 0,
                             rows_key: Optional[tuple] = None
                             ) -> list[int]:
        """TopN exact counts with the candidate block and leaf slabs
        device-resident (budgeted HBM cache) — repeat TopN queries skip
        the per-query pack + upload entirely. threshold>1 / tanimoto
        engage the per-slice pruning program (mesh.topn_filtered_sharded)."""
        from .parallel import mesh as mesh_mod
        from .parallel import residency
        frags = [self.holder.fragment(index, frame_name, VIEW_STANDARD, s)
                 for s in slices]
        key = rows_key if rows_key is not None else self._topn_rows_key(
            mesh, index, frame_name, row_ids, slices)
        rows_arr = residency.candidate_block(mesh, key, frags, row_ids)
        leaf_arrays = [self._leaf_device_array(mesh, index, leaf, slices)
                       for leaf in leaves]
        if threshold > 1 or tanimoto > 0:
            return mesh_mod.topn_filtered_sharded(
                mesh, expr, rows_arr, leaf_arrays,
                threshold=threshold, tanimoto=tanimoto)
        return mesh_mod.topn_exact_sharded(mesh, expr, rows_arr,
                                           leaf_arrays)

    def _topn_local_host_fn(self, index: str, c: Call):
        """Vectorized host leg for the sourceless TopN forms: one
        rank-array pass per fragment, merged as a single id→count dict
        (the reduce_fn's pre-reduced-group shape). The per-slice
        map_fn path builds a Pair per (slice, candidate) — ~4 M Python
        objects at 1024 slices × 1000 candidates, measured ~2.4 s p50;
        this leg replays the same per-slice semantics (floor, then
        per-slice n-trim for the plain form) in numpy, ~50 ms.
        Returns None when the form needs the general path (source
        bitmap, attribute filters, Tanimoto)."""
        (frame_name, n, field, row_ids, min_threshold, filters,
         tanimoto) = self._topn_args(c)
        if (len(c.children) > 0 or (field and filters) or tanimoto > 0):
            return None
        if self.pod is not None:
            # Pod processes shard fragments pod-internally: a batch here
            # includes slices whose data lives on OTHER processes, which
            # this leg would silently count as empty — the podLocal
            # host mapper owns that fan-out.
            return None

        def host_fn(batch: list[int]):
            import numpy as np

            from .storage.cache import LRUCache
            floor = max(min_threshold, 1)
            merged_ids: list[np.ndarray] = []
            merged_counts: list[np.ndarray] = []
            row_arr = (np.asarray(sorted(row_ids), dtype=np.uint64)
                       if row_ids else None)
            for slice in batch:
                frag = self.holder.fragment(index, frame_name,
                                            VIEW_STANDARD, slice)
                if frag is None or not hasattr(frag.cache,
                                               "top_arrays"):
                    continue
                if row_arr is not None and not isinstance(frag.cache,
                                                          LRUCache):
                    # RankCache rankings are rate-limited (stale up to
                    # 10 s) and threshold-trimmed; the per-slice path's
                    # cache.get() reads fresh entries — only the LRU
                    # cache's arrays are equivalent to get() (review
                    # finding: ranked frames returned stale counts).
                    return NotImplemented
                if getattr(frag, "tier_state", "hot") != "hot":
                    # TopN ranks through the count cache, which
                    # demotion drops — a cold/blob fragment must fully
                    # promote (rebuilding the rank cache) before its
                    # arrays mean anything, same contract as the
                    # per-slice fragment.top gate.
                    frag.promote(trigger="read")
                # Same lock the per-slice fragment.top path holds:
                # cache recalculation and the positions walk race
                # concurrent writers otherwise.
                with frag._mu:
                    frag.cache.invalidate()
                    ids, counts = frag.cache.top_arrays()
                    if row_arr is None:
                        # plain form: the ≥-floor prefix, then the
                        # per-slice n trim (fragment.top's array path).
                        cut = len(counts) - int(np.searchsorted(
                            counts[::-1], floor, side="left"))
                        ids, counts = ids[:cut], counts[:cut]
                        if n:
                            ids, counts = ids[:n], counts[:n]
                    elif len(ids) == 0:
                        # empty cache (e.g. lost sidecar): every
                        # candidate goes through the recount fallback.
                        ids, counts = self._topn_recount(
                            frag, row_arr,
                            np.zeros(len(row_arr), np.int64),
                            np.arange(len(row_arr)), floor)
                    else:
                        # ids form (the exact-count refetch):
                        # per-slice counts per candidate; cache misses
                        # with bits recount via row_count
                        # (fragment._top_pairs semantics) + the
                        # per-slice floor.
                        order = np.argsort(ids)
                        sids, scounts = ids[order], counts[order]
                        pos = np.minimum(
                            np.searchsorted(sids, row_arr),
                            len(sids) - 1)
                        hit = sids[pos] == row_arr
                        got = np.where(hit, scounts[pos],
                                       0).astype(np.int64)
                        missing = np.flatnonzero(~hit | (got <= 0))
                        ids, counts = self._topn_recount(
                            frag, row_arr, got, missing, floor)
                if len(ids):
                    merged_ids.append(ids.astype(np.uint64))
                    merged_counts.append(counts.astype(np.int64))
            if not merged_ids:
                return {}
            all_ids = np.concatenate(merged_ids)
            all_counts = np.concatenate(merged_counts)
            uids, inv = np.unique(all_ids, return_inverse=True)
            sums = np.bincount(inv, weights=all_counts).astype(np.int64)
            return dict(zip(uids.tolist(), sums.tolist()))

        return host_fn

    @staticmethod
    def _topn_recount(frag, row_arr, got, missing, floor):
        """Recount the ``missing`` candidate positions of ``got`` via
        row_count — but only for rows that actually have bits here
        (fragment.present_rows; the blind per-id recount was ~900 K
        walks at 1024 slices). Returns the ≥-floor (ids, counts).
        Caller holds frag._mu."""
        if len(missing):
            present = frag.present_rows()
            if present is not None:
                have = np.isin(row_arr[missing], present)
                missing = missing[have]
            if len(missing):
                got = got.copy()
                for mi in missing.tolist():
                    got[mi] = frag.row_count(int(row_arr[mi]))
        keep = got >= floor
        return row_arr[keep], got[keep]

    def _top_n_slice(self, index: str, c: Call, slice: int) -> list[Pair]:
        # executor.go:325-396. Args parse once per call object, not per
        # slice — a 256-slice fan-out re-converting a 1000-entry ids
        # list per slice per phase was measurable.
        parsed = self._topn_args(c)
        (frame_name, n, field, row_ids, min_threshold, filters,
         tanimoto) = parsed

        src = None
        if len(c.children) == 1:
            src = self._bitmap_call_slice(index, c.children[0], slice)
        elif len(c.children) > 1:
            raise PilosaError("TopN() can only have one input bitmap")

        frag = self.holder.fragment(index, frame_name, VIEW_STANDARD, slice)
        if frag is None:
            return []
        # Validation ordering matches the reference: tanimoto bounds
        # are checked by Fragment.Top AFTER the nil-fragment return
        # (fragment.go:490-625) — a bad threshold against a missing
        # fragment is an empty result, not an error.
        if tanimoto > 100:
            raise PilosaError("Tanimoto Threshold is from 1 to 100 only")
        return frag.top(TopOptions(
            n=n, src=src, row_ids=row_ids, filter_field=field,
            filter_values=filters, min_threshold=min_threshold,
            tanimoto_threshold=tanimoto))

    def _topn_args(self, c: Call):
        """Parsed TopN arguments, memoized on the Call object (one
        query evaluates the same immutable call across many slices and
        two phases). Pure parsing only — value validation stays in
        _top_n_slice to preserve the reference's error ordering."""
        parsed = getattr(c, "_topn_parsed", None)
        if parsed is not None:
            return parsed
        frame_name = c.args.get("frame") or DEFAULT_FRAME
        n, _ = c.uint_arg("n")
        field = c.args.get("field", "")
        row_ids, _ = c.uint_slice_arg("ids")
        min_threshold, _ = c.uint_arg("threshold")
        filters = c.args.get("filters") or []
        tanimoto, _ = c.uint_arg("tanimotoThreshold")
        if min_threshold <= 0:
            min_threshold = MIN_THRESHOLD
        parsed = (frame_name, n, field, row_ids, min_threshold, filters,
                  tanimoto)
        c._topn_parsed = parsed
        return parsed

    # -- writes (executor.go:600-797) ----------------------------------------

    # Minimum consecutive same-kind mutation calls before the batched
    # write path engages (below this the per-op path's fixed cost wins).
    _BATCH_MIN_MUTATES = 8

    def _mutate_batch_run(self, index: str, calls: list[Call], start: int,
                          opt: ExecOptions):
        """(results, n_calls) for a maximal run of consecutive
        timestamp-free SetBit (or ClearBit) calls, applied through the
        fragments' native batch engine — ONE native crossing + ONE WAL
        group-commit per touched fragment. Only fully-LOCAL runs batch:
        if any leg would forward to a remote node or another pod
        process, the run falls back to the per-op path, whose
        apply-prefix-then-raise semantics on a mid-stream forwarding
        failure are the reference's (executor.go:664-691,768-797) —
        a batch that had already applied local mutations for later
        calls would otherwise break execute_partial's prefix contract
        (review r5). Also falls back (None) on anything unusual —
        wrong view, timestamps, missing args — so error semantics stay
        exactly per-op."""
        name = calls[start].name
        if name not in ("SetBit", "ClearBit"):
            return None
        n = len(calls)
        j = start
        while (j < n and calls[j].name == name
               and "timestamp" not in calls[j].args
               and calls[j].args.get("view", "") in
               ("", VIEW_STANDARD, VIEW_INVERSE)):
            j += 1
        count = j - start
        if count < self._BATCH_MIN_MUTATES:
            return None
        run = calls[start:j]
        set_ = name == "SetBit"
        idx_obj = self.holder.index(index)
        if idx_obj is None:
            raise IndexNotFoundError(index)

        # Parse phase — nothing is applied until every call parses, so
        # a fallback to the per-op path never double-applies.
        frames: dict[str, object] = {}
        ops: list[tuple] = []  # (k, frame_name, row, col, view)
        for k, c in enumerate(run):
            fname = c.args.get("frame")
            if not fname:
                return None
            frame = frames.get(fname)
            if frame is None:
                frame = idx_obj.frame(fname)
                if frame is None:
                    return None
                frames[fname] = frame
            try:
                row_id, ok = c.uint_arg(frame.row_label)
                col_id, ok2 = c.uint_arg(idx_obj.column_label)
            except (PilosaError, ValueError, TypeError):
                # Non-integer id value: fall back so the per-op path
                # applies the prefix then raises, exactly like the
                # reference's sequential loop.
                return None
            if not (ok and ok2):
                return None
            ops.append((k, fname, row_id, col_id,
                        c.args.get("view", "")))

        results = [False] * count
        # view-ops: (call_k, frame_name, view, axis_row, axis_col) where
        # axis_col routes the slice (for inverse views that is the
        # original row id — executor.go:744-745).
        vops: list[tuple] = []
        for k, fname, row_id, col_id, view in ops:
            frame = frames[fname]
            if view in ("", VIEW_STANDARD):
                vops.append((k, fname, VIEW_STANDARD, row_id, col_id))
            if (view == VIEW_INVERSE
                    or (view == "" and frame.inverse_enabled)):
                vops.append((k, fname, VIEW_INVERSE, col_id, row_id))

        local_groups: dict[tuple, list] = {}   # (frame, view) -> [vop]
        for vop in vops:
            k, fname, view, axis_row, axis_col = vop
            slice = axis_col // SLICE_WIDTH
            for node in self.cluster.fragment_nodes(index, slice):
                if node.host == self.host:
                    if (self.pod is not None and not opt.pod_local
                            and self.pod.owner_pid(slice)
                            != self.pod.pid):
                        return None  # pod-forwarded leg: per-op path
                    local_groups.setdefault((fname, view),
                                            []).append(vop)
                    continue
                if not opt.remote:
                    return None  # remote replica leg: per-op path

        for (fname, view), group in local_groups.items():
            rows = np.fromiter((g[3] for g in group), np.uint64,
                               len(group))
            cols = np.fromiter((g[4] for g in group), np.uint64,
                               len(group))
            changed = frames[fname].mutate_bits(view, rows, cols, set_)
            for g, ch in zip(group, changed.tolist()):
                if ch:
                    results[g[0]] = True

        return results, count

    def _point_mutate_fast(self, index: str, m, opt: ExecOptions
                           ) -> Optional[list]:
        """The string lane's warm half: a ``_POINT_MUTATE_RE`` match
        plus a hot ``_wfast_frag`` entry go straight to the fragment
        mutate. Returns None on any miss — cold cache, closed
        fragment, non-default labels (ent[2]), or a cluster that is
        no longer this single node — and the generic path (which owns
        errors and cache population) re-runs the op from the string."""
        col_id = int(m.group(4))
        ent = self._wfast_frag.get(
            (index, m.group(2), col_id // SLICE_WIDTH))
        if ent is None or not ent[2] or not ent[1]._open:
            return None
        nodes = self.cluster.nodes
        if (len(nodes) != 1 or nodes[0].host != self.host
                or self.cluster.resize is not None):
            # An in-flight resize (1→2 grow) means even a single-node
            # cluster's writes must fan to the union — generic path.
            return None
        if opt.ctx is not None:
            opt.ctx.check()
        frag = ent[1]
        if m.group(1) == "SetBit":
            return [frag.set_bit(int(m.group(3)), col_id)]
        return [frag.clear_bit(int(m.group(3)), col_id)]

    def _execute_set_bit(self, index: str, c: Call, opt: ExecOptions
                         ) -> bool:
        return self._execute_mutate_bit(index, c, opt, set=True)

    def _execute_clear_bit(self, index: str, c: Call, opt: ExecOptions
                           ) -> bool:
        return self._execute_mutate_bit(index, c, opt, set=False)

    def _execute_mutate_bit(self, index: str, c: Call, opt: ExecOptions,
                            set: bool) -> bool:
        # Per-op write fast lane: the production single-op shape
        # (standard view, no timestamp, single-node non-pod cluster,
        # this node the sole owner) resolves (index, frame, slice) ->
        # Fragment through a small cache instead of re-walking
        # placement hashing + frame -> view -> fragment locks per op —
        # the walk cost more than the mutate itself (ISSUE 8). Any
        # unusual shape falls through to the generic path below, which
        # also owns every error message.
        args = c.args
        if ("timestamp" not in args and not args.get("view")
                and self.pod is None
                and self.cluster.resize is None):
            nodes = self.cluster.nodes
            if len(nodes) == 1 and nodes[0].host == self.host:
                idx = self.holder.index(index)
                fname = args.get("frame")
                frame = (idx.frame(fname)
                         if idx is not None and fname else None)
                if frame is not None and not frame.inverse_enabled:
                    row_id = args.get(frame.row_label)
                    col_id = args.get(idx.column_label)
                    if (type(row_id) is int and type(col_id) is int
                            and row_id >= 0 and col_id >= 0):
                        fkey = (index, fname, col_id // SLICE_WIDTH)
                        ent = self._wfast_frag.get(fkey)
                        if (ent is None or ent[0] is not frame
                                or not ent[1]._open):
                            v = frame.create_view_if_not_exists(
                                VIEW_STANDARD)
                            # Third slot: the string lane's one-read
                            # precondition — default labels, so the
                            # regex's literal rowID/columnID keys are
                            # the frame's actual labels (inverse off
                            # is already a condition of being here,
                            # and label/inverse options are fixed at
                            # frame creation).
                            ent = (frame,
                                   v.create_fragment_if_not_exists(
                                       fkey[2]),
                                   frame.row_label == "rowID"
                                   and idx.column_label == "columnID")
                            if len(self._wfast_frag) >= 4096:
                                # Bound the cache without per-op LRU
                                # bookkeeping on the hot read: drop it
                                # wholesale (rebuilds in a few ops) so
                                # entries for deleted frames can't pin
                                # closed fragments forever.
                                self._wfast_frag.clear()
                            self._wfast_frag[fkey] = ent
                        frag = ent[1]
                        return (frag.set_bit(row_id, col_id) if set
                                else frag.clear_bit(row_id, col_id))
        name = "SetBit" if set else "ClearBit"
        view = c.args.get("view", "")
        frame_name = c.args.get("frame")
        if not frame_name:
            raise PilosaError(f"{name}() frame required")
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError(index)
        frame = idx.frame(frame_name)
        if frame is None:
            raise FrameNotFoundError(frame_name)

        row_id, ok = c.uint_arg(frame.row_label)
        if not ok:
            raise PilosaError(
                f"{name}() row field '{frame.row_label}' required")
        col_id, ok = c.uint_arg(idx.column_label)
        if not ok:
            raise PilosaError(
                f"{name}() column field '{idx.column_label}' required")
        timestamp = _parse_timestamp(c) if set else None

        if view == VIEW_STANDARD:
            return self._mutate_bit_view(index, c, frame, view, col_id,
                                         row_id, timestamp, opt, set)
        if view == VIEW_INVERSE:
            return self._mutate_bit_view(index, c, frame, view, row_id,
                                         col_id, timestamp, opt, set)
        if view == "":
            ret = self._mutate_bit_view(index, c, frame, VIEW_STANDARD,
                                        col_id, row_id, timestamp, opt, set)
            if frame.inverse_enabled:
                if self._mutate_bit_view(index, c, frame, VIEW_INVERSE,
                                         row_id, col_id, timestamp, opt,
                                         set):
                    ret = True
            return ret
        raise PilosaError(f"invalid view: {view}")

    def _mutate_bit_view(self, index: str, c: Call, frame, view: str,
                         col_id: int, row_id: int,
                         timestamp: Optional[dt.datetime], opt: ExecOptions,
                         set: bool) -> bool:
        # Route to every replica owner of the slice (executor.go:664-691,
        # 768-797). In the view axis convention, col_id is the id that
        # chooses the slice (for inverse views that is the original row id).
        from . import SLICE_WIDTH
        slice = col_id // SLICE_WIDTH
        ret = False
        for node in self.cluster.fragment_nodes(index, slice):
            if node.host == self.host:
                if (self.pod is not None and not opt.pod_local
                        and self.pod.owner_pid(slice) != self.pod.pid):
                    # This pod owns the slice, but a different pod
                    # process holds it — forward the single-view call
                    # as a podLocal leg (parallel.pod placement).
                    if self._pod_write_remote(index, c, view, slice):
                        ret = True
                    continue
                op = frame.set_bit if set else frame.clear_bit
                if op(view, row_id, col_id, timestamp):
                    ret = True
                continue
            if opt.remote:
                continue
            res = self._exec_remote(node, index, Query([c]), None, opt)
            if res and res[0]:
                ret = True
        return ret

    def _pod_write_remote(self, index: str, c: Call, view: str,
                          slice: int) -> bool:
        """Forward one view's bit mutation to the owning pod process and
        remember the slice exists (the coordinator computes query slice
        lists from its own max-slice knowledge)."""
        pid = self.pod.owner_pid(slice)
        other = c.clone()
        other.args["view"] = view  # pin: owner differs per view axis
        if self.client is None:
            raise SliceUnavailableError(
                f"no client to reach pod process {pid}")
        res = self.client.execute_query(
            Node(self.pod.peers[pid]), index, str(Query([other])), None,
            remote=True, pod_local=True)
        idx = self.holder.index(index)
        if idx is not None:
            if view == VIEW_INVERSE:
                idx.set_remote_max_inverse_slice(slice)
            else:
                idx.set_remote_max_slice(slice)
        return bool(res and res[0])

    def _pod_forward_attrs(self, index: str, calls: list[Call],
                           opt: ExecOptions) -> None:
        """Attribute writes replicate to every pod process (workers read
        their own attr stores for TopN filters), even on cluster-remote
        legs — only podLocal legs stop the fan-out."""
        if (self.pod is None or opt.pod_local
                or not self.pod.is_coordinator or self.client is None):
            return
        q = str(Query(list(calls)))
        for pid in range(1, self.pod.n_procs):
            self.client.execute_query(Node(self.pod.peers[pid]), index,
                                      q, None, remote=True, pod_local=True)

    # -- attributes (executor.go:800-988) ------------------------------------

    def _row_attrs_of(self, index: str, c: Call) -> tuple[str, object,
                                                          int, dict]:
        """Resolve a SetRowAttrs call → (frame_name, frame, row_id,
        attrs-minus-reserved-keys)."""
        frame_name = c.args.get("frame")
        if not frame_name:
            raise PilosaError("SetRowAttrs() frame required")
        frame = self.holder.frame(index, frame_name)
        if frame is None:
            raise FrameNotFoundError(frame_name)
        row_id, ok = c.uint_arg(frame.row_label)
        if not ok:
            raise PilosaError(
                f"SetRowAttrs() row field '{frame.row_label}' required")
        attrs = dict(c.args)
        attrs.pop("frame", None)
        attrs.pop(frame.row_label, None)
        return frame_name, frame, row_id, attrs

    def _execute_set_row_attrs(self, index: str, c: Call,
                               opt: ExecOptions) -> None:
        _, frame, row_id, attrs = self._row_attrs_of(index, c)
        frame.row_attr_store.set_attrs(row_id, attrs)
        self._pod_forward_attrs(index, [c], opt)
        self._broadcast_call(index, [c], opt)

    def _execute_bulk_set_row_attrs(self, index: str, calls: list[Call],
                                    opt: ExecOptions) -> list:
        # executor.go:857-941: group attrs by frame/row, bulk insert.
        by_frame: dict[str, dict[int, dict]] = {}
        for c in calls:
            frame_name, _, row_id, attrs = self._row_attrs_of(index, c)
            by_frame.setdefault(frame_name, {}).setdefault(
                row_id, {}).update(attrs)
        for frame_name, rows in by_frame.items():
            self.holder.frame(index, frame_name).row_attr_store \
                .set_bulk_attrs(rows)
        self._pod_forward_attrs(index, calls, opt)
        self._broadcast_call(index, calls, opt)
        return [None] * len(calls)

    def _execute_set_column_attrs(self, index: str, c: Call,
                                  opt: ExecOptions) -> None:
        idx = self.holder.index(index)
        if idx is None:
            raise IndexNotFoundError(index)
        id, ok = c.uint_arg("id")
        col_name = "id"
        if not ok:
            id, ok = c.uint_arg(idx.column_label)
            if not ok:
                raise PilosaError("SetColumnAttrs() id required")
            col_name = idx.column_label
        attrs = dict(c.args)
        attrs.pop(col_name, None)
        idx.column_attr_store.set_attrs(id, attrs)
        self._pod_forward_attrs(index, [c], opt)
        self._broadcast_call(index, [c], opt)

    def _broadcast_call(self, index: str, calls: list[Call],
                        opt: ExecOptions) -> None:
        """Forward attribute writes to every other node in parallel
        (executor.go:836-854)."""
        if opt.remote:
            return
        others = [n for n in self.cluster.nodes if n.host != self.host]
        if not others:
            return
        errs = []
        threads = []
        q = Query(list(calls))

        def run(node):
            try:
                self._exec_remote(node, index, q, None, opt)
            except Exception as e:  # noqa: BLE001 - collected and re-raised
                errs.append(e)

        for node in others:
            t = threading.Thread(target=run, args=(node,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

    # -- remote execution (executor.go:1000-1083) ----------------------------

    def _exec_remote(self, node: Node, index: str, query: Query,
                     slices: Optional[list[int]], opt: ExecOptions,
                     gens_out: Optional[list] = None) -> list:
        """``gens_out`` (hedged legs only) defers the response's
        generation tokens to the caller instead of letting the client
        apply them — the hedge race applies the WINNER's tokens only."""
        if self.client is None:
            raise SliceUnavailableError(
                f"no client to reach remote node {node.host}")
        ctx = opt.ctx
        kwargs = {}
        if gens_out is not None and getattr(self.client,
                                            "generation_aware", False):
            kwargs["gens_out"] = gens_out
        t0 = time.perf_counter()
        try:
            with _ctx_span(ctx, "rpc", peer=node.host,
                           slices=len(slices) if slices else 0):
                if ctx is not None and getattr(self.client,
                                               "deadline_aware", False):
                    # The peer inherits the REMAINING budget (not the
                    # original) and the query id, so its leg registers
                    # under the same query and a cluster cancel finds
                    # it; the client clamps socket timeouts + its
                    # idempotent retry to the budget. Scripted test
                    # fakes without the marker keep the plain call
                    # shape.
                    ctx.check()
                    return self.client.execute_query(
                        node, index, str(query), slices, remote=True,
                        deadline_s=ctx.remaining(), query_id=ctx.id,
                        **kwargs)
                return self.client.execute_query(node, index,
                                                 str(query), slices,
                                                 remote=True, **kwargs)
        finally:
            obs_metrics.RPC_SECONDS.labels(
                peer=node.host, kind="query").observe(
                    time.perf_counter() - t0)

    def _apply_remote_gens(self, gens_list: list) -> None:
        """Apply a deferred (peer, payload) token list — the winning
        hedge leg's — to the coordinator generation map."""
        if self.gens is None:
            return
        for peer, payload in gens_list:
            self.gens.apply_wire(peer, payload)

    # -- map-reduce core (executor.go:1087-1236) -----------------------------

    def _slices_by_node(self, nodes: list[Node], index: str,
                        slices: list[int],
                        missing: Optional[list] = None
                        ) -> list[tuple[Node, list[int]]]:
        """Group ``slices`` by the replica owner that will serve each.
        With a fault manager attached, owners are consulted in health
        order with open circuits sunk to the end (fault subsystem) —
        so the first query after a peer dies pays one timeout, and
        every query after it routes around the open circuit without
        paying anything. ``missing`` (partial mode) collects slices
        with no owner among ``nodes`` instead of raising.

        Owners come from ``read_nodes`` — READ authority, which equals
        plain placement except during an elastic resize, where a
        stream target's incomplete copy must not serve. This is also
        the server-side fence: a remote leg asking a mid-migration
        target for a moving slice fails here, which is what lets the
        coordinator's double-read treat a successful target leg as
        proof the target considers itself authoritative."""
        fault = self.fault
        # Storage integrity: slices whose LOCAL fragments are
        # quarantined must not be served from this node — skipping
        # the local owner here IS the transparent read failover (the
        # remaining breaker-ordered owners serve; a peer's own
        # quarantine surfaces as its leg failing, which the generic
        # re-map routes around).
        q = getattr(self.holder, "quarantine", None)
        if q is not None and not len(q):
            q = None
        # Tiered storage: same skip for slices whose blob-tier
        # fragments cannot be fetched back (tier.manager blocked set)
        # — no local bytes exist to serve them.
        tier = getattr(self.holder, "tier", None)
        if tier is not None and not tier._blocked_slices:
            tier = None
        m: dict[int, tuple[Node, list[int]]] = {}
        # Placement ordering memo: PARTITION_N bounds the distinct
        # owner tuples, so a 256-slice query pays ≤16 order_nodes
        # calls (each is a sort + per-owner breaker/health consults)
        # instead of one per slice.
        order_memo: dict[tuple, list[Node]] = {}
        for slice in slices:
            owners = self.cluster.read_nodes(index, slice)
            if fault is not None and len(owners) > 1:
                key = tuple(id(n) for n in owners)
                ordered = order_memo.get(key)
                if ordered is None:
                    ordered = order_memo[key] = fault.order_nodes(
                        owners, local=self.host)
                owners = ordered
            for node in owners:
                if (q is not None and node.host == self.host
                        and q.slice_blocked(index, slice)):
                    ctx = sched_context.current()
                    if ctx is not None:
                        # Tail sampling: a corruption-driven failover
                        # is keep-worthy (obs.sampler "corruption").
                        ctx.note_flag("corruption")
                    continue
                if (tier is not None and node.host == self.host
                        and tier.slice_blocked(index, slice)):
                    continue
                if any(n is node for n in nodes):
                    m.setdefault(id(node), (node, []))[1].append(slice)
                    break
            else:
                if missing is not None:
                    missing.append(slice)
                    continue
                raise SliceUnavailableError(str(slice))
        return list(m.values())

    # -- elastic-resize double reads (cluster.resize) ------------------------

    def _resize_moving_groups(self, index: str, slices: list[int]):
        """``{(old_hosts, new_hosts): [slices]}`` for the slices of
        ``slices`` sitting in MIGRATING partitions of an in-flight
        resize, or None when there are none (the hot-path answer —
        one attr read when no resize is in flight)."""
        from .cluster.topology import RESIZE_MIGRATING
        cl = self.cluster
        if cl.resize is None:
            return None
        groups: dict[tuple, list[int]] = {}
        for s in slices:
            mv = cl.moving_slice(index, s)
            if mv is None or mv[0] != RESIZE_MIGRATING:
                continue
            groups.setdefault((mv[1], mv[2]), []).append(s)
        return groups or None

    def _double_read_side(self, hosts, index: str, c: Call,
                          slices: list[int], opt: ExecOptions,
                          map_fn, reduce_fn, local_fn,
                          gens_out: list):
        """One side of a double-read: try each candidate owner in turn
        (local legs compute in-process, remote legs forward with
        private token custody). Raises the last error when every
        candidate failed."""
        cl = self.cluster
        last: Optional[Exception] = None
        ordered = list(hosts)
        if self.fault is not None and len(ordered) > 1:
            ordered = sorted(
                ordered,
                key=lambda h: 0 if (h == self.host
                                    or self.fault.would_allow(h))
                else 1)
        for host in ordered:
            try:
                if host == self.host:
                    # The read-authority fence applies to the local leg
                    # exactly as _slices_by_node applies it to remote
                    # ones: a mid-migration target must not serve its
                    # incomplete copy, even to itself.
                    if not all(cl.read_allowed(host, index, s)
                               for s in slices):
                        raise SliceUnavailableError(
                            f"{host} not read-authoritative for"
                            f" {slices}")
                    with sched_context.use(opt.ctx):
                        if local_fn is not None:
                            r = local_fn(slices)
                            if r is not NotImplemented:
                                return r
                        return self._mapper_local(slices, map_fn,
                                                  reduce_fn)
                node = cl.node_by_host(host) or Node(host)
                rs = self._exec_remote(node, index, Query([c]), slices,
                                       opt, gens_out=gens_out)
                return rs[0] if rs else None
            except (QueryDeadlineError, QueryCancelledError):
                raise
            except Exception as e:  # noqa: BLE001 - next candidate
                last = e
        raise last if last is not None else SliceUnavailableError(
            str(slices))

    def _target_tokens_newest(self, index: str, slices: list[int],
                              gens_list: list) -> bool:
        """The double-read's newest-token-wins check: before the
        TARGET side's answer is accepted, its piggybacked (uid, gen)
        tokens must be at least as new as the map's freshest knowledge
        of each slice — a straggling or rolled-back target (same uid,
        LOWER generation than previously observed) can never win. A
        fresh uid (reopened fragment) reads as newest: its on-disk
        state is the durable acked state."""
        if self.gens is None:
            return True
        from .cluster import generations as gens_mod
        fresh: dict[int, dict] = {}
        peers: dict[int, str] = {}
        for peer, payload in gens_list:
            decoded = gens_mod.decode_wire(payload)
            if decoded is None:
                continue
            idx, tokens = decoded
            if idx != index:
                continue
            for s, toks in tokens.items():
                fresh[s] = toks
                peers[s] = peer
        for s in slices:
            toks = fresh.get(s)
            if toks is None:
                continue  # target reported nothing: nothing to refute
            known = self.gens.tokens(peers.get(s, ""), index, s,
                                     max_age_s=float("inf"))
            if not known:
                continue
            for fk, (uid, gen) in known.items():
                got = toks.get(fk)
                if got is not None and got[0] == uid and got[1] < gen:
                    return False
        return True

    def _exec_double_read(self, index: str, c: Call, slices: list[int],
                          old_hosts, new_hosts, opt: ExecOptions,
                          map_fn, reduce_fn, local_fn=None):
        """A moving slice group's fan-out during the MIGRATING phase
        of an elastic resize (docs/CLUSTER_RESIZE.md): both owner
        sides are queried concurrently —

        - the OLD side is authoritative pre-flip (its copy has every
          bit; the stream target's may not) and wins whenever it
          answers;
        - the NEW side can only answer after it has flipped (the
          read-authority fence in _slices_by_node makes a
          mid-migration target refuse the leg), so a successful target
          answer is proof the epoch advanced under this query — the
          exact window the double-read exists for. It wins only when
          the old side failed AND its piggybacked generation tokens
          are the newest the coordinator map has seen for every slice.

        Token custody follows the hedged-read discipline: each side
        collects privately; ONLY the winner's tokens merge into the
        coordinator map."""
        pool = self._pool("hedge")
        gens_old: list = []
        gens_new: list = []
        f_old = pool.submit(self._double_read_side, old_hosts, index,
                            c, slices, opt, map_fn, reduce_fn,
                            local_fn, gens_old)
        f_new = pool.submit(self._double_read_side, new_hosts, index,
                            c, slices, opt, map_fn, reduce_fn,
                            local_fn, gens_new)
        ctx = opt.ctx
        try:
            while True:
                if ctx is not None:
                    ctx.check()
                if f_old.done():
                    break
                wait([f_old], timeout=(self._CTX_POLL_S
                                       if ctx is not None else None))
        except BaseException:
            f_old.cancel()
            f_new.cancel()
            raise
        try:
            result = f_old.result()
        except (QueryDeadlineError, QueryCancelledError):
            f_new.cancel()
            raise
        except Exception as old_err:  # noqa: BLE001 - target may win
            try:
                while not f_new.done():
                    if ctx is not None:
                        ctx.check()
                    wait([f_new],
                         timeout=(self._CTX_POLL_S
                                  if ctx is not None else None))
                result = f_new.result()
            except (QueryDeadlineError, QueryCancelledError):
                raise
            except Exception:  # noqa: BLE001 - both sides dead
                raise old_err
            if not self._target_tokens_newest(index, slices, gens_new):
                raise old_err
            obs_metrics.RESIZE_DOUBLE_READS.labels("target").inc()
            self._apply_remote_gens(gens_new)
            return result
        obs_metrics.RESIZE_DOUBLE_READS.labels("source").inc()
        self._apply_remote_gens(gens_old)
        # The losing target leg is abandoned, not awaited: its socket
        # timeouts are budget-clamped and its tokens never merge.
        f_new.cancel()
        return result

    # Wake tick of the fan-out wait loop for lifecycle-bound queries:
    # bounds how long a cancellation or deadline expiry can go unseen
    # while every leg is still in flight.
    _CTX_POLL_S = 0.25
    # Grace given to in-flight legs of a DEAD (expired/cancelled)
    # query before abandoning them: each leg is ctx-checked per slice
    # and its remote socket timeouts are clamped to the (now exhausted)
    # budget, so abandoned legs self-terminate promptly — holding the
    # caller (and its admission slot) for a stalled peer would defeat
    # the deadline.
    _DEAD_DRAIN_S = 0.5

    def _map_reduce(self, index: str, slices: list[int], c: Call,
                    opt: ExecOptions, map_fn: Callable,
                    reduce_fn: Callable, local_fn: Callable = None):
        if not slices:
            return None
        if opt.remote:
            nodes = [self.cluster.node_by_host(self.host)]
        else:
            nodes = list(self.cluster.nodes)

        ctx = opt.ctx
        if ctx is not None:
            ctx.check()
            # Every slice leg re-checks the budget on entry, so an
            # expiry stops the per-slice map mid-fan-out instead of
            # draining the whole slice list.
            inner_map, inner_local = map_fn, local_fn

            def map_fn(slice, _m=inner_map):
                ctx.check()
                return _m(slice)

            if inner_local is not None:
                def local_fn(batch, _l=inner_local):
                    ctx.check()
                    return _l(batch)

        result = None
        processed = 0
        pool = self._pool("node")
        futures: dict = {}
        # Degraded reads (?partial=1): slices with no reachable
        # replica land here instead of failing the query; the handler
        # reports them as X-Pilosa-Partial.
        missing: Optional[list] = None
        if opt.partial:
            if opt.missing_slices is None:
                opt.missing_slices = []
            missing = opt.missing_slices

        def submit(nodes, slices):
            nonlocal processed
            before = len(missing) if missing is not None else 0
            # Elastic resize, migrating phase: moving slices fan out as
            # DOUBLE-READ legs (old owner authoritative, new owner the
            # fenced fallback) instead of riding the normal grouping —
            # health ordering must never route a read to a target whose
            # copy is still streaming. The sentinel node None marks
            # these futures: their failover lives inside the leg, so
            # the outer re-map must not retry them.
            if not opt.remote and self.cluster.resize is not None:
                groups = self._resize_moving_groups(index, slices)
                if groups:
                    moved = set()
                    for (old_hosts, new_hosts), group in groups.items():
                        moved.update(group)
                        fut = pool.submit(
                            self._exec_double_read, index, c, group,
                            old_hosts, new_hosts, opt, map_fn,
                            reduce_fn, local_fn)
                        futures[fut] = (None, group)
                        if ctx is not None:
                            ctx.add_leg("double-read", len(group))
                    slices = [s for s in slices if s not in moved]
            for node, node_slices in self._slices_by_node(
                    nodes, index, slices, missing=missing):
                fut = pool.submit(self._mapper_node, node, index, c,
                                  node_slices, opt, map_fn, reduce_fn,
                                  local_fn)
                futures[fut] = (node, node_slices)
                if ctx is not None:
                    ctx.add_leg(node.host, len(node_slices))
            if missing is not None:
                if ctx is not None and len(missing) > before:
                    # Tail sampling: a degraded (partial) answer is
                    # keep-worthy evidence (obs.sampler "partial").
                    ctx.note_flag("partial")
                # Unservable slices still count toward completion —
                # that is what "partial" means.
                processed += len(missing) - before

        # One span covers the whole fan-out INCLUDING the reduce/merge
        # of completed legs (per-leg detail comes from the leg/rpc
        # spans recorded inside _mapper_node).
        span = _ctx_span(ctx, "map_reduce", call=c.name,
                         slices=len(slices))
        span.__enter__()
        try:
            submit(nodes, slices)
            while processed < len(slices):
                if ctx is None:
                    done, _ = wait(list(futures),
                                   return_when=FIRST_COMPLETED)
                else:
                    # Deadline-driven cancellation: wake periodically
                    # so an expiry or DELETE-cancel interrupts the
                    # fan-out even while every leg is still running.
                    ctx.check()
                    done, _ = wait(list(futures),
                                   timeout=self._CTX_POLL_S,
                                   return_when=FIRST_COMPLETED)
                for fut in done:
                    node, node_slices = futures.pop(fut)
                    try:
                        r = fut.result()
                    except (QueryDeadlineError, QueryCancelledError):
                        # The QUERY died, not the node: no replica
                        # re-map — surface it (handler maps to 504/409).
                        raise
                    except Exception as e:  # noqa: BLE001 - retry replicas
                        if node is None:
                            # A double-read leg already exhausted both
                            # sides of the migration (old owners AND
                            # the fenced new owner) — there is no
                            # further replica to re-map onto. Partial
                            # mode keeps its contract: the slices are
                            # reported missing instead of failing the
                            # query.
                            if missing is not None:
                                missing.extend(node_slices)
                                processed += len(node_slices)
                                if ctx is not None:
                                    ctx.note_flag("partial")
                                continue
                            raise
                        # Filter the failed node; re-map its slices onto
                        # surviving replicas (executor.go:1137-1151).
                        # The client already fed the failure into the
                        # breaker/health state, so the re-map's
                        # _slices_by_node consults an open circuit
                        # instead of rediscovering the failure — and
                        # the NEXT query skips the peer up front.
                        nodes = [n for n in nodes if n is not node]
                        obs_metrics.FAILOVER_SLICES.labels(
                            node.host or "local").inc(len(node_slices))
                        if ctx is not None:
                            # Tail sampling: a failover leg is keep-
                            # worthy evidence (obs.sampler "breaker").
                            ctx.note_flag("failover")
                        with _ctx_span(ctx, "failover", peer=node.host,
                                       slices=len(node_slices),
                                       error=type(e).__name__):
                            pass
                        try:
                            submit(nodes, node_slices)
                        except SliceUnavailableError:
                            raise e
                        continue
                    with _ctx_span(ctx, "merge",
                                   host=(node.host if node is not None
                                         else "double-read")):
                        result = reduce_fn(result, r)
                    processed += len(node_slices)
        finally:
            span.__exit__(None, None, None)
            # On an error path, drain what we started: the pool is
            # shared with other queries, and the old per-query pool's
            # exit joined its legs — keep that (cancel what hasn't
            # started, wait out what has). A DEAD query's in-flight
            # legs get a bounded grace instead: they are cooperatively
            # cancelled (per-slice ctx checks, budget-clamped socket
            # timeouts) and waiting a stalled peer out here would hold
            # the executor slot past the deadline the caller paid for.
            pending = [f for f in futures if not f.cancel()]
            if pending:
                if ctx is not None and (ctx.cancelled()
                                        or ctx.expired()):
                    wait(pending, timeout=self._DEAD_DRAIN_S)
                else:
                    wait(pending)
        return result

    def _mapper_node(self, node: Node, index: str, c: Call,
                     slices: list[int], opt: ExecOptions, map_fn, reduce_fn,
                     local_fn=None):
        # Bind the query context to this worker thread so the device
        # dispatch layer (parallel.mesh) and nested pool legs reached
        # from here can check the budget without a ctx argument.
        with sched_context.use(opt.ctx):
            if opt.ctx is not None:
                opt.ctx.check()
            if node.host == self.host:
                with _ctx_span(opt.ctx, "leg", host=node.host or "local",
                               slices=len(slices)):
                    if local_fn is not None:
                        r = local_fn(slices)
                        if r is not NotImplemented:
                            return r
                    if (self.pod is not None and self.pod.is_coordinator
                            and not opt.pod_local):
                        return self._pod_host_mapper(index, c, slices,
                                                     opt, map_fn,
                                                     reduce_fn)
                    return self._mapper_local(slices, map_fn, reduce_fn)
            hedge_s = (self.fault.hedge_delay_s(node.host)
                       if self.fault is not None else None)
            if hedge_s:
                return self._exec_remote_hedged(node, index, c, slices,
                                                opt, map_fn, reduce_fn,
                                                hedge_s)
            results = self._exec_remote(node, index, Query([c]), slices,
                                        opt)
            return results[0] if results else None

    def _exec_remote_hedged(self, node: Node, index: str, c: Call,
                            slices: list[int], opt: ExecOptions,
                            map_fn, reduce_fn, hedge_s: float,
                            local_fn=None):
        """Tail-tolerant remote leg (fault subsystem, opt-in): fire the
        primary replica's RPC; if it hasn't answered within ``hedge_s``
        (max of the configured floor and the peer's p95-ish latency
        EWMA), fire the SAME slices at the surviving replica owners and
        take whichever side completes first — first-response-wins, the
        loser is cancelled if unstarted and abandoned otherwise (its
        socket timeout stays bounded by the query budget). Map-reduce
        legs are pure reads, so a duplicated leg is only spent work,
        never a double write. A hedge that loses the race is never
        re-raised; if BOTH sides fail the primary's error surfaces and
        the outer re-map takes over.

        Generation accounting: each side collects its piggybacked
        tokens privately and ONLY the winner's merge into the
        coordinator map — a loser that straggles in with older state
        (it started earlier, or served a stale replica) must never
        overwrite what the winner reported.

        ``local_fn(slices)`` overrides the local hedge leg's
        computation (the TopN pushdown's exact partial); the default
        runs the per-slice map/reduce."""
        pool = self._pool("hedge")
        query = Query([c])
        primary_gens: list = []

        def primary_leg():
            rs = self._exec_remote(node, index, query, slices, opt,
                                   gens_out=primary_gens)
            return rs[0] if rs else None

        primary = pool.submit(primary_leg)
        done, _ = wait([primary], timeout=hedge_s)
        if done:
            res = primary.result()
            self._apply_remote_gens(primary_gens)
            return res
        others = [n for n in self.cluster.nodes if n is not node]
        try:
            groups = self._slices_by_node(others, index, slices)
        except SliceUnavailableError:
            groups = []
        if not groups:
            res = primary.result()
            self._apply_remote_gens(primary_gens)
            return res
        obs_metrics.HEDGED_REQUESTS.labels("fired").inc()
        with _ctx_span(opt.ctx, "hedge", peer=node.host,
                       slices=len(slices)):
            pass
        hedge_gens: list = []

        def hedge_leg(n2: Node, sl: list[int]):
            if n2.host == self.host:
                with sched_context.use(opt.ctx):
                    if local_fn is not None:
                        return local_fn(sl)
                    return self._mapper_local(sl, map_fn, reduce_fn)
            rs = self._exec_remote(n2, index, query, sl, opt,
                                   gens_out=hedge_gens)
            return rs[0] if rs else None

        hedges = [pool.submit(hedge_leg, n2, sl) for n2, sl in groups]
        ctx = opt.ctx
        primary_err = hedge_err = None
        primary_res = hedge_res = None
        primary_done = hedge_done = False
        while True:
            if ctx is not None:
                ctx.check()
            # Consume completed sides BEFORE blocking: a hedge that
            # finished while we were submitting must win immediately,
            # not after the slow primary finally returns.
            if not primary_done and primary.done():
                primary_done = True
                try:
                    primary_res = primary.result()
                except (QueryDeadlineError, QueryCancelledError):
                    raise
                except Exception as e:  # noqa: BLE001 - hedges cover
                    primary_err = e
            if not hedge_done and all(f.done() for f in hedges):
                hedge_done = True
                try:
                    r = None
                    for f in hedges:
                        r = reduce_fn(r, f.result())
                    hedge_res = r
                except (QueryDeadlineError, QueryCancelledError):
                    raise
                except Exception as e:  # noqa: BLE001 - primary covers
                    hedge_err = e
            if primary_done and primary_err is None:
                obs_metrics.HEDGED_REQUESTS.labels("primary_won").inc()
                for f in hedges:
                    f.cancel()
                self._apply_remote_gens(primary_gens)
                return primary_res
            if hedge_done and hedge_err is None:
                obs_metrics.HEDGED_REQUESTS.labels("hedge_won").inc()
                primary.cancel()
                self._apply_remote_gens(hedge_gens)
                return hedge_res
            if primary_done and hedge_done:
                raise primary_err
            wait([f for f in [primary, *hedges] if not f.done()],
                 timeout=self._CTX_POLL_S if ctx is not None else None,
                 return_when=FIRST_COMPLETED)

    def _pod_host_mapper(self, index: str, c: Call, slices: list[int],
                         opt: ExecOptions, map_fn, reduce_fn):
        """Pod-internal host-path fan-out: this pod's "local" slices are
        spread over its processes, so partition by owner process — owned
        slices run the plain local path, the rest go to the owning pod
        process as podLocal HTTP legs (parallel.pod placement)."""
        by_pid: dict[int, list[int]] = {}
        for s in slices:
            by_pid.setdefault(self.pod.owner_pid(s), []).append(s)
        result = None
        pool = self._pool("pod")
        futs = []
        for pid, group in by_pid.items():
            if pid == self.pod.pid:
                futs.append(pool.submit(self._mapper_local, group,
                                        map_fn, reduce_fn))
            else:
                futs.append(pool.submit(self._exec_pod_remote, pid,
                                        index, c, group, opt.ctx))
        try:
            for fut in futs:
                result = reduce_fn(result, fut.result())
        finally:
            # Shared pool: a failed leg must not abandon its siblings
            # mid-flight (the caller may re-map these slices onto
            # replicas — an abandoned leg would execute them twice).
            pending = [f for f in futs if not f.cancel()]
            if pending:
                wait(pending)
        return result

    def _exec_pod_remote(self, pid: int, index: str, c: Call,
                         slices: list[int], ctx=None):
        if self.client is None:
            raise SliceUnavailableError(
                f"no client to reach pod process {pid}")
        kwargs = {}
        if ctx is not None and getattr(self.client, "deadline_aware",
                                       False):
            ctx.check()
            kwargs = {"deadline_s": ctx.remaining(), "query_id": ctx.id}
        results = self.client.execute_query(
            Node(self.pod.peers[pid]), index, str(Query([c])), slices,
            remote=True, pod_local=True, **kwargs)
        return results[0] if results else None

    def _mapper_local(self, slices: list[int], map_fn, reduce_fn):
        # Goroutine-per-slice equivalent (executor.go:1201-1236); the numpy
        # and device work inside map_fn releases the GIL. Wide fan-outs
        # chunk several slices per pool task and pre-reduce inside the
        # task: at 256 slices the per-task submit/schedule overhead was
        # a third of the whole query, and reduce order is already
        # arbitrary (the cluster layer reduces in completion order).
        if len(slices) == 1:
            return reduce_fn(None, map_fn(slices[0]))
        pool = self._pool("slice")
        # Propagate the calling thread's query context into the nested
        # slice-pool legs: the container algebra (map AND the in-group
        # pre-reduce) actually runs THERE, and without the binding its
        # per-query attribution (cost ledger, spans, profiler query
        # tags) silently lands nowhere.
        ctx = sched_context.current()
        chunk = max(1, len(slices) // (4 * self.max_workers))

        def run_group(group: list[int]):
            # One binding covers the whole group — map legs and the
            # pre-reduce merges between them.
            with sched_context.use(ctx):
                r = None
                for s in group:
                    r = reduce_fn(r, map_fn(s))
                return r

        if chunk == 1:
            # Narrow fan-out: submit per slice — a single-slice group
            # would pay one extra reduce_fn pass per slice for nothing.
            if ctx is None:
                futs = [pool.submit(map_fn, s) for s in slices]
            else:
                def one(s, _ctx=ctx):
                    with sched_context.use(_ctx):
                        return map_fn(s)
                futs = [pool.submit(one, s) for s in slices]
        else:
            futs = [pool.submit(run_group, slices[i:i + chunk])
                    for i in range(0, len(slices), chunk)]
        result = None
        try:
            for fut in futs:
                result = reduce_fn(result, fut.result())
        finally:
            # Shared pool: if map_fn or reduce_fn raised, don't abandon
            # in-flight legs — the caller re-maps these slices onto a
            # replica, and an abandoned leg would run them twice while
            # occupying pool slots (same drain as _mapper/_mapper_pod).
            pending = [f for f in futs if not f.cancel()]
            if pending:
                wait(pending)
        return result
