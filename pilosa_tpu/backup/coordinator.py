"""Crash-safe backup coordinator (journaled like cluster/resize.py).

One node drives a cluster-consistent backup end-to-end: per fragment,
a WAL-barriered footered snapshot is pulled over the resize transport
(``GET /fragment/data?snapshot=1`` — the owner folds its WAL into the
body first, so the pushed bytes verify against the PR-15 footer),
verified, and decomposed into the archive's shared object pool.
Writes keep flowing during the backup; anything committed after a
fragment's snapshot travels via the continuous WAL archive
(backup.walarchive), which restore replays — so the restored state is
consistent AS OF the restore cut, not as of each fragment's
snapshot instant.

Consistency argument: an op record sets one position's membership
definitively (add→present, remove→absent) and the WAL archive
preserves per-fragment commit order, so replaying the archived op
history onto ANY prefix-folded snapshot of the same fragment converges
to the same state. The manifest records ``walStart`` (the per-node
next-segment watermark taken BEFORE the first snapshot): every op NOT
folded into some pushed snapshot lives in a segment ≥ walStart, and
re-applying ops that WERE folded is idempotent.

The journal (``backup.json`` under the data dir, tmp+fsync+rename,
coalesced to one write per _JOURNAL_COALESCE_S) makes a SIGKILLed
coordinator resumable: recovery
re-runs the same backup id, already-journaled fragments are reused,
and the pool's exists-check skips every object a previous attempt got
durable — the whole push is idempotent. The manifest write is the
single commit point; an id with no manifest is invisible to restore
and reclaimed by GC.
"""

from __future__ import annotations

import io
import json
import os
import tarfile
import threading
import time
import uuid
from typing import Optional

from ..errors import PilosaError
from ..obs import metrics as obs_metrics
from ..storage import integrity as integrity_mod
from ..utils import logger as logger_mod
from . import archive as archive_mod

JOURNAL_FILE = "backup.json"

# Inter-fragment pacing (seconds) — the storage.scrub discipline:
# background work yields between fragments so it never monopolizes
# the serving path. Much longer than scrub's 10 ms because a backup
# STREAMS + re-verifies whole fragments (up to 128 MB each) where
# scrub only read-verifies; at 100 ms/fragment a 256-slice index
# pays ~26 s of pacing per pass — noise for a once-per-operator-
# request op, and what keeps the backup-while-serving p50 inside the
# ≤5% bound (benchmarks/suite.py config_backup).
DEFAULT_PACE_S = 0.1

# Journal-write coalescing window: per-fragment journal fsyncs were
# a per-pass disk tax on the serving path's disk; one fsync per
# window bounds what a SIGKILL re-pushes (exists-check skips) without
# it.
_JOURNAL_COALESCE_S = 0.5

PHASE_IDLE = "idle"
PHASE_SNAPSHOT = "snapshot"
PHASE_MANIFEST = "manifest"
PHASE_DONE = "done"
PHASE_FAILED = "failed"
PHASES = (PHASE_IDLE, PHASE_SNAPSHOT, PHASE_MANIFEST, PHASE_DONE,
          PHASE_FAILED)


def set_state_gauge(phase: str) -> None:
    """One-hot the backup-state gauge across the known phase labels."""
    for p in PHASES:
        obs_metrics.BACKUP_STATE.labels(p).set(
            1.0 if p == phase else 0.0)


class BackupError(PilosaError):
    pass


class BackupJournal:
    """Crash-safe record of the coordinator's progress: one JSON file
    under the data dir, rewritten atomically (tmp + fsync + rename)
    per phase and per coalescing window of fragments. ``Server.open``
    replays it — an in-flight backup resumes under the same id."""

    VERSION = 1

    def __init__(self, path: str):
        self.path = path
        self.state: dict = {}
        self._mu = threading.Lock()

    @classmethod
    def for_data_dir(cls, data_dir: str) -> "BackupJournal":
        return cls(os.path.join(data_dir, JOURNAL_FILE))

    def load(self) -> Optional[dict]:
        try:
            with open(self.path) as f:
                loaded = json.load(f)
        except (OSError, ValueError):
            return None
        if loaded.get("version") != self.VERSION:
            return None
        with self._mu:
            self.state = loaded
        return self.state

    def write(self, **updates) -> None:
        with self._mu:
            self.state.update(updates)
            self.state["version"] = self.VERSION
            self.state["updatedAt"] = time.time()
            snapshot = dict(self.state)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snapshot, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)

    def in_flight(self) -> bool:
        return self.state.get("phase") not in (None, PHASE_DONE,
                                               PHASE_FAILED)

    def clear(self) -> None:
        with self._mu:
            self.state = {}
            try:
                os.remove(self.path)
            except OSError:
                pass


class BackupCoordinator:
    """Drives one backup end-to-end against a live cluster. One at a
    time per node (Server.start_backup enforces it)."""

    def __init__(self, server, store, kind: str = "full",
                 backup_id: Optional[str] = None,
                 journal: Optional[BackupJournal] = None,
                 logger=None, pace_s: float = DEFAULT_PACE_S):
        self.server = server
        self.store = store
        self.kind = kind if kind in ("full", "incremental") else "full"
        # Inter-fragment pacing, the storage-scrub discipline: the
        # snapshot/digest/push work yields between fragments so a
        # backup in flight stays out of serving's way (the ≤5%
        # backup-while-serving bound in benchmarks/suite.py
        # config_backup is measured with this pacing).
        self.pace_s = max(0.0, float(pace_s))
        self.id = backup_id or uuid.uuid4().hex[:12]
        self.journal = journal or BackupJournal.for_data_dir(
            server.holder.path)
        self.logger = logger or getattr(server, "logger",
                                        logger_mod.NOP)
        self.phase = PHASE_IDLE
        self.error: Optional[str] = None
        self.fragments_done = 0
        self.fragments_skipped = 0
        self.objects_pushed = 0
        self.bytes_pushed = 0
        self.started_at = 0.0
        self.finished_at = 0.0
        # Watchdog progress signal (obs.watchdog "backup_stall"): any
        # forward step — a pushed fragment, a phase move — touches it.
        self.last_progress = time.monotonic()
        self._journal_at = 0.0  # last coalesced journal write
        self._mu = threading.Lock()
        self._cancel = threading.Event()

    # -- plumbing --------------------------------------------------------------

    def touch(self) -> None:
        self.last_progress = time.monotonic()

    def cancel(self) -> None:
        """Cooperative stop (server close / operator abort). The
        journal stays in flight — the next open RESUMES the backup
        rather than discarding its pushed objects."""
        self._cancel.set()

    def _check_cancel(self) -> None:
        if self._cancel.is_set():
            raise BackupError(f"backup {self.id}: cancelled")

    def _set_phase(self, phase: str, **journal_updates) -> None:
        if phase in (PHASE_DONE, PHASE_FAILED) and not self.finished_at:
            self.finished_at = time.time()
        with self._mu:
            self.phase = phase
        set_state_gauge(phase)
        self.touch()
        self.journal.write(phase=phase, **journal_updates)
        self.logger.printf("backup %s: phase %s", self.id, phase)

    def status(self) -> dict:
        with self._mu:
            phase = self.phase
        return {"id": self.id, "kind": self.kind, "phase": phase,
                "error": self.error,
                "fragments": self.fragments_done,
                "fragmentsSkipped": self.fragments_skipped,
                "objectsPushed": self.objects_pushed,
                "bytesPushed": self.bytes_pushed,
                "startedAt": self.started_at,
                "finishedAt": self.finished_at}

    # -- the run ---------------------------------------------------------------

    def run(self) -> None:
        self.started_at = time.time()
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001 - journaled verdict
            self.error = f"{type(e).__name__}: {e}"
            obs_metrics.BACKUP_ERRORS.labels("coordinator").inc()
            # Backup-window errors are tail-sampling evidence: any
            # query in flight while the backup died may be the cause
            # (or the victim) — keep its trace.
            self._force_keep_traces()
            # Cancellation keeps the journal in flight (resume on the
            # next open); a real failure lands a terminal verdict.
            if self._cancel.is_set():
                self.logger.printf("backup %s: cancelled (journal"
                                   " stays in flight)", self.id)
                set_state_gauge(PHASE_IDLE)
            else:
                self._set_phase(PHASE_FAILED, error=self.error)
            self.logger.printf("backup %s: failed: %s", self.id,
                               self.error)

    def _force_keep_traces(self) -> None:
        server = self.server
        registry = getattr(server, "query_registry", None)
        tracer = getattr(server, "tracer", None)
        sampler = getattr(server, "sampler", None)
        if registry is None or tracer is None:
            return
        for ctx in registry.active_contexts():
            trace = getattr(ctx, "trace", None)
            if trace is None or getattr(trace, "keep_reason", ""):
                continue
            try:
                if tracer.keep(trace, reason="backup") \
                        and sampler is not None:
                    sampler.persist(trace, "backup", ctx=ctx)
            except Exception:  # noqa: BLE001
                continue

    def _client(self):
        return self.server.client_for(self.server.host)

    def _run(self) -> None:
        client = self._client()
        # The WAL watermark FIRST — before any snapshot, so every op
        # not folded into a pushed body is in a segment ≥ walStart
        # (the gap-free direction; overlap is idempotent).
        archiver = getattr(self.server, "wal_archiver", None)
        if archiver is not None:
            try:
                archiver.flush()
            except OSError:
                pass  # buffered batches re-ship on the next tick
        wal_start: dict = {}
        for _key, node, seq in archive_mod.list_wal_segments(
                self.store):
            wal_start[node] = max(wal_start.get(node, -1), seq)
        wal_start = {n: s + 1 for n, s in wal_start.items()}
        parent = None
        if self.kind == "incremental":
            prior = archive_mod.list_backups(self.store)
            if not prior:
                raise BackupError(
                    "incremental backup needs a prior backup in the"
                    " archive (take a full first)")
            parent = prior[-1]["id"]
        self._set_phase(PHASE_SNAPSHOT, id=self.id, kind=self.kind,
                        coordinator=self.server.host,
                        startedAt=self.started_at,
                        walStart=wal_start, parent=parent)
        schema = client.schema()
        max_slices = client.max_slices()
        # Resume: fragments a previous (killed) attempt journaled are
        # reused verbatim — their objects are already durable.
        entries: dict = dict(self.journal.state.get("fragments") or {})
        fragments: list[dict] = []
        for idx in schema:
            iname = idx["name"]
            for frame in idx.get("frames", []):
                fname = frame["name"]
                for view in frame.get("views", []):
                    vname = view["name"]
                    for slice in range(
                            int(max_slices.get(iname, 0)) + 1):
                        entry = self._one_fragment(
                            client, entries, iname, fname, vname,
                            slice)
                        if entry is not None:
                            fragments.append(entry)
        # Flush the coalesced tail before the commit point so the
        # journal names every fragment the manifest will.
        self.journal.write(fragments=entries)
        self._set_phase(PHASE_MANIFEST)
        manifest = {
            "version": archive_mod.MANIFEST_VERSION,
            "id": self.id, "kind": self.kind, "parent": parent,
            "t": time.time(),
            "coordinator": self.server.host,
            "epoch": self.server.cluster.epoch,
            "hosts": [n.host for n in self.server.cluster.nodes],
            "schema": schema,
            "maxSlices": {k: int(v) for k, v in max_slices.items()},
            "walStart": wal_start,
            "fragments": fragments,
        }
        archive_mod.write_backup_manifest(self.store, manifest)
        self._set_phase(PHASE_DONE, finishedAt=time.time())
        self.logger.printf(
            "backup %s: done (%d fragments, %d objects, %d bytes)",
            self.id, self.fragments_done, self.objects_pushed,
            self.bytes_pushed)

    def _one_fragment(self, client, entries: dict, index: str,
                      frame: str, view: str, slice: int
                      ) -> Optional[dict]:
        key = f"{index}/{frame}/{view}/{slice}"
        done = entries.get(key)
        if done is not None:
            self.fragments_skipped += 1
            return done
        self._check_cancel()
        spool = client.backup_slice(index, frame, view, slice,
                                    snapshot=True)
        if spool is None:
            return None  # slice doesn't exist on any owner
        with spool:
            with tarfile.open(fileobj=spool, mode="r|") as tr:
                data = b""
                for info in tr:
                    if info.name == "data":
                        src = tr.extractfile(info)
                        data = src.read() if src is not None else b""
                        break
        if not data:
            return None
        prefix = archive_mod.fragment_prefix(index, frame, view,
                                             slice)
        try:
            frag_manifest, digest, pushed, nbytes = \
                archive_mod.push_fragment_bytes(self.store, prefix,
                                                data)
        except integrity_mod.CorruptionError as e:
            obs_metrics.BACKUP_FRAGMENTS.labels("corrupt").inc()
            raise BackupError(f"backup {self.id}: {key}: {e}")
        entry = {"index": index, "frame": frame, "view": view,
                 "slice": slice, "prefix": prefix,
                 "bodyDigest": digest, "manifest": frag_manifest}
        entries[key] = entry
        self.objects_pushed += pushed
        self.bytes_pushed += nbytes
        self.fragments_done += 1
        obs_metrics.BACKUP_FRAGMENTS.labels("backed_up").inc()
        self.touch()
        # Journal write, COALESCED (at most one fsync per
        # _JOURNAL_COALESCE_S): a SIGKILL resumes from the last
        # journaled fragment, and the few since then re-push as pool
        # exists-check skips — resume stays idempotent, the serving
        # path stops sharing its disk with a per-fragment fsync.
        now = time.monotonic()
        if now - self._journal_at >= _JOURNAL_COALESCE_S:
            self.journal.write(fragments=entries)
            self._journal_at = now
        if self.pace_s:
            # Cancel-aware: an abort doesn't wait out the pace.
            self._cancel.wait(self.pace_s)
        return entry


def recover(server, logger=None) -> Optional[dict]:
    """Resume an in-flight journaled backup after a coordinator crash
    (called from Server.open on a background thread). The same id
    re-runs; journaled fragments and pool-resident objects are
    skipped, so recovery converges instead of re-shipping."""
    logger = logger or getattr(server, "logger", logger_mod.NOP)
    journal = BackupJournal.for_data_dir(server.holder.path)
    state = journal.load()
    if not state or not journal.in_flight():
        return None
    store = getattr(server, "backup_store", None)
    if store is None:
        logger.printf("backup %s: journal in flight but no archive"
                      " configured; leaving journal for the operator",
                      state.get("id"))
        return None
    coord = BackupCoordinator(
        server, store, kind=state.get("kind", "full"),
        backup_id=str(state.get("id")), journal=journal,
        logger=logger)
    server.backup_op = coord
    coord.run()
    return coord.status()
