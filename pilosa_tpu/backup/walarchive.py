"""Continuous WAL-segment archiving (the point-in-time-recovery feed).

A sink registered on the group-commit WAL (storage.wal) observes every
committed op batch — still on the leader thread, so per-WAL order IS
commit order — maps the WAL file back to its fragment, stamps it with
the commit wall-clock, and buffers it. A background loop flushes the
buffer into crc-named archive segments every ``interval_s`` (also
inline past a byte cap, so a bulk import cannot grow the buffer
unboundedly). The stamp sits between a write's issue and its ack,
which is what makes ``--to-timestamp`` exact: a write issued after the
cut has a stamp after the cut and is excluded; a write acked before
the cut has a stamp before it and is replayed.

Loss window: batches buffered but not yet flushed die with the
process — PITR granularity is bounded by ``interval_s`` (close()
flushes, so an orderly shutdown loses nothing).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..obs import metrics as obs_metrics
from ..storage import roaring
from ..storage import wal as wal_mod
from ..utils import logger as logger_mod
from . import archive as archive_mod

DEFAULT_INTERVAL_S = 2.0
# Inline-flush cap: the sink flushes synchronously past this many
# buffered bytes so a bulk import can't balloon the buffer between
# interval ticks.
MAX_BUFFER_BYTES = 4 << 20


class WalArchiver:
    """One node's WAL→archive shipper (module docstring)."""

    def __init__(self, store, data_dir: str, node: str,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 logger=None):
        self.store = store
        self.root = os.path.abspath(data_dir)
        self.node = node
        self.interval_s = max(0.05, float(interval_s))
        self.logger = logger or logger_mod.NOP
        self._buf: list[dict] = []
        self._buf_bytes = 0
        self._seq: Optional[int] = None  # lazy: node may be renamed
        self.segments_written = 0
        self.records_archived = 0
        self.errors = 0
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        wal_mod.register_archive_sink(self.root, self._on_batch)
        self._thread = threading.Thread(target=self._run,
                                        name="pilosa-wal-archive",
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        wal_mod.deregister_archive_sink(self.root)
        thread = self._thread
        if thread is not None \
                and thread is not threading.current_thread():
            thread.join(timeout=5.0)
        self._thread = None
        try:
            self.flush()
        except OSError:
            pass  # batches stay buffered; counted in self.errors

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.flush()
            except Exception:  # noqa: BLE001 - archiving must not kill serving
                pass

    # -- the WAL sink ----------------------------------------------------------

    def _frag_key(self, path: str) -> Optional[str]:
        """Data-file path → ``index/frame/view/slice`` (the models
        layout ``<data>/<index>/<frame>/views/<view>/fragments/<n>``);
        None for files that aren't fragment WALs."""
        rel = os.path.relpath(os.path.abspath(path), self.root)
        parts = rel.split(os.sep)
        if (len(parts) == 6 and parts[2] == "views"
                and parts[4] == "fragments" and parts[5].isdigit()):
            return f"{parts[0]}/{parts[1]}/{parts[3]}/{parts[5]}"
        return None

    def _on_batch(self, path: str, batch: bytes) -> None:
        frag = self._frag_key(path)
        if frag is None or not batch:
            return
        with self._mu:
            self._buf.append({"frag": frag, "t": time.time(),
                              "ops": bytes(batch)})
            self._buf_bytes += len(batch)
            over = self._buf_bytes >= MAX_BUFFER_BYTES
        obs_metrics.BACKUP_WAL_RECORDS.inc(
            len(batch) // roaring.OP_SIZE)
        self.records_archived += len(batch) // roaring.OP_SIZE
        if over:
            # Synchronous backpressure on the commit path — rare (a
            # bulk import between ticks), bounded (one segment write).
            try:
                self.flush()
            except OSError:
                pass

    # -- segments --------------------------------------------------------------

    def flush(self) -> int:
        """Drain the buffer into one archive segment; returns batches
        shipped (0 = nothing buffered). On a store failure the batches
        go back at the FRONT of the buffer — commit order is the PITR
        replay contract and must survive retries."""
        with self._mu:
            batches, self._buf = self._buf, []
            self._buf_bytes = 0
        if not batches:
            return 0
        try:
            if self._seq is None:
                self._seq = archive_mod.next_wal_seq(self.store,
                                                     self.node)
            seq = self._seq
            body = archive_mod.encode_wal_segment(self.node, seq,
                                                  batches)
            archive_mod.put_object(
                self.store,
                archive_mod.wal_segment_key(self.node, seq, body),
                body)
            self._seq = seq + 1
        except OSError as e:
            with self._mu:
                self._buf[:0] = batches
                self._buf_bytes += sum(len(b["ops"]) for b in batches)
            self.errors += 1
            self.logger.printf("wal archive: segment write failed:"
                               " %s", e)
            raise
        obs_metrics.BACKUP_WAL_SEGMENTS.inc()
        self.segments_written += 1
        return len(batches)

    def state(self) -> dict:
        with self._mu:
            buffered = len(self._buf)
            buffered_bytes = self._buf_bytes
        return {"node": self.node, "intervalS": self.interval_s,
                "nextSeq": self._seq, "buffered": buffered,
                "bufferedBytes": buffered_bytes,
                "segmentsWritten": self.segments_written,
                "recordsArchived": self.records_archived,
                "errors": self.errors}
