"""Archive retention + GC: keep the last N fulls and everything their
restore chains depend on.

Safety invariants (checked twice — at plan time and again inside
``run_gc`` before any delete):

- The newest full backup's restore chain (its manifest, every pool
  object it references, and every WAL segment at-or-past its
  ``walStart`` watermark) is NEVER collectable — an archive must
  always hold at least one restorable backup.
- A dropped backup's objects are deleted only if NO kept backup
  references them (the pool is shared; incrementals alias their
  parents' blocks).
- Incrementals depend on their parent chain: keeping a backup keeps
  every ancestor, even ancestors older than the retention window.
- WAL segments are kept from the MINIMUM ``walStart`` across kept
  backups — point-in-time restore from any kept backup stays possible.
- The orphan sweep (pool objects no committed manifest references —
  debris from crashed, never-committed backups) runs only when asked:
  an IN-FLIGHT backup's objects are unreferenced until its manifest
  commits, so sweeping while a backup runs would eat it. Callers gate
  this on "no backup in flight".
"""

from __future__ import annotations

from . import archive as archive_mod


class GCError(Exception):
    pass


def _chain_closure(by_id: dict, roots: list[dict]) -> dict:
    """roots + every ancestor via ``parent`` lineage, keyed by id."""
    kept: dict = {}
    stack = list(roots)
    while stack:
        m = stack.pop()
        if m["id"] in kept:
            continue
        kept[m["id"]] = m
        parent = m.get("parent")
        if parent and parent in by_id:
            stack.append(by_id[parent])
    return kept


def plan_gc(store, keep_fulls: int = 2) -> dict:
    """The retention plan — pure read, never deletes. Keeps the last
    ``keep_fulls`` full backups (floor 1), every incremental taken
    since the oldest kept full, and every ancestor any kept backup
    depends on; everything else is droppable."""
    keep_fulls = max(1, int(keep_fulls))
    backups = archive_mod.list_backups(store)  # oldest first
    by_id = {m["id"]: m for m in backups}
    fulls = [m for m in backups if m.get("kind") == "full"]
    kept_fulls = fulls[-keep_fulls:]
    if fulls and not kept_fulls:
        raise GCError("retention would drop every full backup")
    roots = list(kept_fulls)
    if kept_fulls:
        horizon = (kept_fulls[0].get("t", 0.0),
                   kept_fulls[0].get("id", ""))
        roots += [m for m in backups if m.get("kind") != "full"
                  and (m.get("t", 0.0), m.get("id", "")) >= horizon]
    else:
        roots = list(backups)  # no fulls at all: keep everything
    kept = _chain_closure(by_id, roots)
    dropped = [m for m in backups if m["id"] not in kept]

    kept_objects: set = set()
    for m in kept.values():
        kept_objects |= archive_mod.manifest_object_keys(m)
    drop_objects: set = set()
    for m in dropped:
        drop_objects |= archive_mod.manifest_object_keys(m)
    drop_objects -= kept_objects

    # WAL horizon: the minimum walStart per node across kept backups —
    # every kept backup must stay point-in-time restorable.
    wal_floor: dict = {}
    for m in kept.values():
        for node, seq in (m.get("walStart") or {}).items():
            cur = wal_floor.get(node)
            wal_floor[node] = seq if cur is None else min(cur, seq)
    drop_wal = []
    if kept:  # no kept backups -> no floor -> keep all WAL
        for key, node, seq in archive_mod.list_wal_segments(store):
            if node in wal_floor and seq < wal_floor[node]:
                drop_wal.append(key)

    referenced = kept_objects | set()
    for m in backups:
        referenced |= archive_mod.manifest_object_keys(m)
    orphans = [key for key in store.list(archive_mod.DATA_PREFIX + "/")
               if key not in referenced]

    return {"keepFulls": keep_fulls,
            "kept": [m["id"] for m in
                     sorted(kept.values(),
                            key=lambda m: (m.get("t", 0.0),
                                           m.get("id", "")))],
            "newestFull": kept_fulls[-1]["id"] if kept_fulls else None,
            "dropBackups": [m["id"] for m in dropped],
            "dropObjects": sorted(drop_objects),
            "dropWalSegments": sorted(drop_wal),
            "orphanObjects": sorted(orphans)}


def run_gc(store, keep_fulls: int = 2, dry_run: bool = False,
           sweep_orphans: bool = False, logger=None) -> dict:
    """Execute (or with ``dry_run`` just report) the retention plan.
    Re-asserts before deleting that the newest full's restore chain is
    untouched — a GCError here means a planner bug, and nothing has
    been deleted."""
    plan = plan_gc(store, keep_fulls)
    plan["dryRun"] = bool(dry_run)
    plan["sweepOrphans"] = bool(sweep_orphans)
    if not sweep_orphans:
        plan["orphanObjects"] = []

    if plan["newestFull"] is not None:
        newest = archive_mod.read_backup(store, plan["newestFull"])
        if newest is None:
            raise GCError(f"newest full {plan['newestFull']}"
                          f" unreadable; refusing to GC")
        chain = archive_mod.manifest_object_keys(newest)
        doomed = set(plan["dropObjects"]) | set(plan["orphanObjects"])
        clash = chain & doomed
        if clash or plan["newestFull"] in plan["dropBackups"]:
            raise GCError(
                f"plan would break the newest full's restore chain"
                f" ({len(clash)} objects); refusing to GC")
        floors = newest.get("walStart") or {}
        for key in plan["dropWalSegments"]:
            parsed = archive_mod.parse_wal_key(key)
            if parsed is not None \
                    and parsed[1] >= floors.get(parsed[0], 0):
                raise GCError(
                    f"plan would drop WAL segment {key} the newest"
                    f" full still replays; refusing to GC")

    deleted = 0
    if not dry_run:
        for bid in plan["dropBackups"]:
            store.delete(archive_mod.backup_manifest_key(bid))
            deleted += 1
        for key in (plan["dropObjects"] + plan["dropWalSegments"]
                    + plan["orphanObjects"]):
            store.delete(key)
            deleted += 1
    plan["deleted"] = deleted
    if logger is not None:
        logger.printf(
            "backup gc: kept %d, dropped %d backups, %d objects,"
            " %d wal segments, %d orphans%s", len(plan["kept"]),
            len(plan["dropBackups"]), len(plan["dropObjects"]),
            len(plan["dropWalSegments"]), len(plan["orphanObjects"]),
            " (dry run)" if dry_run else "")
    return plan
