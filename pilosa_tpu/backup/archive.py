"""Backup archive layout + failpoint-wrapped object I/O.

The archive is any :class:`tier.blob.BlobStore`; the layout::

    backups/<id>/manifest.json   whole-backup manifest — the COMMIT
                                 POINT: schema, topology epoch/hosts,
                                 per-fragment blob manifests + body
                                 digests, backup lineage (parent), and
                                 the WAL watermark restore replays from
    data/<index>/<frame>/<view>/<slice>/<obj>
                                 ONE content-addressed object pool
                                 shared by every backup — a push skips
                                 objects the pool already holds, so an
                                 incremental backup ships only changed
                                 blocks (the FragmentStreamer
                                 block-diff shape, keyed by the PR-15
                                 per-block crc table)
    wal/<node>/<seq>-<crc32>     archived WAL segments (JSON batches
                                 of committed op records), crc-named
                                 so ``check --deep`` re-verifies them
                                 without trusting their contents

Every object write goes through :func:`put_object` (the
``backup.push`` failpoint: fires AFTER the store write so error mode
models a crash with the object durable — resume must skip it; torn
mode replaces the object with a prefix; corrupt flips stored bits) and
every restore read through :func:`get_object` (``restore.fetch``:
corrupt flips the stored bytes BEFORE the read so digest-verified
admission must reject them).
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import re
import zlib
from typing import Optional

from ..fault import failpoints as _fp
from ..obs import metrics as obs_metrics
from ..storage import integrity as integrity_mod
from ..storage import roaring
from ..tier import blob as blob_mod

BACKUPS_PREFIX = "backups"
DATA_PREFIX = "data"
WAL_PREFIX = "wal"
MANIFEST_VERSION = 1

_WAL_KEY_RE = re.compile(
    r"^wal/(?P<node>[^/]+)/(?P<seq>\d{12})-(?P<crc>[0-9a-f]{8})$")


def open_archive(spec: str, data_dir: str
                 ) -> Optional[blob_mod.BlobStore]:
    """``[backup] archive`` spec → a store (same grammar as the tier's
    blob spec). ``""`` disables the archive; ``dir`` roots the
    local-dir backend at ``<data_dir>/_archive``; ``dir:<path>`` roots
    it explicitly (the only sane choice for real DR — the archive must
    survive the data dir's destruction)."""
    if not spec:
        return None
    if spec == "dir":
        return blob_mod.LocalDirBlobStore(
            os.path.join(data_dir, "_archive"))
    if spec.startswith("dir:"):
        return blob_mod.LocalDirBlobStore(spec[len("dir:"):])
    raise ValueError(f"unknown backup archive backend: {spec!r}")


# -- failpoint-wrapped object I/O ---------------------------------------------


class _PutWriter:
    """Torn-mode adapter: failpoints' torn branch writes a PREFIX of
    the data through this, replacing the just-stored object with a
    truncated one — exactly the state a crashed multipart upload
    leaves behind."""

    def __init__(self, store: blob_mod.BlobStore, key: str):
        self.store = store
        self.key = key

    def write(self, data) -> int:
        self.store.put(self.key, bytes(data))
        return len(data)


def _local_path(store: blob_mod.BlobStore, key: str) -> Optional[str]:
    if isinstance(store, blob_mod.LocalDirBlobStore):
        return store._path(key)
    return None


def put_object(store: blob_mod.BlobStore, key: str,
               data: bytes) -> None:
    """One archive object write. The ``backup.push`` hit sits AFTER
    the store write: error mode models a coordinator crash with the
    object already durable (idempotent resume must skip it), torn mode
    replaces the object with a prefix, corrupt mode flips real stored
    bits; partition mode scopes by object key."""
    try:
        store.put(key, data)
        if _fp.ACTIVE is not None:
            _fp.ACTIVE.hit("backup.push", host=key,
                           writer=_PutWriter(store, key), data=data,
                           path=_local_path(store, key))
    except OSError:
        obs_metrics.BACKUP_ERRORS.labels("backup.push").inc()
        raise
    obs_metrics.BACKUP_OBJECTS.labels("pushed").inc()
    obs_metrics.BACKUP_BYTES.labels("push").inc(len(data))


def get_object(store: blob_mod.BlobStore, key: str) -> bytes:
    """One archive object read. The ``restore.fetch`` hit sits BEFORE
    the store read so corrupt mode rots the stored bytes first — the
    caller's digest check is what keeps rotten bytes out of a restored
    cluster."""
    try:
        if _fp.ACTIVE is not None:
            _fp.ACTIVE.hit("restore.fetch", host=key,
                           path=_local_path(store, key))
        data = store.get(key)
    except OSError:
        obs_metrics.BACKUP_ERRORS.labels("restore.fetch").inc()
        raise
    obs_metrics.BACKUP_BYTES.labels("fetch").inc(len(data))
    return data


# -- fragment bodies -----------------------------------------------------------


def fragment_prefix(index: str, frame: str, view: str,
                    slice: int) -> str:
    return f"{DATA_PREFIX}/{index}/{frame}/{view}/{slice}"


def parse_verified(buf) -> tuple:
    """Parse + fully verify raw fragment-file bytes (snapshot body
    [+footer] [+op tail]); returns ``(FooterInfo, ops_start)`` where
    ``ops_start`` is the end of body+footer. Raises CorruptionError on
    any mismatch or when the file predates integrity footers — an
    unverifiable body must never enter the archive."""
    try:
        (hdr, _run_mask, _ns, offs, sizes, ops_offset,
         body_end) = roaring.parse_snapshot_layout(memoryview(buf))
    except ValueError as e:
        raise integrity_mod.CorruptionError(str(e))
    info = integrity_mod.parse_and_verify_footer(
        buf, len(hdr), ops_offset, offs, sizes, body_end,
        check_body=True)
    if info is None:
        raise integrity_mod.CorruptionError(
            "no integrity footer (vintage file cannot be archived)")
    return info, body_end + info.size


def body_digest(buf) -> str:
    """The per-fragment body digest the backup manifest records and
    restore admission re-checks (independent of the per-object crcs —
    it covers the REASSEMBLY, not just each part)."""
    return hashlib.blake2b(bytes(buf), digest_size=16).hexdigest()


def push_fragment_bytes(store: blob_mod.BlobStore, prefix: str,
                        filebuf: bytes) -> tuple:
    """Verify + decompose one fragment file into the shared object
    pool (block-diff: pool-resident objects are skipped). Any op tail
    is dropped from the pushed body — committed ops travel via the WAL
    archive, and restore replays them. Returns
    ``(frag_manifest, body_digest, objects_pushed, bytes_pushed)``."""
    info, ops_start = parse_verified(filebuf)
    buf = bytes(filebuf[:ops_start])
    manifest = blob_mod.build_manifest(buf, info)
    pushed, nbytes = blob_mod.push_objects(
        store, prefix, buf, manifest,
        put=lambda key, data: put_object(store, key, data))
    skipped = (2 + int(manifest["blockN"])) - pushed
    if skipped > 0:
        obs_metrics.BACKUP_OBJECTS.labels("skipped").inc(skipped)
    return manifest, body_digest(buf), pushed, nbytes


def fetch_fragment_bytes(store: blob_mod.BlobStore, prefix: str,
                         manifest: dict, digest: str = "") -> bytes:
    """Reassemble one fragment body from the pool with FULL admission
    verification (the PR-15 contract): per-object crcs, the recorded
    body digest, and the reassembled footer's own header/body/block
    checks. Raises CorruptionError — corrupt or torn archive bytes are
    never admitted, never served."""
    buf = blob_mod.fetch_objects(
        store, prefix, manifest,
        get=lambda key: get_object(store, key))
    if digest and body_digest(buf) != digest:
        raise integrity_mod.CorruptionError(
            f"archive fragment {prefix}: body digest mismatch")
    parse_verified(buf)
    return buf


# -- WAL segments --------------------------------------------------------------


def sanitize_node(host: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]", "_", host or "node")


def wal_segment_key(node: str, seq: int, body: bytes) -> str:
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return f"{WAL_PREFIX}/{sanitize_node(node)}/{seq:012d}-{crc:08x}"


def parse_wal_key(key: str) -> Optional[tuple]:
    """``wal/<node>/<seq>-<crc>`` → (node, seq, crc) or None."""
    m = _WAL_KEY_RE.match(key)
    if m is None:
        return None
    return m.group("node"), int(m.group("seq")), int(m.group("crc"),
                                                    16)


def encode_wal_segment(node: str, seq: int,
                       batches: list[dict]) -> bytes:
    """Segment body: committed op batches in commit order. ``ops``
    bytes ride base64 (the segment is JSON so ``check --deep`` and
    humans can read it; the crc in the KEY is the integrity check)."""
    return json.dumps(
        {"version": MANIFEST_VERSION, "node": node, "seq": seq,
         "batches": [{"frag": b["frag"], "t": b["t"],
                      "ops": base64.b64encode(b["ops"]).decode()}
                     for b in batches]}).encode()


def read_wal_segment(store: blob_mod.BlobStore, key: str) -> dict:
    """Fetch + verify one WAL segment (crc from the key name, then
    JSON shape); ``ops`` come back as bytes. CorruptionError on any
    mismatch."""
    parsed = parse_wal_key(key)
    if parsed is None:
        raise integrity_mod.CorruptionError(
            f"wal segment {key}: unparseable key")
    data = get_object(store, key)
    if (zlib.crc32(data) & 0xFFFFFFFF) != parsed[2]:
        raise integrity_mod.CorruptionError(
            f"wal segment {key}: crc mismatch")
    try:
        doc = json.loads(data)
        batches = [{"frag": str(b["frag"]), "t": float(b["t"]),
                    "ops": base64.b64decode(b["ops"])}
                   for b in doc.get("batches", [])]
    except (ValueError, KeyError, TypeError) as e:
        raise integrity_mod.CorruptionError(
            f"wal segment {key}: undecodable: {e}")
    return {"node": str(doc.get("node", parsed[0])),
            "seq": int(doc.get("seq", parsed[1])),
            "batches": batches}


def list_wal_segments(store: blob_mod.BlobStore) -> list[tuple]:
    """Every archived segment as (key, node, seq), seq-ordered per
    node (keys that don't parse are ignored — they are GC's orphan
    sweep's problem, not the replayer's)."""
    out = []
    for key in store.list(WAL_PREFIX + "/"):
        parsed = parse_wal_key(key)
        if parsed is not None:
            out.append((key, parsed[0], parsed[1]))
    out.sort(key=lambda t: (t[1], t[2]))
    return out


def next_wal_seq(store: blob_mod.BlobStore, node: str) -> int:
    """The next unused segment seq for ``node`` — resumes numbering
    across restarts from the store itself."""
    san = sanitize_node(node)
    seqs = [seq for _k, n, seq in list_wal_segments(store)
            if n == san]
    return (max(seqs) + 1) if seqs else 0


# -- backup manifests ----------------------------------------------------------


def backup_manifest_key(backup_id: str) -> str:
    return f"{BACKUPS_PREFIX}/{backup_id}/manifest.json"


def read_backup(store: blob_mod.BlobStore,
                backup_id: str) -> Optional[dict]:
    try:
        doc = json.loads(store.get(backup_manifest_key(backup_id)))
    except (OSError, ValueError):
        return None
    if doc.get("version") != MANIFEST_VERSION:
        return None
    return doc


def list_backups(store: blob_mod.BlobStore) -> list[dict]:
    """Every committed backup's manifest, oldest first. An id dir
    without a readable manifest is an uncommitted (crashed) backup —
    invisible here, reclaimed by GC's orphan sweep."""
    out = []
    for key in store.list(BACKUPS_PREFIX + "/"):
        parts = key.split("/")
        if len(parts) == 3 and parts[2] == "manifest.json":
            doc = read_backup(store, parts[1])
            if doc is not None:
                out.append(doc)
    out.sort(key=lambda d: (d.get("t", 0.0), d.get("id", "")))
    return out


def write_backup_manifest(store: blob_mod.BlobStore,
                          manifest: dict) -> None:
    put_object(store, backup_manifest_key(manifest["id"]),
               json.dumps(manifest).encode())


def manifest_object_keys(manifest: dict) -> set[str]:
    """Every pool object a backup's restore chain references."""
    keys: set[str] = set()
    for frag in manifest.get("fragments", []):
        prefix = frag["prefix"]
        fm = frag["manifest"]
        keys.add(f"{prefix}/{fm['head']}")
        keys.update(f"{prefix}/{name}" for name in fm["blocks"])
        keys.add(f"{prefix}/{fm['tail']}")
    return keys


# -- offline verification (the ``check --deep`` archive walk) ------------------


def verify_backup(store: blob_mod.BlobStore,
                  manifest: dict) -> list[tuple]:
    """Re-crc every object a backup references; per-fragment verdicts
    in the scrub_file shape (the same format as the data-dir walk).
    Returns ``[(name, verdict), ...]``."""
    out = []
    for frag in manifest.get("fragments", []):
        name = (f"{manifest['id']}: {frag['index']}/{frag['frame']}"
                f"/{frag['view']}/{frag['slice']}")
        try:
            buf = fetch_fragment_bytes(store, frag["prefix"],
                                       frag["manifest"],
                                       frag.get("bodyDigest", ""))
            verdict = {"corrupt": False, "coverage": "full",
                       "blocks": int(frag["manifest"]["blockN"]),
                       "bytes": len(buf)}
        except integrity_mod.CorruptionError as e:
            verdict = {"corrupt": True, "error": str(e),
                       "coverage": "full"}
        except OSError as e:
            verdict = {"corrupt": True,
                       "error": f"missing object: {e}",
                       "coverage": "none"}
        out.append((name, verdict))
    return out


def verify_wal(store: blob_mod.BlobStore) -> list[tuple]:
    """Re-crc every archived WAL segment; ``[(key, verdict), ...]``."""
    out = []
    for key, _node, _seq in list_wal_segments(store):
        try:
            seg = read_wal_segment(store, key)
            verdict = {"corrupt": False, "coverage": "full",
                       "batches": len(seg["batches"])}
        except integrity_mod.CorruptionError as e:
            verdict = {"corrupt": True, "error": str(e),
                       "coverage": "full"}
        except OSError as e:
            verdict = {"corrupt": True,
                       "error": f"missing object: {e}",
                       "coverage": "none"}
        out.append((key, verdict))
    return out
