"""Point-in-time restore: archive → a live cluster of ANY size.

The restorer never places data itself — it recreates the schema, then
asks the TARGET cluster who owns each slice (``/fragment/nodes``, the
same jump-hash placement the executor uses) and POSTs each
reassembled fragment to every owner. A 1-node backup restores onto a
3-node cluster (and vice versa) because placement is re-derived, not
recorded.

Admission is digest-verified (the PR-15 contract): every object is
crc-checked, the reassembled body re-checked against the manifest's
recorded digest AND its own integrity footer — torn or corrupt
archive objects raise before any byte reaches a fragment, so they are
never admitted, never served.

``--to-timestamp`` picks the newest backup taken at-or-before the
cut, then replays archived WAL batches with commit stamps ≤ the cut;
batches stamped after it are excluded (the stamp lands between a
write's issue and its ack — see backup.walarchive). Restore without a
cut replays the whole archive: the restored cluster serves the LATEST
archived state, including writes committed after the backup ran.

Per fragment, replay takes ONE source node's batch stream (replicas
archive duplicate streams; the one with the most op bytes is the most
complete) in segment order — per-WAL sink order is commit order, and
op records are idempotent per position, so replay over the folded
snapshot converges (see backup.coordinator's consistency argument).
"""

from __future__ import annotations

import io
import tarfile
from typing import Optional

from ..obs import metrics as obs_metrics
from ..storage import integrity as integrity_mod
from ..storage import roaring
from ..utils import logger as logger_mod
from . import archive as archive_mod


class RestoreError(Exception):
    pass


def pick_backup(store, backup_id: Optional[str] = None,
                to_timestamp: Optional[float] = None) -> dict:
    """The restore base: an explicit id, or the newest committed
    backup taken at-or-before the cut (a backup taken AFTER the cut
    already embeds post-cut state in its snapshots — it can never be
    the base for that cut)."""
    if backup_id:
        manifest = archive_mod.read_backup(store, backup_id)
        if manifest is None:
            raise RestoreError(f"no committed backup {backup_id!r}"
                               f" in the archive")
        if to_timestamp is not None \
                and manifest.get("t", 0.0) > to_timestamp:
            raise RestoreError(
                f"backup {backup_id} was taken after the requested"
                f" timestamp; pick an earlier backup")
        return manifest
    backups = archive_mod.list_backups(store)
    if to_timestamp is not None:
        backups = [b for b in backups
                   if b.get("t", 0.0) <= to_timestamp]
    if not backups:
        raise RestoreError("no usable backup in the archive"
                           + (" at-or-before the requested timestamp"
                              if to_timestamp is not None else ""))
    return backups[-1]


def gather_wal_ops(store, wal_start: dict,
                   cut: Optional[float] = None) -> dict:
    """Archived op batches to replay, keyed by fragment
    (``index/frame/view/slice``): per fragment, the single
    most-complete node's batches concatenated in segment order,
    excluding batches stamped after the cut. Segments below a node's
    ``walStart`` watermark predate the backup's snapshots and are
    skipped."""
    per_node: dict = {}  # node -> frag -> [ops...]
    for key, node, seq in archive_mod.list_wal_segments(store):
        if seq < int(wal_start.get(node, 0)):
            continue
        seg = archive_mod.read_wal_segment(store, key)
        frags = per_node.setdefault(node, {})
        for batch in seg["batches"]:
            if cut is not None and batch["t"] > cut:
                continue
            frags.setdefault(batch["frag"], []).append(batch["ops"])
    out: dict = {}
    for node, frags in per_node.items():
        for frag, chunks in frags.items():
            ops = b"".join(chunks)
            if len(ops) > len(out.get(frag, b"")):
                out[frag] = ops
    return out


def _empty_body() -> bytes:
    """A footered empty-bitmap snapshot — the base for fragments that
    exist ONLY in the WAL archive (created after the backup ran)."""
    buf = io.BytesIO()
    roaring.Bitmap().write_to(buf, footer=True)
    return buf.getvalue()


def _fragment_tar(file_bytes: bytes) -> io.BytesIO:
    """The ``write_to`` wire shape (data + empty cache) around raw
    fragment-file bytes, ready for POST /fragment/data."""
    out = io.BytesIO()
    with tarfile.open(fileobj=out, mode="w|") as tw:
        info = tarfile.TarInfo("data")
        info.size = len(file_bytes)
        info.mode = 0o600
        tw.addfile(info, io.BytesIO(file_bytes))
        cinfo = tarfile.TarInfo("cache")
        cinfo.size = 0
        cinfo.mode = 0o600
        tw.addfile(cinfo)
    out.seek(0)
    return out


def _push_fragment(client, index: str, frame: str, view: str,
                   slice: int, file_bytes: bytes) -> int:
    """POST one reassembled fragment to EVERY owner the TARGET
    cluster names for its slice (any-size placement). Returns the
    owner count."""
    nodes = client.fragment_nodes(index, slice)
    tar = _fragment_tar(file_bytes)
    body = tar.getvalue()
    for node in nodes:
        status, raw = client._do(
            "POST", f"/fragment/data?index={index}&frame={frame}"
                    f"&view={view}&slice={slice}", body,
            {"Content-Type": "application/octet-stream",
             "Content-Length": str(len(body))},
            host=node["host"])
        client._ok(status, raw,
                   f"restore {index}/{frame}/{view}/{slice}")
    return len(nodes)


def run_restore(host: str, store, backup_id: Optional[str] = None,
                to_timestamp: Optional[float] = None,
                client=None, logger=None) -> dict:
    """Restore a backup (+ WAL replay up to ``to_timestamp``) into
    the live cluster at ``host``. Returns a summary dict; raises
    RestoreError / CorruptionError — a restore that cannot verify
    every byte fails loudly rather than serving wrong answers."""
    logger = logger or logger_mod.NOP
    if client is None:
        from ..cluster.client import Client
        client = Client(host)
    manifest = pick_backup(store, backup_id=backup_id,
                           to_timestamp=to_timestamp)
    logger.printf("restore: base backup %s (kind %s, %d fragments)",
                  manifest["id"], manifest.get("kind"),
                  len(manifest.get("fragments", [])))
    for idx in manifest.get("schema", []):
        client.create_index(idx["name"])
        for frame in idx.get("frames", []):
            options = {}
            if frame.get("fields"):
                options["fields"] = frame["fields"]
            client.create_frame(idx["name"], frame["name"], options)
    wal_ops = gather_wal_ops(store,
                             manifest.get("walStart") or {},
                             cut=to_timestamp)
    restored = 0
    ops_bytes = 0
    corrupt: list[str] = []
    for frag in manifest.get("fragments", []):
        key = (f"{frag['index']}/{frag['frame']}/{frag['view']}"
               f"/{frag['slice']}")
        try:
            body = archive_mod.fetch_fragment_bytes(
                store, frag["prefix"], frag["manifest"],
                frag.get("bodyDigest", ""))
        except (integrity_mod.CorruptionError, OSError) as e:
            obs_metrics.BACKUP_FRAGMENTS.labels("corrupt").inc()
            corrupt.append(f"{key}: {e}")
            continue
        ops = wal_ops.pop(key, b"")
        _push_fragment(client, frag["index"], frag["frame"],
                       frag["view"], frag["slice"], body + ops)
        obs_metrics.BACKUP_FRAGMENTS.labels("restored").inc()
        restored += 1
        ops_bytes += len(ops)
    # Fragments born AFTER the backup exist only as WAL batches:
    # rebuild them from an empty footered base + their op history.
    wal_only = 0
    empty = None
    for key, ops in sorted(wal_ops.items()):
        parts = key.split("/")
        if len(parts) != 4 or not parts[3].isdigit() or not ops:
            continue
        if empty is None:
            empty = _empty_body()
        _push_fragment(client, parts[0], parts[1], parts[2],
                       int(parts[3]), empty + ops)
        obs_metrics.BACKUP_FRAGMENTS.labels("restored").inc()
        wal_only += 1
        ops_bytes += len(ops)
    if corrupt:
        raise RestoreError(
            f"restore {manifest['id']}: {len(corrupt)} fragments"
            f" failed verification and were NOT admitted: "
            + "; ".join(corrupt[:4]))
    summary = {"id": manifest["id"], "kind": manifest.get("kind"),
               "backupT": manifest.get("t"),
               "toTimestamp": to_timestamp,
               "fragments": restored, "walOnlyFragments": wal_only,
               "walOpsBytes": ops_bytes,
               "hosts": [n["host"] for n in
                         (client.nodes() if hasattr(client, "nodes")
                          else [])] or None}
    logger.printf("restore: done (%d fragments, %d wal-only,"
                  " %d op bytes replayed)", restored, wal_only,
                  ops_bytes)
    return summary
