"""Disaster recovery: consistent cluster backups, WAL archiving, and
verified point-in-time restore.

The subsystem behind ``pilosa-tpu backup|restore`` and the
``/backup`` + ``/debug/backup`` routes (docs/DISASTER_RECOVERY.md):

- :mod:`.archive` — the archive layout over a ``tier.blob`` store:
  one shared content-addressed object pool (block-diff economics for
  incrementals), per-backup manifests as the commit point, crc-named
  WAL segments, and the ``backup.push`` / ``restore.fetch``
  failpoint-wrapped object I/O every other module goes through.
- :mod:`.walarchive` — continuous WAL-segment archiving: a sink on
  the group-commit WAL ships every committed op batch into the
  archive, bounding point-in-time-recovery granularity by the flush
  interval.
- :mod:`.coordinator` — the journaled (crash-safe, resumable) backup
  coordinator taking cluster-consistent full/incremental backups.
- :mod:`.restore` — rebuilds a cluster of ANY size from a backup
  (placement re-derived via the target cluster's jump-hash), with
  digest-verified admission and ``--to-timestamp`` WAL replay.
- :mod:`.verify` — restore verification by replaying a captured
  workload (obs.capture) and comparing result digests.
- :mod:`.retention` — archive retention + GC: keep the last N fulls
  plus everything their restore chains depend on.
"""
