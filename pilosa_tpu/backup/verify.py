"""Restore verification: replay a captured workload, compare digests.

The acceptance test for a restore is not "the files are back" — it is
"the cluster gives the same answers". This module replays the read
records of a captured workload (obs.capture / obs.replay, the PR-19
shadow-diff machinery) against the restored cluster and compares each
response's result digest against the digest recorded at capture time
on the ORIGINAL cluster. Zero mismatches = the restore provably
serves the same answers the source did.

Only reads are replayed (writes would mutate the restored state), and
only records that captured a digest participate — a record without
one can't be checked, so it is counted but never scored.
"""

from __future__ import annotations

from typing import Optional

from ..obs import replay as replay_mod
from ..utils import logger as logger_mod

# Queries that read; everything else (SetBit/ClearBit/SetFieldValue…)
# would mutate the restored cluster mid-verification.
READ_CALLS = ("Bitmap", "Union", "Intersect", "Difference", "Count",
              "TopN", "Range", "Sum", "Min", "Max")


def is_read(rec: dict) -> bool:
    pql = (rec.get("pql") or "").lstrip()
    return pql.startswith(READ_CALLS)


def verify_restore(host: str, records: list[dict],
                   limit: Optional[int] = None,
                   logger=None) -> dict:
    """Replay each comparable read record against ``host``; returns
    ``{"compared", "matches", "mismatches", "skipped", "errors",
    "mismatchSamples"}``. ``mismatches == 0`` over a non-empty
    ``compared`` set is the restore-verified verdict."""
    logger = logger or logger_mod.NOP
    compared = matches = skipped = errors = 0
    samples: list[dict] = []
    for rec in records:
        if limit is not None and compared >= limit:
            break
        if not is_read(rec) or not rec.get("digest"):
            skipped += 1
            continue
        out = replay_mod._issue(host, rec)
        if out["status"] != rec.get("status", 200) \
                or not out["digest"]:
            errors += 1
            continue
        compared += 1
        if out["digest"] == rec["digest"]:
            matches += 1
        elif len(samples) < 8:
            samples.append({"pql": rec.get("pql"),
                            "index": rec.get("index"),
                            "want": rec["digest"],
                            "got": out["digest"]})
    mismatches = compared - matches
    logger.printf("restore verify: %d compared, %d mismatches,"
                  " %d skipped, %d errors", compared, mismatches,
                  skipped, errors)
    return {"compared": compared, "matches": matches,
            "mismatches": mismatches, "skipped": skipped,
            "errors": errors, "mismatchSamples": samples}
