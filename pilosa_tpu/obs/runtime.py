"""Runtime collector: periodic gauges of process internals.

Samples, on a background thread (and on demand at /metrics scrape and
/status), the sizes that explain serving behavior but have no natural
increment site:

- holder shape: open indexes/frames/fragments, row-cache entries;
- device residency: HBM bytes used/budgeted, hit/miss/eviction counts
  (parallel.residency.device_cache);
- XLA compile cache: program-cache hits/misses, programs built, and
  wall seconds spent in first-call trace+compile
  (parallel.mesh.compile_stats — the counters that answer VERDICT
  weak #2's "is the cache hitting, does anything warm it");
- roaring container op counts by container kind
  (storage.roaring.op_counts), plus the live container mix — counts
  and resident bytes by kind (array/bitmap/run) aggregated from each
  fragment's epoch-cached container_stats — published as
  ``pilosa_roaring_containers_live`` / ``pilosa_roaring_container_bytes``;
- thread activity: live threads, and on-CPU threads via the
  utils.profiling sampler's idle-leaf filter;
- admission controller depth/in-flight.

Everything lands twice: as gauges/counters in the metrics registry
(``pilosa_runtime_*``, ``pilosa_holder_*``, ``pilosa_residency_*``,
``pilosa_compile_cache_*``) and as the ``runtime`` JSON block in
``/status``.
"""

from __future__ import annotations

import platform
import sys
import threading
import time
from typing import Optional

from . import metrics as obs_metrics

DEFAULT_INTERVAL_S = 10.0

_build_info: Optional[dict] = None


def build_info() -> dict:
    """Build identity: package version, python, jax version, and the
    jax backend platform — the value block behind the
    ``pilosa_build_info`` gauge and the ``build`` block in /status.
    The jax fields read from the ALREADY-IMPORTED module only: a bare
    handler serving /status must not pay (or fail) a jax import, and
    ``default_backend()`` is only consulted once something else has
    initialized a backend."""
    global _build_info
    if _build_info is not None:
        return _build_info
    from .. import __version__
    jax_mod = sys.modules.get("jax")
    jax_version = getattr(jax_mod, "__version__", "") if jax_mod else ""
    backend = ""
    if jax_mod is not None:
        try:
            backend = jax_mod.default_backend()
        except Exception:  # noqa: BLE001 - backend init can fail off-TPU
            backend = "unavailable"
    info = {"version": __version__,
            "python": platform.python_version(),
            "jax": jax_version or "unloaded",
            "backend": backend or "unloaded"}
    # Publish (and cache) only once jax is actually loaded: an early
    # /status on a bare handler must neither freeze "unloaded" for the
    # process nor leave a second, stale build_info series behind.
    if jax_mod is not None:
        obs_metrics.BUILD_INFO.labels(**info).set(1)
        _build_info = info
    return info


class RuntimeCollector:
    def __init__(self, holder=None, executor=None, admission=None,
                 registry=None, interval_s: float = DEFAULT_INTERVAL_S,
                 slo=None, profiler=None, history=None,
                 tenant_slo=None):
        self.holder = holder
        self.executor = executor
        self.admission = admission
        # SLO burn-rate trackers (obs.slo.SLOTracker and the
        # per-tenant obs.slo.TenantSLOTracker) and the continuous
        # profiler (obs.profile) — sampled/summarized on the same
        # cadence so /status carries both.
        self.slo = slo
        self.tenant_slo = tenant_slo
        self.profiler = profiler
        # Metric history (obs.history): one registry-wide sampling
        # pass per collector tick — AFTER the gauges above refresh, so
        # each tick's rings see this tick's sizes. The store guards
        # against the on-demand /status path double-sampling a tick.
        self.history = history
        self.registry = registry or obs_metrics.default_registry()
        self.interval_s = interval_s
        self._mu = threading.Lock()
        self._last: dict = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="pilosa-runtime-collector",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Stop AND join: callers close the metric history right
        after, and a collector thread still mid-collect would write
        a fresh history segment past the close."""
        self._stop.set()
        thread = self._thread
        if thread is not None \
                and thread is not threading.current_thread():
            thread.join(timeout=5.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.collect()
            except Exception:  # noqa: BLE001 - sampling must not kill serving
                pass

    # -- sampling ------------------------------------------------------------

    def collect(self) -> dict:
        """One sampling pass: update registry gauges, return (and
        retain for /status) the snapshot dict."""
        snap: dict = {"sampledAt": time.time()}
        snap["build"] = build_info()
        snap["holder"] = self._holder_sizes()
        snap["threads"] = self._thread_sample()
        snap["deviceBlockCache"] = self._residency()
        snap["compileCache"] = self._compile_cache()
        snap["roaringOps"] = self._roaring_ops()
        if self.admission is not None:
            adm = self.admission.snapshot()
            snap["admission"] = adm
            obs_metrics.ADMISSION_IN_FLIGHT.set(adm.get("inFlight", 0))
            for lane, depth in (adm.get("queued") or {}).items():
                obs_metrics.ADMISSION_QUEUE_DEPTH.labels(lane).set(depth)
        if self.executor is not None:
            snap["deviceFallbacks"] = getattr(self.executor,
                                              "device_fallbacks", 0)
            snap["costModelVetoes"] = getattr(self.executor,
                                              "cost_vetoes", 0)
        if self.slo is not None:
            try:
                snap["slo"] = self.slo.record()
            except Exception:  # noqa: BLE001 - visibility only
                pass
        if self.tenant_slo is not None:
            try:
                snap["tenantSlo"] = self.tenant_slo.record()
            except Exception:  # noqa: BLE001 - visibility only
                pass
        if self.profiler is not None:
            snap["profiler"] = self.profiler.snapshot()
        if self.history is not None:
            try:
                self.history.sample()
                snap["history"] = self.history.stats()
            except Exception:  # noqa: BLE001 - history must not break /status
                pass
        with self._mu:
            self._last = snap
        return snap

    def snapshot(self) -> dict:
        """Most recent sample (collecting one if none exists yet)."""
        with self._mu:
            last = self._last
        if not last:
            try:
                return self.collect()
            except Exception:  # noqa: BLE001 - visibility, not serving
                return {}
        return last

    # -- individual samplers -------------------------------------------------

    def _holder_sizes(self) -> dict:
        out = {"indexes": 0, "frames": 0, "fragments": 0,
               "cacheEntries": 0}
        # Container mix by kind (array/bitmap/run): counts + resident
        # bytes, from each fragment's per-epoch-cached stats walk —
        # "the mix shifts to runs" as gauges, not prose.
        kind_counts = {"array": 0, "bitmap": 0, "run": 0}
        kind_bytes = {"array": 0, "bitmap": 0, "run": 0}
        holder = self.holder
        if holder is None:
            return out
        try:
            indexes = dict(holder.indexes)
        except Exception:  # noqa: BLE001 - holder may be mid-close
            return out
        out["indexes"] = len(indexes)
        for idx in indexes.values():
            frames = dict(idx.frames)
            out["frames"] += len(frames)
            for frame in frames.values():
                for view in dict(frame.views).values():
                    frags = dict(view.fragments)
                    out["fragments"] += len(frags)
                    for frag in frags.values():
                        cache = getattr(frag, "cache", None)
                        if cache is not None:
                            try:
                                out["cacheEntries"] += len(cache)
                            except TypeError:
                                pass
                        try:
                            cs = frag.container_stats()
                        except Exception:  # noqa: BLE001 - mid-close
                            continue
                        for kind in kind_counts:
                            kind_counts[kind] += cs["counts"][kind]
                            kind_bytes[kind] += cs["bytes"][kind]
        out["containers"] = {"counts": kind_counts, "bytes": kind_bytes}
        obs_metrics.HOLDER_FRAGMENTS.set(out["fragments"])
        obs_metrics.HOLDER_CACHE_ENTRIES.set(out["cacheEntries"])
        for kind in kind_counts:
            obs_metrics.ROARING_CONTAINERS.labels(kind).set(
                kind_counts[kind])
            obs_metrics.ROARING_CONTAINER_BYTES.labels(kind).set(
                kind_bytes[kind])
        return out

    def _thread_sample(self) -> dict:
        from ..utils import profiling
        live = threading.active_count()
        try:
            on_cpu = len(profiling.collect_sample(include_idle=False))
        except Exception:  # noqa: BLE001 - interpreter-internal API
            on_cpu = 0
        obs_metrics.RUNTIME_THREADS.labels("live").set(live)
        obs_metrics.RUNTIME_THREADS.labels("on_cpu").set(on_cpu)
        return {"live": live, "onCpu": on_cpu}

    def _residency(self) -> dict:
        try:
            from ..parallel import residency
            snap = residency.device_cache().snapshot()
        except Exception:  # noqa: BLE001 - jax backend may be absent
            return {}
        obs_metrics.RESIDENCY_BYTES.labels("used").set(
            snap.get("usedBytes", 0))
        obs_metrics.RESIDENCY_BYTES.labels("budget").set(
            snap.get("budgetBytes", 0))
        return snap

    def _compile_cache(self) -> dict:
        try:
            from ..parallel import mesh as mesh_mod
            stats = mesh_mod.compile_stats()
        except Exception:  # noqa: BLE001 - mesh import can fail sans jax
            return {}
        obs_metrics.COMPILE_HITS.set_total(stats.get("hits", 0))
        obs_metrics.COMPILE_MISSES.set_total(stats.get("misses", 0))
        obs_metrics.COMPILE_SECONDS.set_total(
            stats.get("compileSeconds", 0.0))
        obs_metrics.COMPILE_PROGRAMS.set(stats.get("programs", 0))
        fair = mesh_mod.fair_dispatch_state()
        if fair is not None:
            stats = dict(stats)
            stats["fairDispatch"] = fair
        return stats

    def _roaring_ops(self) -> dict:
        try:
            from ..storage import roaring
            counts = roaring.op_counts()
        except Exception:  # noqa: BLE001 - visibility only
            return {}
        out = {}
        for (op, kind), n in counts.items():
            if n:
                obs_metrics.ROARING_OPS.labels(op, kind).set_total(n)
                out[f"{op}:{kind}"] = n
        return out
