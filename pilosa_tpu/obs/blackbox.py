"""Blackbox flight recorder: periodic whole-system snapshots, dumped
in full when something dies.

An aircraft flight recorder does not wait to be asked: it records
continuously into a bounded loop and the loop is read AFTER the
incident. Same here — the recorder snapshots whole-system state
(admission queues, breaker states, generation maps, WAL dirty set +
flusher heartbeat, cache counters, a thread dump, recent slow-log
entries) on a fixed cadence into a bounded on-disk segment ring
(obs.diskring) under the holder data dir, and **dumps** the whole ring
plus one fresh snapshot to a standalone JSON file on:

- SIGTERM (the orderly-kill the operator sends before the SIGKILL
  they regret),
- an uncaught thread exception (``threading.excepthook`` chain),
- a watchdog trip (obs.watchdog calls ``dump("watchdog:<cause>")``),
- ``POST /debug/blackbox/dump``.

The state callable is injected by the server (it owns the wiring);
the recorder never raises into serving and its disk use is bounded by
the ring (snapshots) plus ``max_dumps`` dump files (oldest unlinked).
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Callable, Optional

from . import metrics as obs_metrics
from .diskring import SegmentRing

DEFAULT_INTERVAL_S = 10.0
DEFAULT_SEGMENT_BYTES = 256 << 10
DEFAULT_MAX_SEGMENTS = 4
DEFAULT_MAX_DUMPS = 4


class Blackbox:
    """One node's flight recorder (module docstring)."""

    def __init__(self, dir: str,
                 state_fn: Callable[[], dict],
                 interval_s: float = DEFAULT_INTERVAL_S,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 max_segments: int = DEFAULT_MAX_SEGMENTS,
                 max_dumps: int = DEFAULT_MAX_DUMPS,
                 node: str = "", logger=None):
        from ..utils import logger as logger_mod
        self.dir = dir
        self.state_fn = state_fn
        self.interval_s = max(0.05, float(interval_s))
        self.max_dumps = max(1, int(max_dumps))
        self.node = node
        self.logger = logger or logger_mod.NOP
        self.ring = SegmentRing(os.path.join(dir, "ring"),
                                segment_bytes=segment_bytes,
                                max_segments=max_segments)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dump_mu = threading.Lock()

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()  # restartable (A/B harnesses stop/start)
        _register(self)
        self._thread = threading.Thread(target=self._run,
                                        name="pilosa-blackbox",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            # Join before a possible start(): a thread mid-snapshot
            # would otherwise return to wait() AFTER start() cleared
            # the flag and loop on as a leaked second recorder.
            thread.join(timeout=5.0)
        self._thread = None
        _deregister(self)
        self.ring.close()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.snapshot("periodic")
            except Exception:  # noqa: BLE001 - recording must not kill serving
                pass

    # -- recording ------------------------------------------------------------

    def snapshot(self, trigger: str = "manual",
                 extra: Optional[dict] = None) -> dict:
        """One whole-system state sample into the ring. ``extra``
        rides the record verbatim — the regression sentinel names the
        regressed metric there, so the snapshot self-documents WHY it
        was taken (the trigger label stays low-cardinality)."""
        snap = {"ts": time.time(), "node": self.node,
                "trigger": trigger}
        try:
            snap.update(self.state_fn() or {})
        except Exception as e:  # noqa: BLE001 - partial state beats none
            snap["stateError"] = str(e)[:200]
        if extra:
            snap.update(extra)
        self.ring.append(snap)
        obs_metrics.BLACKBOX_SNAPSHOTS.labels(trigger).inc()
        return snap

    def dump(self, cause: str) -> Optional[str]:
        """The full ring + one fresh snapshot to
        ``<dir>/dump-<unix-ms>-<cause>.json``; returns the path (None
        if the write failed). Serialized — concurrent triggers produce
        one dump each, never interleaved bytes."""
        with self._dump_mu:
            try:
                fresh = self.snapshot(f"dump:{cause}")
            except Exception:  # noqa: BLE001
                fresh = {"ts": time.time(), "error": "snapshot failed"}
            doc = {
                "cause": cause,
                "dumpedAt": time.time(),
                "node": self.node,
                "current": fresh,
                # Oldest-first so the dump reads as a timeline.
                "ring": list(self.ring.scan(newest_first=False)),
            }
            safe = "".join(c if c.isalnum() or c in "-_." else "_"
                           for c in cause)[:48]
            path = os.path.join(
                self.dir, f"dump-{int(time.time() * 1e3)}-{safe}.json")
            try:
                os.makedirs(self.dir, exist_ok=True)
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(doc, f, indent=1, default=str)
                os.replace(tmp, path)
            except OSError:
                return None
            obs_metrics.BLACKBOX_DUMPS.labels(
                cause.split(":", 1)[0]).inc()
            self.logger.printf("blackbox dump (%s): %s", cause, path)
            self._prune_dumps()
            return path

    def dumps(self) -> list[str]:
        """Existing dump files, oldest first."""
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith("dump-")
                           and n.endswith(".json"))
        except OSError:
            return []
        return [os.path.join(self.dir, n) for n in names]

    def _prune_dumps(self) -> None:
        for path in self.dumps()[:-self.max_dumps]:
            try:
                os.unlink(path)
            except OSError:
                pass

    def stats(self) -> dict:
        return {"dir": self.dir, "intervalS": self.interval_s,
                "ring": self.ring.stats(),
                "dumps": [os.path.basename(p) for p in self.dumps()]}


# -- process-level triggers ----------------------------------------------------
# Every live recorder registers here; the (once-installed) SIGTERM and
# threading.excepthook chains dump them all. In-process multi-server
# tests each get their own dump under their own data dir.

_active_mu = threading.Lock()
_active: list[Blackbox] = []
_thread_hook_installed = False
_sigterm_installed = False
_prev_sigterm = None
_prev_thread_hook = None


def _register(bb: Blackbox) -> None:
    with _active_mu:
        if bb not in _active:
            _active.append(bb)


def _deregister(bb: Blackbox) -> None:
    with _active_mu:
        try:
            _active.remove(bb)
        except ValueError:
            pass


def dump_all(cause: str) -> list[str]:
    with _active_mu:
        boxes = list(_active)
    out = []
    for bb in boxes:
        try:
            path = bb.dump(cause)
            if path:
                out.append(path)
        except Exception:  # noqa: BLE001 - a dying process dumps best-effort
            pass
    return out


def install_process_hooks() -> bool:
    """Install the SIGTERM + uncaught-thread-exception dump triggers,
    once per process (each hook latches independently: a first call
    from a non-main thread installs only the excepthook chain, and a
    later main-thread call still gets to install the signal hook).
    Returns True once the SIGTERM hook is in place."""
    global _thread_hook_installed, _sigterm_installed
    global _prev_sigterm, _prev_thread_hook
    with _active_mu:
        if not _thread_hook_installed:
            _thread_hook_installed = True

            def _thread_hook(args):
                try:
                    dump_all("fatal:"
                             + getattr(args.exc_type, "__name__", "?"))
                except Exception:  # noqa: BLE001
                    pass
                if _prev_thread_hook is not None:
                    _prev_thread_hook(args)

            _prev_thread_hook = threading.excepthook
            threading.excepthook = _thread_hook
        if _sigterm_installed:
            return True

    def _sigterm(signum, frame):
        dump_all("sigterm")
        # Restore whatever was there and re-deliver, so process exit
        # semantics are exactly the pre-hook ones.
        prev = _prev_sigterm
        if callable(prev):
            prev(signum, frame)
            return
        signal.signal(signal.SIGTERM,
                      prev if prev is not None else signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)

    try:
        prev = signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:  # not the main thread; a later call may succeed
        return False
    with _active_mu:
        _prev_sigterm = prev
        _sigterm_installed = True
    return True
