"""Regression sentinel: the trajectory watcher that tells you the
perf cliff happened while it is still happening.

Perf regressions were only caught when someone manually re-ran
``bench.py`` against MANIFEST.json. The sentinel closes that loop on a
slow cadence against the live metric history (obs.history):

- **Robust-z rules**: for every watched series (by default the query
  latency ``:p50``/``:p99`` and ``:rate`` derivations per lane/call),
  compare the recent window's median against the trailing baseline
  window's median/MAD. ``z = (recent - median) / (1.4826 * MAD)``
  past the threshold AND a minimum effect ratio → a finding. MAD, not
  stddev — one old outlier must not widen the band until a real cliff
  hides inside it.
- **Manifest envelope rules**: the committed benchmark artifacts
  (benchmarks/MANIFEST.json) define what this build measured on this
  class of hardware; live medians sustained past ``manifest_tolerance``
  × the committed number breach the envelope, whatever the local
  baseline drifted to (a slow regression that re-baselines itself
  every hour still trips this one).

A firing rule:

- increments ``pilosa_sentinel_findings_total{metric,direction}`` and
  raises ``pilosa_sentinel_findings_active{metric,direction}`` until
  the condition clears;
- force-keeps every in-flight trace with the new keep reason
  ``anomaly`` (the queries running THROUGH the cliff are the
  evidence);
- lands a blackbox snapshot whose record names the regressed metric —
  so a silent perf cliff self-documents: history shows the bend, the
  kept traces show the queries inside it, the blackbox shows the
  system state around it.

Per-metric re-fires are rate-limited (``retrip_s``); recovery clears
the active gauge on the next pass.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from . import metrics as obs_metrics
from .history import split_key

DEFAULT_INTERVAL_S = 30.0
DEFAULT_WINDOW_S = 120.0
DEFAULT_BASELINE_S = 3600.0
DEFAULT_ZSCORE = 6.0
DEFAULT_MIN_POINTS = 5
DEFAULT_MIN_RATIO = 1.5
DEFAULT_RETRIP_S = 300.0
DEFAULT_MANIFEST_TOLERANCE = 5.0

# Which history series the robust-z rules watch, and in which
# direction a finding fires: latency quantiles regress UP, rates
# cliff DOWN (a traffic collapse is as much an incident as a latency
# spike). The rule catalogue is documented in docs/OBSERVABILITY.md.
DEFAULT_WATCHES = (
    ("pilosa_query_duration_seconds:p99", "up"),
    ("pilosa_query_duration_seconds:p50", "up"),
    ("pilosa_query_duration_seconds:rate", "down"),
    ("pilosa_cluster_rpc_seconds:p99", "up"),
    ("pilosa_wal_group_commit_flush_seconds:p99", "up"),
    ("pilosa_import_stage_seconds:p99", "up"),
    # Per-tenant latency regression: one tenant's p99 bending while
    # the aggregate stays flat is exactly the noisy-neighbor signature
    # the multi-tenant isolation work exists to catch.
    ("pilosa_tenant_query_duration_seconds:p99", "up"),
)

# Per-tenant SLO-burn rule (absolute, not robust-z): a tenant whose
# recent-median burn rate sits past this is eating its error budget
# 10x faster than sustainable — the classic fast-burn page threshold.
# Series: pilosa_tenant_slo_burn_rate_ratio{tenant,window}.
DEFAULT_TENANT_BURN_FAMILY = "pilosa_tenant_slo_burn_rate_ratio"
DEFAULT_TENANT_BURN_THRESHOLD = 10.0

# Planner misestimation rule (absolute): the planner's per-node
# (actual+1)/(est+1) ratio distribution. A p99 sustained past this
# means the cardinality estimator is off by ~an order of magnitude on
# the tail — plans reorder/place on numbers that are wrong, so the
# finding points at the estimator (stale rank caches, skew past the
# sampler) before users notice the slow plans it picks.
DEFAULT_PLANNER_MISEST_FAMILY = \
    "pilosa_planner_misestimation_ratio:p99"
DEFAULT_PLANNER_MISEST_THRESHOLD = 8.0

# Manifest envelope rules: (manifest metrics key, live series name,
# unit scale manifest→seconds). Only the committed keys that map
# cleanly onto a live series ride the default catalogue; a missing
# key skips its rule (older manifests must not crash newer servers).
DEFAULT_MANIFEST_RULES = (
    ("latency_below_cap_p99", "pilosa_query_duration_seconds:p99",
     1e-3),
    ("latency_below_cap_p50", "pilosa_query_duration_seconds:p50",
     1e-3),
)


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def robust_z(recent: list[float], baseline: list[float]
             ) -> tuple[float, float, float]:
    """(z, recent_median, baseline_median) via median/MAD. A flat
    baseline (MAD 0) falls back to a fraction of the median as the
    scale so a constant-then-jump series still yields a finite z."""
    rm = _median(recent)
    bm = _median(baseline)
    mad = _median([abs(v - bm) for v in baseline])
    scale = 1.4826 * mad
    if scale <= 0:
        scale = max(abs(bm) * 0.05, 1e-9)
    return (rm - bm) / scale, rm, bm


class Sentinel:
    """The slow-cadence evaluator (module docstring). ``history`` is
    the obs.history.MetricHistory to read; tracer/sampler/registry/
    blackbox are the evidence-capture hooks (same wiring shape as the
    watchdog)."""

    def __init__(self, history, registry=None, tracer=None,
                 sampler=None, blackbox=None,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 window_s: float = DEFAULT_WINDOW_S,
                 baseline_s: float = DEFAULT_BASELINE_S,
                 zscore: float = DEFAULT_ZSCORE,
                 min_points: int = DEFAULT_MIN_POINTS,
                 min_ratio: float = DEFAULT_MIN_RATIO,
                 retrip_s: float = DEFAULT_RETRIP_S,
                 manifest_path: str = "",
                 manifest_tolerance: float = DEFAULT_MANIFEST_TOLERANCE,
                 watches=DEFAULT_WATCHES,
                 tenant_burn_threshold: float
                 = DEFAULT_TENANT_BURN_THRESHOLD,
                 planner_misest_threshold: float
                 = DEFAULT_PLANNER_MISEST_THRESHOLD, logger=None):
        from ..utils import logger as logger_mod
        self.history = history
        self.registry = registry    # sched.QueryRegistry
        self.tracer = tracer        # obs.trace.Tracer
        self.sampler = sampler      # obs.sampler.TailSampler
        self.blackbox = blackbox    # obs.blackbox.Blackbox
        self.interval_s = max(0.02, float(interval_s))
        self.window_s = float(window_s)
        self.baseline_s = float(baseline_s)
        self.zscore = float(zscore)
        self.min_points = max(2, int(min_points))
        self.min_ratio = max(1.0, float(min_ratio))
        self.retrip_s = float(retrip_s)
        self.manifest_path = manifest_path
        self.manifest_tolerance = float(manifest_tolerance)
        self.watches = tuple(watches)
        self.tenant_burn_threshold = float(tenant_burn_threshold)
        self.planner_misest_threshold = float(planner_misest_threshold)
        self.logger = logger or logger_mod.NOP
        self.findings: list[dict] = []   # newest last, bounded
        self.checks = 0
        self._mu = threading.Lock()
        self._last_fire: dict[str, float] = {}
        self._active: set[tuple[str, str]] = set()
        self._manifest: Optional[dict] = None
        self._manifest_mtime = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="pilosa-sentinel",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Stop AND join: the server closes the blackbox/trace disk
        rings right after, and a sentinel thread still mid-check with
        a firing rule would reopen a stray segment past the close
        (the RuntimeCollector.stop discipline)."""
        self._stop.set()
        thread = self._thread
        if thread is not None \
                and thread is not threading.current_thread():
            thread.join(timeout=5.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check()
            except Exception:  # noqa: BLE001 - the sentinel must not die
                pass

    # -- the manifest envelope -------------------------------------------------

    def _manifest_metrics(self) -> dict:
        """The committed metrics table, re-read when the file changes
        (bench passes rewrite it); {} when absent/broken."""
        path = self.manifest_path
        if not path:
            return {}
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            return {}
        if self._manifest is None or mtime != self._manifest_mtime:
            try:
                with open(path) as f:
                    doc = json.load(f)
                self._manifest = doc.get("metrics", {}) or {}
                self._manifest_mtime = mtime
            except (OSError, ValueError):
                return self._manifest or {}
        return self._manifest or {}

    # -- evaluation ------------------------------------------------------------

    def check(self, now: Optional[float] = None) -> list[dict]:
        """One pass over every rule; fires (and returns) the findings
        raised this pass. Recovered conditions clear their active
        gauge."""
        now = time.time() if now is None else float(now)
        fired = []
        seen_active: set[tuple[str, str]] = set()
        for finding in self._evaluate(now):
            key = (finding["metric"], finding["direction"])
            seen_active.add(key)
            if self._fire(finding, now):
                fired.append(finding)
        with self._mu:
            recovered = self._active - seen_active
            self._active = seen_active
            self.checks += 1
        for metric, direction in recovered:
            obs_metrics.SENTINEL_ACTIVE.labels(metric, direction).set(0)
        for metric, direction in seen_active:
            obs_metrics.SENTINEL_ACTIVE.labels(metric, direction).set(1)
        obs_metrics.SENTINEL_CHECKS.inc()
        return fired

    def _evaluate(self, now: float) -> list[dict]:
        out = []
        hist = self.history
        if hist is None:
            return out
        # Robust-z rules over every labeled series of each watch.
        for family, direction in self.watches:
            for key in hist.keys():
                name, labels = split_key(key)
                if name != family:
                    continue
                recent = hist.window_values(
                    key, now - self.window_s, now + 1.0)
                baseline = hist.window_values(
                    key, now - self.baseline_s, now - self.window_s)
                if (len(recent) < self.min_points
                        or len(baseline) < self.min_points):
                    continue
                z, rm, bm = robust_z(recent, baseline)
                if direction == "up":
                    ratio_ok = rm >= bm * self.min_ratio
                    z_ok = z >= self.zscore
                else:
                    ratio_ok = bm > 0 and rm <= bm / self.min_ratio
                    z_ok = z <= -self.zscore
                if z_ok and ratio_ok:
                    out.append({
                        "rule": "robust_z", "metric": family,
                        "series": key, "labels": labels,
                        "direction": direction,
                        "z": round(z, 2),
                        "recentMedian": round(rm, 6),
                        "baselineMedian": round(bm, 6),
                        "windowS": self.window_s,
                        "baselineS": self.baseline_s})
        # Per-tenant SLO-burn rule: absolute threshold over the
        # tenant burn-rate gauge series (sched.tenants isolation
        # contract — a quiet tenant's burn past the fast-burn
        # threshold is a finding whoever caused it).
        if self.tenant_burn_threshold > 0:
            for key in hist.keys():
                name, labels = split_key(key)
                if name != DEFAULT_TENANT_BURN_FAMILY:
                    continue
                recent = hist.window_values(
                    key, now - self.window_s, now + 1.0)
                if len(recent) < self.min_points:
                    continue
                rm = _median(recent)
                if rm > self.tenant_burn_threshold:
                    out.append({
                        "rule": "tenant_burn",
                        "metric": DEFAULT_TENANT_BURN_FAMILY,
                        "series": key, "labels": labels,
                        "direction": "up",
                        "recentMedian": round(rm, 4),
                        "threshold": self.tenant_burn_threshold,
                        "windowS": self.window_s})
        # Planner misestimation rule: absolute threshold over the
        # misestimation-ratio p99 series (plan.planner observes
        # (actual+1)/(est+1) per node as actuals land).
        if self.planner_misest_threshold > 0:
            for key in hist.keys():
                name, labels = split_key(key)
                if name != DEFAULT_PLANNER_MISEST_FAMILY:
                    continue
                recent = hist.window_values(
                    key, now - self.window_s, now + 1.0)
                if len(recent) < self.min_points:
                    continue
                rm = _median(recent)
                if rm > self.planner_misest_threshold:
                    out.append({
                        "rule": "planner_misestimate",
                        "metric": DEFAULT_PLANNER_MISEST_FAMILY,
                        "series": key, "labels": labels,
                        "direction": "up",
                        "recentMedian": round(rm, 4),
                        "threshold": self.planner_misest_threshold,
                        "windowS": self.window_s})
        # Manifest envelope rules.
        metrics = self._manifest_metrics()
        for man_key, family, to_seconds in DEFAULT_MANIFEST_RULES:
            entry = metrics.get(man_key)
            if not isinstance(entry, dict) or "value" not in entry:
                continue
            try:
                committed = float(entry["value"]) * to_seconds
            except (TypeError, ValueError):
                continue
            if committed <= 0:
                continue
            bound = committed * self.manifest_tolerance
            for key in hist.keys():
                name, labels = split_key(key)
                if name != family:
                    continue
                recent = hist.window_values(
                    key, now - self.window_s, now + 1.0)
                if len(recent) < self.min_points:
                    continue
                rm = _median(recent)
                if rm > bound:
                    out.append({
                        "rule": "manifest", "metric": family,
                        "series": key, "labels": labels,
                        "direction": "up",
                        "recentMedian": round(rm, 6),
                        "committed": round(committed, 6),
                        "tolerance": self.manifest_tolerance,
                        "manifestKey": man_key})
        return out

    # -- firing ----------------------------------------------------------------

    def _fire(self, finding: dict, now: float) -> bool:
        key = finding["series"]
        with self._mu:
            last = self._last_fire.get(key, 0.0)
            if last and now - last < self.retrip_s:
                return False
            self._last_fire[key] = now
            finding = dict(finding, firedAt=now)
            self.findings.append(finding)
            del self.findings[:-64]
        obs_metrics.SENTINEL_FINDINGS.labels(
            finding["metric"], finding["direction"]).inc()
        self.logger.printf(
            "sentinel finding: %s %s (%s: recent=%s baseline/bound"
            "=%s)", finding["metric"], finding["direction"],
            finding["rule"], finding.get("recentMedian"),
            finding.get("baselineMedian", finding.get("committed")))
        self._force_keep_traces()
        if self.blackbox is not None:
            try:
                self.blackbox.snapshot("sentinel",
                                       extra={"sentinel": finding})
            except TypeError:  # pre-extra test doubles
                self.blackbox.snapshot("sentinel")
            except Exception:  # noqa: BLE001 - evidence best-effort
                pass
        return True

    def _force_keep_traces(self) -> None:
        """Every in-flight query's trace-so-far, kept under reason
        ``anomaly`` — the queries living through the cliff are the
        evidence (same claim discipline as the watchdog's force-keep:
        exactly one keeper enters the ring/disk)."""
        if self.registry is None or self.tracer is None:
            return
        for ctx in self.registry.active_contexts():
            trace = getattr(ctx, "trace", None)
            if trace is None or getattr(trace, "keep_reason", ""):
                continue
            try:
                if self.tracer.keep(trace, reason="anomaly") \
                        and self.sampler is not None:
                    self.sampler.persist(trace, "anomaly", ctx=ctx)
            except Exception:  # noqa: BLE001
                continue

    def snapshot(self) -> dict:
        with self._mu:
            return {"checks": self.checks,
                    "findings": list(self.findings[-16:]),
                    "active": sorted(f"{m}:{d}"
                                     for m, d in self._active),
                    "intervalS": self.interval_s,
                    "windowS": self.window_s,
                    "baselineS": self.baseline_s,
                    "zscore": self.zscore,
                    "manifest": self.manifest_path or None}
