"""Deterministic workload replay + shadow diff (obs.capture consumers).

The engine behind ``pilosa-tpu replay`` and ``benchmarks/replay.py``:
re-issues a captured (or merged multi-node) record stream against any
cluster as a **multi-process open-loop driver** — each record fires at
its recorded arrival offset (scaled by ``--rate xN``) regardless of
completions, so queueing delay shows up as latency exactly like the
live traffic it was recorded from. Tenant headers, lanes, and the
effective ``?timeout=``/``?partial=`` options replay verbatim;
latency counts from the SCHEDULED send time (open-loop accounting,
the latency_under_load.py discipline).

Records with ``kind == "import"`` mark state mutations whose payload
the capture ring does not hold (only the ack is recorded); replay
counts them as skipped — bulk loads re-drive via the import tool.

Shadow mode replays the same stream against a baseline AND a candidate
endpoint: write queries go to both **in order** first (state must
converge before reads compare), then reads fire at both concurrently
and the canonical result digests (X-Pilosa-Result-Digest, recomputed
from the body when the header is absent) are compared. Mismatches
report the plan fingerprint — the /debug/plans key on both sides —
and full result dumps for the first K.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from . import capture as obs_capture

# Statuses that count as load shedding (not errors): admission 429,
# cost-policy kill 402, write-unready 507.
SHED_STATUSES = (429, 402, 507)

DEFAULT_SENDERS = 32


# -- record sources -----------------------------------------------------------


def load_records(path: str) -> list[dict]:
    """Records from a file: JSONL (one record per line) or a JSON
    document carrying a ``records`` list (the /debug/capture/records
    response shape, saved verbatim)."""
    with open(path) as f:
        text = f.read()
    text = text.strip()
    if not text:
        return []
    try:
        doc = json.loads(text)
    except ValueError:
        # JSONL: one record per line (a ring segment saved verbatim).
        return [json.loads(line) for line in text.splitlines() if line]
    if isinstance(doc, list):
        return doc
    return doc.get("records", [])


def fetch_records(host: str, since: int = 0, limit: int = 10000,
                  cluster: bool = False,
                  timeout: float = 30.0) -> list[dict]:
    """Records exported live from a node's /debug/capture/records
    (``cluster=True`` asks for the merged cluster scope)."""
    params = {"since": since, "limit": limit}
    if cluster:
        params["scope"] = "cluster"
    url = (f"http://{host}/debug/capture/records?"
           + urllib.parse.urlencode(params))
    with urllib.request.urlopen(url, timeout=timeout) as r:
        doc = json.loads(r.read())
    return doc.get("records", [])


def schedule(records: list[dict], rate: float = 1.0) -> list[float]:
    """Send offsets (seconds from replay start) preserving the
    recorded inter-arrival gaps, compressed by ``rate`` (x2 = half
    the gaps)."""
    rate = max(rate, 1e-9)
    return [off / rate
            for off in obs_capture.arrival_offsets(records)]


# -- one request --------------------------------------------------------------


def _issue(host: str, rec: dict, timeout_s: float = 30.0,
           want_results: bool = False) -> dict:
    """Re-issue one captured query record; returns
    ``{"status", "digest", "latS", "results"?}``. Network errors map
    to status 0."""
    params = dict(rec.get("opts") or {})
    if params.get("partial") is True:
        params["partial"] = "1"
    path = f"/index/{rec.get('index', '')}/query"
    if params:
        path += "?" + urllib.parse.urlencode(params)
    headers = {}
    if rec.get("tenant"):
        headers["X-Pilosa-Tenant"] = rec["tenant"]
    req = urllib.request.Request(
        f"http://{host}{path}", data=rec.get("pql", "").encode(),
        method="POST", headers=headers)
    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            body = r.read()
            digest = r.headers.get(obs_capture.DIGEST_HEADER, "")
            status = r.status
    except urllib.error.HTTPError as e:
        e.read()
        return {"status": e.code, "digest": "",
                "latS": time.perf_counter() - t0}
    except OSError:
        return {"status": 0, "digest": "",
                "latS": time.perf_counter() - t0}
    out = {"status": status, "digest": digest,
           "latS": time.perf_counter() - t0}
    if want_results or not digest:
        try:
            results = json.loads(body).get("results", [])
        except ValueError:
            results = None
        if results is not None:
            if not digest:
                out["digest"] = obs_capture.result_digest(results)
            if want_results:
                out["results"] = results
    return out


# -- the open-loop shard (one process) ----------------------------------------


def _replay_shard(args: tuple) -> list[dict]:
    """Open-loop replay of one shard: (records, offsets, host,
    t0_wall, senders). Runs in a worker process (or inline) and
    returns per-record outcomes ``{"lane", "status", "latS",
    "lateS"}``. Latency counts from the SCHEDULED time."""
    records, offsets, host, t0_wall, senders = args
    outcomes: list[Optional[dict]] = [None] * len(records)
    mu = threading.Lock()
    ticket = {"i": 0}

    def sender():
        while True:
            with mu:
                i = ticket["i"]
                if i >= len(records):
                    return
                ticket["i"] = i + 1
            scheduled = t0_wall + offsets[i]
            delay = scheduled - time.time()
            if delay > 0:
                time.sleep(delay)
            rec = records[i]
            if rec.get("kind") != "query":
                outcomes[i] = {"lane": rec.get("lane", "write"),
                               "status": -1, "latS": 0.0,
                               "lateS": 0.0}
                continue
            res = _issue(host, rec)
            # Open-loop accounting: sender-pool delay is latency.
            late = max(0.0, time.time() - scheduled - res["latS"])
            outcomes[i] = {"lane": rec.get("lane", "read"),
                           "status": res["status"],
                           "latS": res["latS"] + late,
                           "lateS": late}

    threads = [threading.Thread(target=sender)
               for _ in range(max(1, min(senders, len(records))))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return [o for o in outcomes if o is not None]


def _percentile(sorted_vals: list[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1,
            max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


def _summarize(outcomes: list[dict], offered_qps: float,
               wall_s: float) -> dict:
    """Per-lane p50/p99 + shed rates + achieved-vs-offered QPS over
    the flattened shard outcomes."""
    lanes: dict[str, dict] = {}
    completed = shed = errors = skipped = 0
    for o in outcomes:
        if o["status"] == -1:
            skipped += 1
            continue
        lane = lanes.setdefault(o["lane"],
                                {"lats": [], "shed": 0, "errors": 0})
        if o["status"] == 200:
            completed += 1
            lane["lats"].append(o["latS"])
        elif o["status"] in SHED_STATUSES:
            shed += 1
            lane["shed"] += 1
        else:
            errors += 1
            lane["errors"] += 1
    per_lane = {}
    for lane, st in sorted(lanes.items()):
        lats = sorted(st["lats"])
        n = len(lats) + st["shed"] + st["errors"]
        per_lane[lane] = {
            "sent": n, "completed": len(lats),
            "shed": st["shed"], "errors": st["errors"],
            "shed_rate": round(st["shed"] / n, 4) if n else 0.0,
            "p50_ms": round(_percentile(lats, 50) * 1e3, 3),
            "p99_ms": round(_percentile(lats, 99) * 1e3, 3),
        }
    return {
        "offered": len(outcomes) - skipped,
        "completed": completed, "shed": shed, "errors": errors,
        "skipped_imports": skipped,
        "offered_qps": round(offered_qps, 1),
        "achieved_qps": round(completed / wall_s, 1) if wall_s else 0.0,
        "wall_s": round(wall_s, 3),
        "lanes": per_lane,
    }


def replay(records: list[dict], host: str, rate: float = 1.0,
           processes: int = 1, senders: int = DEFAULT_SENDERS) -> dict:
    """Multi-process open-loop replay of ``records`` against ``host``.
    Shards round-robin across ``processes`` worker processes sharing
    one wall-clock t0 (``processes=1`` runs inline — the test path,
    fork-free). Returns the summary dict (REPLAY.json's ``replay``
    block)."""
    records = [r for r in records if r.get("kind") in
               ("query", "import")]
    if not records:
        return _summarize([], 0.0, 0.0)
    offsets = schedule(records, rate)
    span_s = max(offsets[-1], 1e-6)
    n_q = sum(1 for r in records if r.get("kind") == "query")
    offered_qps = n_q / span_s
    processes = max(1, int(processes))
    shards: list[tuple] = []
    t0_wall = time.time() + 0.25  # let every process reach the gate
    for p in range(processes):
        recs = records[p::processes]
        offs = offsets[p::processes]
        if recs:
            shards.append((recs, offs, host, t0_wall, senders))
    wall_t0 = time.perf_counter()
    if len(shards) == 1:
        results = [_replay_shard(shards[0])]
    else:
        import multiprocessing as mp
        with mp.get_context("fork").Pool(len(shards)) as pool:
            results = pool.map(_replay_shard, shards)
    wall_s = time.perf_counter() - wall_t0
    outcomes = [o for shard in results for o in shard]
    out = _summarize(outcomes, offered_qps, wall_s)
    out["rate"] = rate
    out["processes"] = len(shards)
    return out


# -- shadow diff --------------------------------------------------------------


def shadow(records: list[dict], baseline: str, candidate: str,
           max_dumps: int = 8,
           senders: int = DEFAULT_SENDERS) -> dict:
    """Differential replay: write queries go to BOTH endpoints in
    recorded order (sequentially — state must converge), then each
    read fires at both concurrently and the canonical digests are
    compared. Returns mismatch rate + the first ``max_dumps``
    mismatches with full result dumps and plan fingerprints."""
    writes = [r for r in records if r.get("kind") == "query"
              and r.get("lane") != "read"]
    reads = [r for r in records if r.get("kind") == "query"
             and r.get("lane") == "read"]
    for rec in writes:
        _issue(baseline, rec)
        _issue(candidate, rec)

    compared = [0]
    mismatches: list[dict] = []
    mu = threading.Lock()
    ticket = {"i": 0}

    def check(rec: dict) -> None:
        pair: dict = {}

        def side(name: str, host: str) -> None:
            pair[name] = _issue(host, rec, want_results=True)

        tb = threading.Thread(target=side, args=("baseline", baseline))
        tc = threading.Thread(target=side,
                              args=("candidate", candidate))
        tb.start(); tc.start(); tb.join(); tc.join()
        b, c = pair["baseline"], pair["candidate"]
        if b["status"] != 200 or c["status"] != 200:
            return
        with mu:
            compared[0] += 1
            if b["digest"] != c["digest"]:
                entry = {"seq": rec.get("seq"),
                         "pql": rec.get("pql", ""),
                         "index": rec.get("index", ""),
                         "plan": rec.get("plan", ""),
                         "recordedDigest": rec.get("digest", ""),
                         "baselineDigest": b["digest"],
                         "candidateDigest": c["digest"]}
                if len(mismatches) < max_dumps:
                    entry["baselineResults"] = b.get("results")
                    entry["candidateResults"] = c.get("results")
                mismatches.append(entry)

    def sender():
        while True:
            with mu:
                i = ticket["i"]
                if i >= len(reads):
                    return
                ticket["i"] = i + 1
            check(reads[i])

    threads = [threading.Thread(target=sender)
               for _ in range(max(1, min(senders, len(reads) or 1)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    n = compared[0]
    return {
        "baseline": baseline, "candidate": candidate,
        "writes_replayed": len(writes), "reads_compared": n,
        "mismatches": len(mismatches),
        "mismatch_rate": round(len(mismatches) / n, 4) if n else 0.0,
        "dumps": mismatches[:max_dumps],
    }
