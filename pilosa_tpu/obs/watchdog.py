"""Stall watchdog: detects wedged internals and triggers evidence
capture while the wedge is still observable.

Four stall detectors, each cheap enough to run every second:

- **wal_flusher** — the WAL group-commit flusher is wedged: some WAL
  has had pending (unflushed) records for longer than ``wal_stall_s``
  (storage.wal keeps a per-WAL dirty-since timestamp plus a flusher
  heartbeat; a healthy flusher drains within ~one window).
- **stuck_query** — an executor leg is still ``running`` more than
  ``deadline_grace_s`` past its deadline: cooperative cancellation
  should have surfaced QueryDeadlineError long ago, so something is
  blocked in a non-checking section (a hung syscall, a lost lock).
- **gossip_silence** — a multi-node cluster's membership layer has
  received nothing for ``gossip_silence_s``: probes, push/pull and
  rumors are all silent, so failure detection is blind.
- **admission_stall** — queries are queued but nothing has been
  granted a slot for ``queue_stall_s``: the queue is not draining
  (every slot wedged, or a lost wakeup).
- **resize_stall** — this node coordinates an elastic resize whose
  active phase has made no forward progress (no ack, no streamed
  block, no phase move) for ``resize_stall_s``: a wedged stream
  target, a partitioned flip, or a stuck control send — the window
  where the cluster is paying double-write/double-read overhead for
  nothing (docs/CLUSTER_RESIZE.md).
- **scrub_stall** — a background storage-scrub pass (storage.scrub)
  is in flight but has verified no fragment for ``scrub_stall_s``: a
  hung disk read or a wedged pacing sleep — the window where silent
  corruption detection is blind.
- **tier_stall** — the tier working-set manager (tier.manager) has
  demotion/eviction work pending but has completed no transition for
  ``tier_stall_s``: a wedged snapshot barrier or a hung blob
  transfer — the window where watermark pressure keeps building
  and cold reads stop promoting.
- **backup_stall** — the backup plane (pilosa_tpu.backup) has work in
  flight — a coordinated backup pushing fragments, or the continuous
  WAL archiver with pending segments — but has completed nothing for
  ``backup_stall_s``: a hung archive store or a wedged source fetch,
  the window where the recovery point silently stops advancing.

A trip increments ``pilosa_watchdog_trips_total{cause}``, force-keeps
every in-flight trace (reason ``watchdog`` — the wedged query's spans
so far are exactly the evidence), and triggers a blackbox dump naming
the cause. Per-cause re-trips are rate-limited (``retrip_s``) so a
persistent wedge produces a dump per window, not per tick.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from . import metrics as obs_metrics

DEFAULT_INTERVAL_S = 1.0
DEFAULT_WAL_STALL_S = 5.0
DEFAULT_DEADLINE_GRACE_S = 5.0
DEFAULT_GOSSIP_SILENCE_S = 60.0
DEFAULT_QUEUE_STALL_S = 10.0
DEFAULT_RESIZE_STALL_S = 60.0
DEFAULT_SCRUB_STALL_S = 300.0
DEFAULT_TIER_STALL_S = 120.0
DEFAULT_BACKUP_STALL_S = 120.0
DEFAULT_RETRIP_S = 60.0

CAUSES = ("wal_flusher", "stuck_query", "gossip_silence",
          "admission_stall", "resize_stall", "scrub_stall",
          "tier_stall", "backup_stall")


class Watchdog:
    def __init__(self, registry=None, admission=None, tracer=None,
                 sampler=None, blackbox=None,
                 gossip_age_fn: Optional[Callable[[], Optional[float]]]
                 = None,
                 resize_progress_fn: Optional[Callable] = None,
                 scrub_progress_fn: Optional[Callable] = None,
                 tier_progress_fn: Optional[Callable] = None,
                 backup_progress_fn: Optional[Callable] = None,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 wal_stall_s: float = DEFAULT_WAL_STALL_S,
                 deadline_grace_s: float = DEFAULT_DEADLINE_GRACE_S,
                 gossip_silence_s: float = DEFAULT_GOSSIP_SILENCE_S,
                 queue_stall_s: float = DEFAULT_QUEUE_STALL_S,
                 resize_stall_s: float = DEFAULT_RESIZE_STALL_S,
                 scrub_stall_s: float = DEFAULT_SCRUB_STALL_S,
                 tier_stall_s: float = DEFAULT_TIER_STALL_S,
                 backup_stall_s: float = DEFAULT_BACKUP_STALL_S,
                 retrip_s: float = DEFAULT_RETRIP_S, logger=None):
        from ..utils import logger as logger_mod
        self.registry = registry      # sched.QueryRegistry
        self.admission = admission    # sched.AdmissionController
        self.tracer = tracer          # obs.trace.Tracer
        self.sampler = sampler        # obs.sampler.TailSampler
        self.blackbox = blackbox      # obs.blackbox.Blackbox
        self.gossip_age_fn = gossip_age_fn
        # () -> None | (phase, seconds_without_progress): the server's
        # view of an ACTIVE resize it coordinates (cluster.resize).
        self.resize_progress_fn = resize_progress_fn
        # () -> None | seconds_without_progress of an IN-FLIGHT scrub
        # pass (storage.scrub.Scrubber.stall_age).
        self.scrub_progress_fn = scrub_progress_fn
        # () -> None | seconds_without_progress while the tier
        # manager has pending demotion/eviction work
        # (tier.manager.TierManager.stall_age).
        self.tier_progress_fn = tier_progress_fn
        # () -> None | seconds_without_progress while the backup plane
        # has in-flight work (server.BackupManager.stall_age).
        self.backup_progress_fn = backup_progress_fn
        self.interval_s = max(0.02, float(interval_s))
        self.wal_stall_s = float(wal_stall_s)
        self.deadline_grace_s = float(deadline_grace_s)
        self.gossip_silence_s = float(gossip_silence_s)
        self.queue_stall_s = float(queue_stall_s)
        self.resize_stall_s = float(resize_stall_s)
        self.scrub_stall_s = float(scrub_stall_s)
        self.tier_stall_s = float(tier_stall_s)
        self.backup_stall_s = float(backup_stall_s)
        self.retrip_s = float(retrip_s)
        self.logger = logger or logger_mod.NOP
        self.trips = 0
        self._last_trip: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run,
                                        name="pilosa-watchdog",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check()
            except Exception:  # noqa: BLE001 - the watchdog must not die
                pass

    # -- detectors ------------------------------------------------------------

    def check(self) -> list[tuple[str, str]]:
        """One pass over every detector; trips (and returns) the
        ``(cause, detail)`` pairs that fired this pass."""
        fired = []
        for cause, detail in self._stalls():
            if self._trip(cause, detail):
                fired.append((cause, detail))
        return fired

    def _stalls(self) -> list[tuple[str, str]]:
        out = []
        # Wedged WAL flusher (dirty-age past threshold).
        try:
            from ..storage import wal as storage_wal
            health = storage_wal.flusher_health()
        except Exception:  # noqa: BLE001
            health = {}
        age = health.get("oldestDirtyAgeS") or 0.0
        if self.wal_stall_s > 0 and age > self.wal_stall_s:
            worst = (health.get("wals") or [{}])[0]
            out.append(("wal_flusher",
                        f"dirty {age:.2f}s: {worst.get('file', '?')}"
                        f" ({worst.get('pendingBytes', 0)}B pending)"))
        # Executor legs stuck past deadline grace.
        if self.registry is not None and self.deadline_grace_s > 0:
            for ctx in self.registry.active_contexts():
                rem = ctx.remaining()
                if (rem is not None and -rem > self.deadline_grace_s
                        and ctx.state == "running"):
                    out.append((
                        "stuck_query",
                        f"query {ctx.id} {-rem:.2f}s past deadline"
                        f" (pql={ctx.pql[:80]!r})"))
                    break  # one trip covers the pass; the dump lists all
        # Gossip silence (multi-node only; the fn returns None when
        # silence is not observable — single node, static membership).
        if self.gossip_age_fn is not None and self.gossip_silence_s > 0:
            try:
                age = self.gossip_age_fn()
            except Exception:  # noqa: BLE001
                age = None
            if age is not None and age > self.gossip_silence_s:
                out.append(("gossip_silence",
                            f"no membership traffic for {age:.1f}s"))
        # Non-draining admission queue.
        if self.admission is not None and self.queue_stall_s > 0:
            queued, grant_age = self.admission.stall_state()
            if queued > 0 and grant_age > self.queue_stall_s:
                out.append((
                    "admission_stall",
                    f"{queued} queued, no grant for {grant_age:.1f}s"))
        # Stalled elastic resize (this node coordinating).
        if (self.resize_progress_fn is not None
                and self.resize_stall_s > 0):
            try:
                st = self.resize_progress_fn()
            except Exception:  # noqa: BLE001
                st = None
            if st is not None:
                phase, age = st
                if age > self.resize_stall_s:
                    out.append((
                        "resize_stall",
                        f"resize phase {phase}: no progress for"
                        f" {age:.1f}s"))
        # Stalled storage scrub pass (storage.scrub).
        if (self.scrub_progress_fn is not None
                and self.scrub_stall_s > 0):
            try:
                age = self.scrub_progress_fn()
            except Exception:  # noqa: BLE001
                age = None
            if age is not None and age > self.scrub_stall_s:
                out.append((
                    "scrub_stall",
                    f"scrub pass in flight, no fragment verified for"
                    f" {age:.1f}s"))
        # Stalled tier working-set manager (tier.manager).
        if (self.tier_progress_fn is not None
                and self.tier_stall_s > 0):
            try:
                age = self.tier_progress_fn()
            except Exception:  # noqa: BLE001
                age = None
            if age is not None and age > self.tier_stall_s:
                out.append((
                    "tier_stall",
                    f"tier work pending, no transition completed for"
                    f" {age:.1f}s"))
        # Stalled backup plane (pilosa_tpu.backup).
        if (self.backup_progress_fn is not None
                and self.backup_stall_s > 0):
            try:
                age = self.backup_progress_fn()
            except Exception:  # noqa: BLE001
                age = None
            if age is not None and age > self.backup_stall_s:
                out.append((
                    "backup_stall",
                    f"backup work in flight, no progress for"
                    f" {age:.1f}s"))
        return out

    # -- the trip --------------------------------------------------------------

    def _trip(self, cause: str, detail: str) -> bool:
        now = time.monotonic()
        last = self._last_trip.get(cause, 0.0)
        if last and now - last < self.retrip_s:
            return False
        self._last_trip[cause] = now
        self.trips += 1
        obs_metrics.WATCHDOG_TRIPS.labels(cause).inc()
        self.logger.printf("watchdog trip: %s (%s)", cause, detail)
        self._force_keep_traces(cause)
        if self.blackbox is not None:
            try:
                self.blackbox.dump(f"watchdog:{cause}")
            except Exception:  # noqa: BLE001
                pass
        return True

    def _force_keep_traces(self, cause: str) -> None:
        """Every in-flight query's trace-so-far into the ring + disk:
        the wedged query is by definition still running, and its spans
        up to the wedge are the evidence."""
        if self.registry is None or self.tracer is None:
            return
        for ctx in self.registry.active_contexts():
            trace = getattr(ctx, "trace", None)
            if trace is None or getattr(trace, "keep_reason", ""):
                continue
            try:
                # keep() claims atomically — a concurrently-finishing
                # query's own keep decision may win the race, in which
                # case this trace is already entered and we skip it.
                if self.tracer.keep(trace, reason="watchdog") \
                        and self.sampler is not None:
                    self.sampler.persist(trace, "watchdog", ctx=ctx)
            except Exception:  # noqa: BLE001
                continue

    def snapshot(self) -> dict:
        now = time.monotonic()
        return {"trips": self.trips,
                "lastTrip": {c: round(now - t, 1)
                             for c, t in self._last_trip.items()},
                "thresholds": {"walStallS": self.wal_stall_s,
                               "deadlineGraceS": self.deadline_grace_s,
                               "gossipSilenceS": self.gossip_silence_s,
                               "queueStallS": self.queue_stall_s,
                               "resizeStallS": self.resize_stall_s,
                               "scrubStallS": self.scrub_stall_s,
                               "tierStallS": self.tier_stall_s,
                               "backupStallS": self.backup_stall_s}}
