"""Continuous profiling: an always-on low-Hz wall-clock sampler.

``utils/profiling.py``'s samplers are on-demand (a request blocks for
N seconds while the sampler runs). Production wants the opposite: a
background sampler that is ALWAYS running at a rate too low to matter
(default 10 Hz, a few microseconds of work per tick), so that when a
query is slow you already have its stacks — no reproduction required.

Samples land in a bounded ring as **query-id-tagged folded stacks**:
each sampled thread's collapsed stack is tagged with the query id
bound to that thread (sched.context.by_thread), so
``GET /debug/pprof/flame?query=<id>`` answers "where did THAT query
spend its wall time" — the continuous-profiling analogue of the
per-query cost ledger (obs.accounting).

``GET /debug/pprof/flame`` serves collapsed-stack text
(``a;b;c count`` lines — directly loadable by speedscope and
flamegraph.pl). Overhead contract mirrors tracing's: a profiler that
was never started samples nothing and the serving path never touches
it (the nop path is a None check in the handler).
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter, deque
from typing import Optional

from ..utils.profiling import _is_idle_leaf

DEFAULT_HZ = 10.0
DEFAULT_RING = 8192

# Stack-depth cap per sample: flame views past ~64 frames are noise
# and unbounded recursion must not balloon the ring's memory.
MAX_FRAMES = 64


def _collapse(frame) -> str:
    stack = []
    f = frame
    depth = 0
    while f is not None and depth < MAX_FRAMES:
        code = f.f_code
        stack.append(f"{code.co_name} "
                     f"({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
        f = f.f_back
        depth += 1
    return ";".join(reversed(stack))


class ContinuousProfiler:
    """Background low-Hz sampler with a bounded sample ring.

    Each ring entry is ``(wall_ts, query_id_or_empty, folded_stack)``.
    The ring bounds memory whatever the rate: at the default 10 Hz and
    8192 entries it holds the last ~10 minutes of a busy node.
    """

    def __init__(self, hz: float = DEFAULT_HZ,
                 ring: int = DEFAULT_RING):
        self.hz = max(0.1, min(float(hz), 100.0))
        self.interval = 1.0 / self.hz
        self._ring: deque[tuple[float, str, str]] = deque(
            maxlen=max(16, int(ring)))
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples_taken = 0
        self.idle_dropped = 0
        self.started_at: Optional[float] = None

    # -- lifecycle -----------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()
        self.started_at = time.time()
        self._thread = threading.Thread(target=self._run,
                                        name="pilosa-continuous-profiler",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - sampling must not die
                pass

    # -- sampling ------------------------------------------------------------

    def sample_once(self) -> int:
        """One sampling tick: collapse every non-idle thread stack,
        tagged with the query id bound to that thread (if any).
        Returns how many stacks were recorded."""
        from ..sched import context as sched_context
        me = threading.get_ident()
        by_thread = sched_context.by_thread()
        now = time.time()
        recorded = 0
        entries = []
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            if _is_idle_leaf(frame):
                self.idle_dropped += 1
                continue
            ctx = by_thread.get(tid)
            qid = ctx.id if ctx is not None else ""
            entries.append((now, qid, _collapse(frame)))
            recorded += 1
        if entries:
            with self._mu:
                self._ring.extend(entries)
        self.samples_taken += 1
        from . import metrics as obs_metrics
        obs_metrics.PROFILE_SAMPLES.inc()
        return recorded

    # -- export --------------------------------------------------------------

    def flame(self, query: str = "", since_s: float = 0.0) -> str:
        """Collapsed-stack text (``stack count`` lines, weight-sorted)
        aggregated over the ring — speedscope/flamegraph.pl-loadable.
        ``query`` filters to one query id's samples; ``since_s`` keeps
        only samples newer than that many seconds."""
        cutoff = time.time() - since_s if since_s > 0 else 0.0
        counts: Counter[str] = Counter()
        matched = 0
        with self._mu:
            ring = list(self._ring)
        for ts, qid, stack in ring:
            if ts < cutoff:
                continue
            if query and qid != query:
                continue
            counts[stack] += 1
            matched += 1
        header = (f"# continuous profile: {matched} samples"
                  f" ({len(ring)} in ring, {self.hz:g} Hz,"
                  f" {self.idle_dropped} idle dropped)"
                  + (f" query={query}" if query else ""))
        lines = [header]
        for stack, c in counts.most_common():
            lines.append(f"{stack} {c}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        with self._mu:
            n = len(self._ring)
        return {"running": self.running, "hz": self.hz,
                "ringSamples": n, "ticks": self.samples_taken,
                "idleDropped": self.idle_dropped,
                "startedAt": self.started_at}


# Module default, for layers constructed without explicit wiring (bare
# test handlers) — NOT started; the server builds and starts its own
# from [profile] config.
_profiler = ContinuousProfiler()


def get_profiler() -> ContinuousProfiler:
    return _profiler
