"""Distributed tracing: one trace per query, spans per pipeline stage.

A trace's id IS the query id (sched.context.QueryContext.id), which
already rides cluster fan-out as ``X-Pilosa-Query-Id`` — so every
node's spans for one query share an id for free. The wire contract:

- ``X-Pilosa-Trace: 1`` on a forwarded (remote) query asks the peer to
  trace its leg even when the peer's own tracing is off;
- the peer piggybacks its spans back as the compact JSON response
  header ``X-Pilosa-Trace-Spans``, and the coordinator's cluster
  client stitches them into the originating trace (child spans with
  the remote node's attribution).

Spans record wall-clock start + duration (microsecond precision is
plenty; coordinator and peers align on wall time), a name, optional
tags, the owning node, and the recording thread. ``GET /debug/traces``
lists the per-node bounded ring of recent traces;
``GET /debug/traces/{id}`` exports one as Chrome trace-event JSON
(open in https://ui.perfetto.dev — each node renders as a process,
each thread as a track).

Overhead contract: the *keep-everything* mode is OFF by default, and a
QueryContext whose ``trace`` is None allocates nothing —
``span_current()`` returns a shared no-op context manager after two
attribute reads. Since the always-on PR the serving layer attaches a
span buffer to EVERY query (tail sampling, obs.sampler): the buffer
itself is the measured-near-free part, and the keep decision at query
end picks which traces reach the ring and the on-disk segment ring
(``Tracer.keep(trace, reason)``; the keep-reason catalogue lives in
obs.sampler / docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Optional

from ..sched import context as sched_context

# Wire headers (see module docstring).
TRACE_HEADER = "X-Pilosa-Trace"
SPANS_HEADER = "X-Pilosa-Trace-Spans"

# Hard caps so a pathological query can't balloon a trace or the
# piggyback header.
MAX_SPANS = 512
MAX_TRACES = 64


class Span:
    __slots__ = ("name", "start", "dur", "tags", "node", "tid")

    def __init__(self, name: str, start: float, dur: float,
                 tags: Optional[dict] = None, node: str = "",
                 tid: int = 0):
        self.name = name
        self.start = start          # wall seconds
        self.dur = dur              # seconds
        self.tags = tags
        self.node = node
        self.tid = tid

    def to_json(self) -> list:
        # Compact array form: [name, start_us, dur_us, node, tid, tags]
        return [self.name, round(self.start * 1e6),
                round(self.dur * 1e6), self.node, self.tid,
                self.tags or None]

    @staticmethod
    def from_json(row: list) -> "Span":
        return Span(row[0], row[1] / 1e6, row[2] / 1e6,
                    tags=row[5], node=row[3], tid=int(row[4]))


class _SpanCM:
    """Context manager recording one span into a trace on exit."""

    __slots__ = ("_trace", "_name", "_tags", "_t0")

    def __init__(self, trace: "Trace", name: str, tags: Optional[dict]):
        self._trace = trace
        self._name = name
        self._tags = tags

    def __enter__(self):
        self._t0 = time.time()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._trace.add_span(self._name, self._t0,
                             time.time() - self._t0, self._tags)
        return False


class _NopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


NOP_SPAN = _NopSpan()


class Trace:
    """All spans this node recorded (or stitched) for one query."""

    def __init__(self, id: str, node: str = "", pql: str = "",
                 max_spans: int = MAX_SPANS):
        self.id = id
        self.node = node
        self.pql = pql
        self.started = time.time()
        self.max_spans = max_spans
        self.dropped = 0
        # Why the tail sampler retained this trace ("" while in
        # flight / never kept) — obs.sampler's keep-reason catalogue.
        self.keep_reason = ""
        self._mu = threading.Lock()
        self._spans: list[Span] = []

    # -- recording -----------------------------------------------------------

    def claim_keep(self, reason: str) -> bool:
        """Atomically claim the keep of this trace (first claimant
        wins): the end-of-query decision and the watchdog's force-keep
        can race, and exactly ONE of them may enter the ring/disk."""
        with self._mu:
            if self.keep_reason:
                return False
            self.keep_reason = reason
            return True

    def span(self, name: str, **tags) -> _SpanCM:
        return _SpanCM(self, name, tags or None)

    def add_span(self, name: str, start: float, dur: float,
                 tags: Optional[dict] = None, node: str = "",
                 tid: Optional[int] = None) -> None:
        s = Span(name, start, dur, tags, node or self.node,
                 threading.get_ident() if tid is None else tid)
        with self._mu:
            if len(self._spans) >= self.max_spans:
                self.dropped += 1
                return
            self._spans.append(s)

    def add_remote_json(self, payload: str) -> None:
        """Stitch a peer's piggybacked spans (SPANS_HEADER value)."""
        try:
            rows = json.loads(payload)
        except ValueError:
            return
        with self._mu:
            for row in rows:
                if len(self._spans) >= self.max_spans:
                    self.dropped += 1
                    break
                try:
                    self._spans.append(Span.from_json(row))
                except (IndexError, TypeError, ValueError):
                    continue

    # -- export --------------------------------------------------------------

    def spans(self) -> list[Span]:
        with self._mu:
            return list(self._spans)

    # Serialized-spans budget for the piggyback header: http.client
    # rejects header LINES over 65536 bytes (LineTooLong kills the
    # whole response), so the wire form must stay comfortably under.
    _WIRE_BYTES = 48 << 10

    def spans_json(self, max_bytes: int = _WIRE_BYTES) -> str:
        """Compact JSON of this trace's spans, capped at ``max_bytes``
        serialized — over budget, the newest spans drop (the early
        pipeline stages are the ones a stitched view can't infer)."""
        spans = self.spans()
        out = json.dumps([s.to_json() for s in spans],
                         separators=(",", ":"))
        while len(out) > max_bytes and len(spans) > 1:
            spans = spans[:max(1, len(spans) // 2)]
            out = json.dumps([s.to_json() for s in spans],
                             separators=(",", ":"))
        return out

    def summary(self) -> dict:
        spans = self.spans()
        end = max((s.start + s.dur for s in spans),
                  default=self.started)
        out = {
            "id": self.id,
            "node": self.node,
            "pql": self.pql[:200],
            "startedAt": self.started,
            "durationS": round(max(0.0, end - self.started), 6),
            "spanN": len(spans),
            "dropped": self.dropped,
            "nodes": sorted({s.node for s in spans if s.node}),
        }
        if self.keep_reason:
            out["reason"] = self.keep_reason
        return out

    def to_chrome(self) -> dict:
        """Chrome trace-event JSON (perfetto-loadable): one process
        per node, one track per recording thread, spans as complete
        ("X") events in microseconds."""
        events = []
        pids: dict[str, int] = {}
        tids: dict[tuple[int, int], int] = {}
        for s in self.spans():
            node = s.node or self.node or "?"
            pid = pids.setdefault(node, len(pids) + 1)
            tid = tids.setdefault((pid, s.tid), len(tids) + 1)
            ev = {"name": s.name, "ph": "X", "pid": pid, "tid": tid,
                  "ts": round(s.start * 1e6),
                  "dur": max(1, round(s.dur * 1e6))}
            if s.tags:
                ev["args"] = s.tags
            events.append(ev)
        for node, pid in pids.items():
            events.append({"name": "process_name", "ph": "M",
                           "pid": pid, "args": {"name": node}})
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"traceId": self.id, "pql": self.pql[:200],
                          "coordinator": self.node,
                          "dropped": self.dropped},
        }


class Tracer:
    """Per-node tracer: the enabled flag plus the bounded ring of
    recent traces behind /debug/traces."""

    def __init__(self, enabled: bool = False,
                 max_traces: int = MAX_TRACES,
                 max_spans: int = MAX_SPANS):
        self.enabled = enabled
        self.max_spans = max_spans
        self._mu = threading.Lock()
        self._ring: deque[Trace] = deque(maxlen=max(1, max_traces))

    def start(self, ctx, node: str = "") -> Trace:
        """Open a trace for a query context and bind it (ctx.trace) so
        every layer below can record spans through the context."""
        trace = Trace(ctx.id, node=node or getattr(ctx, "node", ""),
                      pql=getattr(ctx, "pql", ""),
                      max_spans=self.max_spans)
        ctx.trace = trace
        return trace

    def keep(self, trace: Trace, reason: str = "requested") -> bool:
        """Retain ``trace`` in the ring under ``reason``; idempotent —
        False (and no second ring entry / counter tick) when another
        keeper already claimed it."""
        from . import metrics as obs_metrics
        if not trace.claim_keep(reason):
            return False
        with self._mu:
            self._ring.append(trace)
        obs_metrics.TRACES_KEPT.labels(reason).inc()
        return True

    def traces(self) -> list[dict]:
        with self._mu:
            ring = list(self._ring)
        return [t.summary() for t in reversed(ring)]

    def get(self, id: str) -> Optional[Trace]:
        with self._mu:
            for t in reversed(self._ring):
                if t.id == id:
                    return t
        return None


# Module default, for layers constructed without explicit wiring (bare
# test handlers); the server builds its own Tracer from [trace] config.
_tracer = Tracer(enabled=False)


def get_tracer() -> Tracer:
    return _tracer


def span_current(name: str, **tags):
    """A span on the current query's trace, or the shared no-op when
    the thread has no traced query — the single hook device dispatch
    and compile layers call without taking a ctx argument. The
    disabled fast path is two attribute reads and no allocation."""
    ctx = sched_context.current()
    if ctx is None:
        return NOP_SPAN
    trace = getattr(ctx, "trace", None)
    if trace is None:
        return NOP_SPAN
    return trace.span(name, **tags)
