"""Crash-safe on-disk segment ring: the persistence substrate shared by
the tail-sampled trace store (obs.sampler) and the blackbox flight
recorder (obs.blackbox).

Layout: ``<dir>/seg-<NNNNNNNN>.jsonl``, each line one record framed as

    <crc32-hex-8> <compact-json>\\n

The crc covers the JSON bytes, so reopen-after-crash can tell a whole
record from a torn tail without trusting the filesystem: scanning a
segment stops at the first line whose frame is short, whose crc
mismatches, or whose JSON fails to parse — everything before it is
served, everything after it in THAT segment is skipped (a torn write
tears the tail, never the middle of an fsynced prefix), and every
OTHER segment still serves. Segments rotate at ``segment_bytes`` and
the oldest is unlinked past ``max_segments``, so total disk is bounded
at roughly ``segment_bytes * max_segments`` whatever the write rate.

Writes go through the ``ring.write`` failpoint (fault.failpoints) with
``writer`` + ``data``, so the torn-write chaos tests tear a segment
exactly where power loss mid-append would.

Durability is deliberately the WAL's weakest tier: records are
buffered through the OS (no fsync) — this ring holds *diagnostics*,
and the one crash mode that loses the last buffered records is also
the one a flight recorder cannot help with anyway.
"""

from __future__ import annotations

import json
import os
import re
import threading
import zlib
from typing import Iterator, Optional

from ..fault import failpoints as _fp

_SEG_RE = re.compile(r"^seg-(\d{8})\.jsonl$")

DEFAULT_SEGMENT_BYTES = 1 << 20
DEFAULT_MAX_SEGMENTS = 8


def _frame(record: dict) -> bytes:
    body = json.dumps(record, separators=(",", ":"),
                      default=str).encode()
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return b"%08x " % crc + body + b"\n"


def _unframe(line: bytes) -> Optional[dict]:
    """One framed line back to its record; None for anything torn or
    corrupt (short frame, crc mismatch, broken JSON)."""
    if len(line) < 10 or line[8:9] != b" ":
        return None
    try:
        want = int(line[:8], 16)
    except ValueError:
        return None
    body = line[9:].rstrip(b"\n")
    if (zlib.crc32(body) & 0xFFFFFFFF) != want:
        return None
    try:
        out = json.loads(body)
    except ValueError:
        return None
    return out if isinstance(out, dict) else None


class SegmentRing:
    """Bounded ring of crc-framed JSONL segments (module docstring).
    Thread-safe; every method degrades to a no-op (with counters) on
    I/O errors — a diagnostics store must never take serving down."""

    def __init__(self, dir: str,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 max_segments: int = DEFAULT_MAX_SEGMENTS):
        self.dir = dir
        self.segment_bytes = max(4 << 10, int(segment_bytes))
        self.max_segments = max(1, int(max_segments))
        self._mu = threading.Lock()
        self._file = None
        self._file_bytes = 0
        self._seq = 0
        self.written = 0   # records appended this process
        self.dropped = 0   # appends lost to I/O errors / failpoints
        self.skipped = 0   # corrupt/torn records skipped by scans
        os.makedirs(dir, exist_ok=True)
        segs = self._segments()
        if segs:
            self._seq = segs[-1][0]

    # -- write ----------------------------------------------------------------

    def append(self, record: dict) -> bool:
        """Append one record; True when it reached the OS. A failed or
        torn write closes the current segment (the torn tail is
        skipped by scans; later records open a fresh segment), so one
        bad write can never poison records after it."""
        data = _frame(record)
        with self._mu:
            try:
                f = self._open_locked(len(data))
                if _fp.ACTIVE is not None:
                    _fp.ACTIVE.hit("ring.write", writer=f, data=data)
                f.write(data)
                f.flush()
                self._file_bytes += len(data)
                self.written += 1
                return True
            except Exception:  # noqa: BLE001 - diagnostics must not raise
                self.dropped += 1
                self._close_locked()
                return False

    def _open_locked(self, need: int):
        if self._file is not None \
                and self._file_bytes + need > self.segment_bytes:
            self._close_locked()
        if self._file is None:
            self._seq += 1
            path = os.path.join(self.dir, f"seg-{self._seq:08d}.jsonl")
            self._file = open(path, "ab")
            self._file_bytes = self._file.tell()
            self._prune_locked()
        return self._file

    def _close_locked(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except Exception:  # noqa: BLE001
                pass
            self._file = None
            self._file_bytes = 0

    def _prune_locked(self) -> None:
        segs = self._segments()
        for seq, path in segs[:-self.max_segments]:
            try:
                os.unlink(path)
            except OSError:
                pass

    def close(self) -> None:
        with self._mu:
            self._close_locked()

    # -- read -----------------------------------------------------------------

    def _segments(self) -> list[tuple[int, str]]:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        out = []
        for n in names:
            m = _SEG_RE.match(n)
            if m:
                out.append((int(m.group(1)), os.path.join(self.dir, n)))
        out.sort()
        return out

    def scan(self, newest_first: bool = True) -> Iterator[dict]:
        """Every whole record on disk. A torn/corrupt line ends ITS
        segment's scan (counted in ``skipped``); other segments are
        unaffected — the reopen-skips-the-bad-segment contract."""
        with self._mu:
            # Buffered bytes must be visible to the read-side open.
            if self._file is not None:
                try:
                    self._file.flush()
                except Exception:  # noqa: BLE001
                    pass
        segs = self._segments()
        if newest_first:
            segs = segs[::-1]
        for _seq, path in segs:
            records = []
            try:
                with open(path, "rb") as f:
                    for line in f:
                        rec = _unframe(line)
                        if rec is None:
                            self.skipped += 1
                            break
                        records.append(rec)
            except OSError:
                continue
            yield from (reversed(records) if newest_first else records)

    def stats(self) -> dict:
        segs = self._segments()
        size = 0
        for _seq, path in segs:
            try:
                size += os.path.getsize(path)
            except OSError:
                pass
        return {"dir": self.dir, "segments": len(segs),
                "bytes": size, "segmentBytes": self.segment_bytes,
                "maxSegments": self.max_segments,
                "written": self.written, "dropped": self.dropped,
                "skippedCorrupt": self.skipped}
