"""SLO health: rolling latency-objective burn rates + readiness.

Three pieces, all computed from observability state that PR 3 already
collects:

- **SLOTracker** — rolling burn rates over the existing query-latency
  histogram (obs.metrics.QUERY_SECONDS). The objective is "fraction
  ``target`` of queries complete within ``objective_s``"; the burn
  rate is the classic multi-window ratio: (observed bad fraction) /
  (allowed bad fraction). 1.0 means the error budget burns exactly at
  the sustainable rate; 10x means it is gone in a tenth of the window.
  Sampled by the runtime collector's cadence; published as
  ``pilosa_slo_burn_rate_ratio{window=...}`` and in ``/status``.
- **Exemplars** — the latency histograms carry OpenMetrics exemplars
  (the trace/query id of a recent observation per bucket), rendered at
  /metrics when the scraper negotiates the OpenMetrics content type —
  the pivot from "p99 got worse" to "here is a trace id to open".
  (The mechanics live in obs.metrics; the handler records them.)
- **HealthChecker** — a real READINESS probe for ``GET /health``,
  distinct from liveness (/version answers as long as the process
  serves): holder open, gossip converged, admission not saturated,
  data directory writable. Load balancers should route on this.
"""

from __future__ import annotations

import os
import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Optional

from . import metrics as obs_metrics

# Rolling windows (seconds) the burn rate is computed over — the
# standard fast/slow pair: the short window catches an active incident,
# the long one catches slow budget bleed.
DEFAULT_WINDOWS = ((300, "5m"), (3600, "1h"))

DEFAULT_OBJECTIVE_S = 0.25
DEFAULT_TARGET = 0.99


class SLOTracker:
    """Rolling latency-objective accounting over a latency histogram.

    Keeps a bounded ring of (ts, good, total) cumulative snapshots of
    the histogram family; a burn rate over a window is computed from
    the delta between now and the oldest snapshot inside the window —
    no per-request work at all (the histogram observe the handler
    already does is the only hot-path cost).
    """

    def __init__(self, histogram: Optional[obs_metrics.Histogram] = None,
                 objective_s: float = DEFAULT_OBJECTIVE_S,
                 target: float = DEFAULT_TARGET,
                 windows=DEFAULT_WINDOWS):
        self.histogram = histogram or obs_metrics.QUERY_SECONDS
        self.objective_s = float(objective_s)
        self.target = min(max(float(target), 0.0), 0.999999)
        self.windows = tuple(windows)
        # The histogram's buckets are fixed at family creation; the
        # objective maps to the smallest bucket bound >= objective (an
        # upper bound on "good" — documented, deterministic).
        bounds = self.histogram.buckets
        i = bisect_left(bounds, self.objective_s)
        self._good_le = bounds[i] if i < len(bounds) else None
        self._mu = threading.Lock()
        # ring spans the longest window at the collector cadence; 1024
        # entries at 10 s/sample covers ~2.8 h. Seeded with the counts
        # at construction so the first window has a baseline (a server
        # constructs its tracker before serving traffic).
        self._ring: deque[tuple[float, int, int]] = deque(maxlen=1024)
        good0, total0 = self._counts()
        self._ring.append((time.time(), good0, total0))
        obs_metrics.SLO_OBJECTIVE.set(self.objective_s)

    # -- sampling ------------------------------------------------------------

    def _counts(self) -> tuple[int, int]:
        """(good, total) cumulative over every label child of the
        histogram family."""
        good = total = 0
        for _labels, child in self.histogram._label_dicts():
            counts, _sum, n = child.snapshot()
            total += n
            if self._good_le is None:
                good += n
                continue
            cum = 0
            for bound, c in zip(self.histogram.buckets, counts):
                cum += c
                if bound == self._good_le:
                    break
            good += cum
        return good, total

    def record(self) -> dict:
        """One sampling pass (runtime-collector cadence): append a
        snapshot, update the burn-rate gauges, return the /status
        block."""
        good, total = self._counts()
        now = time.time()
        with self._mu:
            ring = list(self._ring)
            self._ring.append((now, good, total))
        out = {
            "objectiveS": self.objective_s,
            "target": self.target,
            "goodTotal": good,
            "requestsTotal": total,
            "burnRates": {},
        }
        budget = 1.0 - self.target
        for window_s, label in self.windows:
            # Baseline: the newest prior snapshot at or beyond the
            # window's far edge; when none is that old yet, the oldest
            # one we have (the window is effectively shorter until it
            # fills — correct at startup).
            base = ring[0] if ring else (now, good, total)
            for ts, g, t in ring:
                if ts <= now - window_s:
                    base = (ts, g, t)
                else:
                    break
            d_total = total - base[2]
            d_bad = (total - good) - (base[2] - base[1])
            if d_total <= 0:
                burn = 0.0
            else:
                burn = (d_bad / d_total) / budget
            out["burnRates"][label] = round(burn, 4)
            obs_metrics.SLO_BURN_RATE.labels(label).set(round(burn, 4))
        return out


class TenantSLOTracker:
    """Per-tenant burn rates over the tenant-labeled latency histogram
    (``pilosa_tenant_query_duration_seconds``) — the SLOTracker
    machinery applied per label child, so "the quiet tenant's burn
    stays below threshold while an aggressor sheds" is a measurable,
    alertable statement (published as
    ``pilosa_tenant_slo_burn_rate_ratio{tenant,window}``, /status,
    /debug/tenants). Same zero-hot-path-cost contract: the handler's
    one histogram observe is the only per-request work; burn math
    runs at the runtime collector's cadence."""

    def __init__(self, histogram: Optional[obs_metrics.Histogram] = None,
                 objective_s: float = DEFAULT_OBJECTIVE_S,
                 target: float = DEFAULT_TARGET,
                 windows=DEFAULT_WINDOWS):
        self.histogram = histogram or obs_metrics.TENANT_QUERY_SECONDS
        self.objective_s = float(objective_s)
        self.target = min(max(float(target), 0.0), 0.999999)
        self.windows = tuple(windows)
        bounds = self.histogram.buckets
        i = bisect_left(bounds, self.objective_s)
        self._good_le = bounds[i] if i < len(bounds) else None
        self._mu = threading.Lock()
        # tenant -> ring of (ts, good, total); rings appear lazily as
        # tenants first serve traffic, seeded at the TRACKER's start
        # with zero counts — the tracker is built before serving, so
        # a newly-appearing tenant's whole count history genuinely
        # accumulated after this stamp and lands inside the window.
        self._t0 = time.time()
        self._rings: dict[str, deque] = {}
        self._last: dict[str, dict] = {}

    def _counts(self) -> dict[str, tuple[int, int]]:
        out: dict[str, tuple[int, int]] = {}
        for labels, child in self.histogram._label_dicts():
            tenant = labels.get("tenant", "")
            counts, _sum, n = child.snapshot()
            if self._good_le is None:
                good = n
            else:
                good = 0
                for bound, c in zip(self.histogram.buckets, counts):
                    good += c
                    if bound == self._good_le:
                        break
            prev = out.get(tenant, (0, 0))
            out[tenant] = (prev[0] + good, prev[1] + n)
        return out

    def record(self) -> dict:
        """One sampling pass: per-tenant burn rates per window, gauges
        updated, /status + /debug/tenants block returned."""
        now = time.time()
        budget = 1.0 - self.target
        out: dict[str, dict] = {}
        for tenant, (good, total) in self._counts().items():
            with self._mu:
                ring = self._rings.get(tenant)
                if ring is None:
                    ring = self._rings[tenant] = deque(maxlen=1024)
                    ring.append((self._t0, 0, 0))
                snaps = list(ring)
                ring.append((now, good, total))
            burns = {}
            for window_s, label in self.windows:
                base = snaps[0] if snaps else (now, good, total)
                for ts, g, t in snaps:
                    if ts <= now - window_s:
                        base = (ts, g, t)
                    else:
                        break
                d_total = total - base[2]
                d_bad = (total - good) - (base[2] - base[1])
                burn = 0.0 if d_total <= 0 else \
                    (d_bad / d_total) / budget
                burns[label] = round(burn, 4)
                obs_metrics.TENANT_SLO_BURN.labels(tenant, label).set(
                    round(burn, 4))
            out[tenant] = {"requestsTotal": total,
                           "goodTotal": good,
                           "burnRates": burns}
        with self._mu:
            self._last = out
        return out

    def last(self) -> dict:
        """The most recent record() pass (for /debug/tenants — no
        recompute on the request path)."""
        with self._mu:
            return dict(self._last)


class HealthChecker:
    """Readiness checks behind ``GET /health`` — every check is cheap
    (the disk probe is throttled) so a load balancer can poll at 1 Hz
    without showing up in the profiles."""

    DISK_PROBE_INTERVAL_S = 5.0

    def __init__(self, holder=None, cluster=None, admission=None,
                 host: str = ""):
        self.holder = holder
        self.cluster = cluster
        self.admission = admission
        self.host = host
        self._disk_mu = threading.Lock()
        self._disk_last = 0.0
        self._disk_ok = True
        self._disk_err = ""

    def check(self) -> tuple[bool, dict]:
        """(ready, checks) — ready only when every check passes."""
        checks: dict[str, dict] = {}

        holder = self.holder
        if holder is None:
            checks["holder"] = {"ok": False, "detail": "no holder"}
        else:
            # Holder.open() creates the data dir and sets .path; a
            # closed/never-opened holder has no usable directory.
            path = getattr(holder, "path", "") or ""
            ok = bool(path) and os.path.isdir(path)
            checks["holder"] = {"ok": ok,
                                "detail": path or "not open"}

        if (self.cluster is not None and len(self.cluster.nodes) > 1
                and getattr(self.cluster, "node_set", None)
                is not None):
            try:
                states = self.cluster.node_states()
            except Exception as e:  # noqa: BLE001 - membership mid-close
                states = {}
                checks["gossip"] = {"ok": False, "detail": str(e)[:120]}
            if "gossip" not in checks:
                down = sorted(h for h, s in states.items() if s != "UP")
                checks["gossip"] = {
                    "ok": not down,
                    "detail": (f"down: {','.join(down)}" if down
                               else f"{len(states)} nodes UP")}
        elif self.cluster is not None and len(self.cluster.nodes) > 1:
            # Static/HTTP membership has no failure detector —
            # node_states() would report every peer DOWN and a load
            # balancer routing on /health would drain a healthy
            # cluster. Convergence simply isn't observable here.
            checks["gossip"] = {
                "ok": True,
                "detail": "static membership (no failure detector)"}
        else:
            checks["gossip"] = {"ok": True, "detail": "single node"}

        if self.admission is not None:
            snap = self.admission.snapshot()
            queued = sum((snap.get("queued") or {}).values())
            depth = snap.get("queueDepth", 0) or 1
            # Saturated = the queue is full (the next arrival would be
            # rejected); a busy-but-absorbing queue stays ready.
            ok = queued < depth
            checks["admission"] = {
                "ok": ok,
                "detail": f"queued={queued}/{depth}"
                          f" inFlight={snap.get('inFlight', 0)}"}
        else:
            checks["admission"] = {"ok": True, "detail": "unlimited"}

        checks["disk"] = self._check_disk()

        # Storage integrity (storage.integrity): quarantined fragments
        # are a degraded-but-serving condition when replicas exist
        # (reads fail over while the repairer re-streams); with no
        # peers to fail over to, the touched slices genuinely cannot
        # answer, so readiness reflects it.
        q = (getattr(self.holder, "quarantine", None)
             if self.holder is not None else None)
        if q is not None:
            n = len(q)
            # Failover needs a REPLICA of the quarantined data, not
            # just another node: replica_n=1 means no copy exists
            # anywhere else regardless of cluster size.
            replicated = (self.cluster is not None
                          and len(self.cluster.nodes) > 1
                          and getattr(self.cluster, "replica_n", 1)
                          > 1)
            checks["storage"] = {
                "ok": n == 0 or replicated,
                "detail": ("clean" if n == 0 else
                           f"{n} fragments quarantined"
                           + ("" if replicated
                              else " (no replica to fail over to)"))}

        # Disk-full degradation (fault.diskfull): while ENOSPC holds
        # the node write-unready, /health SAYS so — but the node is
        # not "down": reads keep serving, so the block carries its
        # own key instead of failing the disk probe (which may well
        # still succeed for tiny probe files on a nearly-full disk).
        from ..fault import diskfull as _diskfull
        wr = _diskfull.write_ready()
        checks["writeReady"] = {
            "ok": wr,
            "detail": ("writes accepted" if wr else
                       "write-unready after ENOSPC (writes answer"
                       " 507, reads serving)")}

        ready = all(c["ok"] for c in checks.values())
        return ready, checks

    def _check_disk(self) -> dict:
        path = getattr(self.holder, "path", "") or "" \
            if self.holder is not None else ""
        if not path:
            return {"ok": False, "detail": "no data dir"}
        now = time.monotonic()
        with self._disk_mu:
            if now - self._disk_last < self.DISK_PROBE_INTERVAL_S:
                return {"ok": self._disk_ok,
                        "detail": self._disk_err or path}
            self._disk_last = now
        probe = os.path.join(path, ".health-probe")
        try:
            with open(probe, "w") as f:
                f.write(str(time.time()))
            os.remove(probe)
            ok, err = True, ""
        except OSError as e:
            ok, err = False, str(e)[:120]
        with self._disk_mu:
            self._disk_ok, self._disk_err = ok, err
        return {"ok": ok, "detail": err or path}
