"""Cluster-wide metric federation: aggregate at query time, not at
write time.

The Monarch split this PR adopts keeps high-resolution history at each
leaf (obs.history) and answers fleet questions by fanning the question
out when it is asked. This module is the fan-out half:

- ``GET /metrics/cluster`` — the coordinator scrapes every peer's
  ``/metrics`` in bounded parallel over the existing pooled client
  (breaker-aware: a dead peer's open circuit fails the leg fast
  instead of paying the timeout again; per-peer deadline otherwise),
  parses the 0.0.4 exposition, and merges: **counters sum** across
  nodes, **histograms merge** (bucket/sum/count sums per label set),
  **gauges stay per-node** labeled ``{node="host"}`` (summing HBM
  residency across nodes answers no question anyone asks).
- ``GET /debug/cluster`` — the same fan-out over each node's local
  debug rollup (build info, epoch, breaker states, SLO burn, WAL
  flusher health, resize phase — the blackbox state, fleet-wide).
- ``GET /debug/metrics/history?scope=cluster`` — per-node history
  series with a ``node`` attribution on every series.

Partial semantics follow the ``?partial=1`` contract from the fault
PR: without it, an unreachable peer fails the whole request (503);
with it, the merge serves what answered and names the missing nodes in
``X-Pilosa-Partial-Nodes``.
"""

from __future__ import annotations

import re
import threading
from typing import Callable, Optional

from . import metrics as obs_metrics

DEFAULT_PEER_TIMEOUT_S = 2.0
DEFAULT_FANOUT = 8

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\})?"
    r"\s+(NaN|[-+]?(?:[0-9.eE+-]+|Inf))"
    r"(?:\s+[0-9.]+)?$")
_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_SUFFIX_RE = re.compile(r"_(bucket|sum|count)$")


def unescape_label_value(v: str) -> str:
    """Inverse of the exposition renderer's label-value escaping
    (``\\\\`` → ``\\``, ``\\"`` → ``"``, ``\\n`` → newline)."""
    out = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            if nxt == "n":
                out.append("\n")
            elif nxt in ("\\", '"'):
                out.append(nxt)
            else:  # unknown escape: keep both chars (promtext rule)
                out.append(c)
                out.append(nxt)
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


def parse_exposition(text: str) -> dict:
    """Prometheus text format 0.0.4 → ``{family: {"type": t, "help":
    h, "samples": [(sample_name, labels_dict, float_value)]}}`` with
    label values UNESCAPED back to their true strings. Unparseable
    lines are skipped (a federating coordinator must tolerate a peer
    one version ahead), unknown families default to untyped."""
    families: dict = {}

    def fam_for(name: str) -> dict:
        base = _SUFFIX_RE.sub("", name)
        fam = families.get(base)
        if fam is None and base != name:
            fam = families.get(name)
            base = name if fam is not None else base
        if fam is None:
            fam = families.setdefault(
                base, {"type": "untyped", "help": "", "samples": []})
        return fam

    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            try:
                name, typ = line[len("# TYPE "):].split()
            except ValueError:
                continue
            families.setdefault(
                name, {"type": typ, "help": "", "samples": []})[
                "type"] = typ
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            # Unescape back to the true string — render_merged
            # re-escapes, and a still-escaped stored form would
            # double-escape on every federation hop.
            families.setdefault(
                name, {"type": "untyped", "help": "", "samples": []})[
                "help"] = unescape_label_value(help_text)
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, rawlabels, rawvalue = m.group(1), m.group(2), m.group(3)
        labels = {k: unescape_label_value(v)
                  for k, v in _LABEL_RE.findall(rawlabels or "")}
        try:
            value = float(rawvalue)
        except ValueError:
            continue
        fam_for(name)["samples"].append((name, labels, value))
    return families


# -- merging -------------------------------------------------------------------


def merge_node_families(per_node: dict[str, dict]) -> dict:
    """{node: parse_exposition(...)} → one merged family dict.
    Counters and histogram components sum per identical label set;
    gauges (and untyped) get a ``node`` label per source node."""
    merged: dict = {}
    for node in sorted(per_node):
        for name, fam in per_node[node].items():
            out = merged.setdefault(
                name, {"type": fam["type"], "help": fam.get("help", ""),
                       "samples": {}})
            if fam["type"] != "untyped":
                out["type"] = fam["type"]
            summed = out["type"] in ("counter", "histogram")
            for sample_name, labels, value in fam["samples"]:
                if summed:
                    key = (sample_name,
                           tuple(sorted(labels.items())))
                    cur = out["samples"].get(key)
                    out["samples"][key] = (value if cur is None
                                           else cur + value)
                else:
                    key = (sample_name, tuple(
                        sorted({**labels, "node": node}.items())))
                    out["samples"][key] = value
    return merged


def render_merged(merged: dict) -> str:
    """Merged families back to 0.0.4 exposition text."""
    lines = []
    for name in sorted(merged):
        fam = merged[name]
        if fam.get("help"):
            lines.append(f"# HELP {name} "
                         + obs_metrics.escape_help(fam["help"]))
        lines.append(f"# TYPE {name} {fam['type']}")
        for (sample_name, labels), value in sorted(
                fam["samples"].items(), key=lambda kv: kv[0]):
            if labels:
                lab = ",".join(
                    f'{k}="{obs_metrics.escape_label_value(str(v))}"'
                    for k, v in labels)
                lines.append(f"{sample_name}{{{lab}}}"
                             f" {obs_metrics.format_value(value)}")
            else:
                lines.append(
                    f"{sample_name} {obs_metrics.format_value(value)}")
    return "\n".join(lines) + "\n"


class PeerUnavailable(Exception):
    """One federation leg failed (circuit open, timeout, bad status);
    carries the peer host for the partial-marking contract."""

    def __init__(self, host: str, why: str):
        super().__init__(f"{host}: {why}")
        self.host = host
        self.why = why


class Federator:
    """The coordinator side: bounded parallel fan-out of one scrape
    function over the peer set, with the local node answered
    in-process (no self-scrape over HTTP)."""

    def __init__(self, host: str, cluster=None,
                 client_for: Optional[Callable] = None,
                 peer_timeout_s: float = DEFAULT_PEER_TIMEOUT_S,
                 fanout: int = DEFAULT_FANOUT):
        self.host = host
        self.cluster = cluster
        self.client_for = client_for
        self.peer_timeout_s = float(peer_timeout_s)
        self.fanout = max(1, int(fanout))

    def peers(self) -> list[str]:
        if self.cluster is None:
            return []
        return [n.host for n in self.cluster.nodes
                if n.host != self.host]

    def fan_out(self, fetch: Callable[[str], object],
                local: Callable[[], object]
                ) -> tuple[dict[str, object], list[str]]:
        """``{host: result}`` for every reachable node (the local
        result computed in-process) plus the list of unreachable
        hosts. Each remote leg is bounded by the per-peer timeout and
        the target's circuit breaker; legs run on a bounded pool so a
        large fleet cannot explode thread count."""
        from concurrent.futures import ThreadPoolExecutor
        peers = self.peers()
        results: dict[str, object] = {}
        missing: list[str] = []
        mu = threading.Lock()

        def leg(host: str) -> None:
            try:
                got = fetch(host)
            except Exception as e:  # noqa: BLE001 - leg outcome recorded
                obs_metrics.FEDERATION_SCRAPES.labels(
                    host, "error").inc()
                with mu:
                    missing.append(host)
                _ = e
                return
            obs_metrics.FEDERATION_SCRAPES.labels(host, "ok").inc()
            with mu:
                results[host] = got

        if peers:
            with ThreadPoolExecutor(
                    max_workers=min(self.fanout, len(peers))) as tp:
                list(tp.map(leg, peers))
        try:
            results[self.host] = local()
        except Exception:  # noqa: BLE001 - local side best-effort too
            missing.append(self.host)
        return results, sorted(missing)
