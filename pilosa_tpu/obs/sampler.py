"""Tail-based trace sampling: every query buffers spans, the
interesting ones persist.

PR 3's tracing was ask-first: off by default, per-request ``?trace=1``
— so the deadline-exceeded leg, the breaker-trip failover, the 429
burst all finished before anyone thought to trace them, and the
64-entry in-memory ring forgot the few that were caught. This module
inverts the decision to *query end*, when the outcome is known:

- every query gets the (near-free) span buffer — the handler attaches
  a Trace whenever a TailSampler is wired, and cluster legs always
  carry ``X-Pilosa-Trace: 1`` so the coordinator's keep decision
  captures the stitched remote side too;
- at the end, ``decide()`` keeps the trace if it was **slow** (dynamic
  threshold derived from the PR-3 latency histogram's p99), **errored**,
  **deadline**-exceeded, **cancelled**, answered **partial**, was
  **shed** (a 429, or its lane rejected arrivals in the recent
  window), touched an open **breaker** (failover/circuit-open flags on
  the context) or an armed **failpoint**, or hit the 1-in-N **head**
  sample;
- kept traces (spans + stitched remote spans + the PR-4 cost ledger
  roll-up) go to the in-memory ring AND a size-bounded on-disk segment
  ring (obs.diskring) under the holder data dir that survives
  restarts, browsable via ``/debug/traces?source=disk&reason=...``.

The keep-reason catalogue (docs/OBSERVABILITY.md):
``slow``, ``error``, ``deadline``, ``cancelled``, ``partial``,
``corruption`` (the query detected on-disk corruption or failed over
a quarantined fragment — storage integrity subsystem), ``shed``,
``breaker``, ``failpoint``, ``head``, ``requested`` (the explicit
[trace] enabled / ?trace=1 / coordinator-asked paths), ``watchdog``
(in-flight traces force-kept on a stall trip), and ``anomaly``
(force-kept by a regression-sentinel finding, obs.sentinel).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..errors import QueryCancelledError, QueryDeadlineError
from . import metrics as obs_metrics
from .diskring import SegmentRing
from .trace import Span, Trace

# Keep reasons, in decision order (the first matching wins).
# ``watchdog``, ``anomaly``, and ``backup`` are force-keeps claimed
# mid-flight (a stall trip / a sentinel finding / a backup-window
# error), not end-of-query decisions.
REASONS = ("deadline", "cancelled", "error", "shed", "partial",
           "corruption", "breaker", "failpoint", "slow", "head",
           "requested", "watchdog", "anomaly", "backup")

DEFAULT_HEAD_N = 1000
DEFAULT_SLOW_FLOOR_S = 0.1
# Below this many histogram observations the p99 estimate is noise;
# use a conservative fixed threshold instead.
_MIN_OBSERVATIONS = 100
_COLD_SLOW_S = 0.5
_THRESHOLD_TTL_S = 5.0


class TailSampler:
    """End-of-query keep decision + disk persistence for kept traces.

    ``admission`` (sched.AdmissionController) feeds the shed-lane
    signal; the slow threshold derives from ``histogram``
    (obs.metrics.QUERY_SECONDS by default) so "slow" tracks the
    workload instead of a hand-tuned constant."""

    def __init__(self, disk: Optional[SegmentRing] = None,
                 head_n: int = DEFAULT_HEAD_N,
                 slow_floor_s: float = DEFAULT_SLOW_FLOOR_S,
                 admission=None, histogram=None,
                 quantile: float = 0.99,
                 shed_window_s: float = 10.0):
        self.disk = disk
        self.head_n = max(0, int(head_n))
        self.slow_floor_s = float(slow_floor_s)
        self.admission = admission
        self.histogram = histogram or obs_metrics.QUERY_SECONDS
        self.quantile = min(max(float(quantile), 0.5), 0.9999)
        self.shed_window_s = float(shed_window_s)
        self._mu = threading.Lock()
        self._seen = 0                      # head-sample counter
        self._threshold = (0.0, _COLD_SLOW_S)  # (computed_at, value)

    # -- dynamic slow threshold ----------------------------------------------

    def slow_threshold_s(self) -> float:
        """max(histogram p-quantile bucket bound, floor), recomputed
        at most every few seconds — the "slow" that tracks the live
        latency distribution instead of a constant."""
        now = time.monotonic()
        with self._mu:
            at, value = self._threshold
            if now - at < _THRESHOLD_TTL_S:
                return value
            # Refresh outside the lock would race harmlessly; keeping
            # it here keeps the math single-writer.
            value = self._compute_threshold()
            self._threshold = (now, value)
            return value

    def _compute_threshold(self) -> float:
        counts = [0] * (len(self.histogram.buckets) + 1)
        total = 0
        try:
            for _labels, child in self.histogram._label_dicts():
                cs, _sum, n = child.snapshot()
                total += n
                for i, c in enumerate(cs):
                    counts[i] += c
        except Exception:  # noqa: BLE001 - sampling must not raise
            return max(self.slow_floor_s, _COLD_SLOW_S)
        if total < _MIN_OBSERVATIONS:
            return max(self.slow_floor_s, _COLD_SLOW_S)
        want = total * self.quantile
        cum = 0
        bound = self.histogram.buckets[-1]
        for i, c in enumerate(counts[:-1]):
            cum += c
            if cum >= want:
                bound = self.histogram.buckets[i]
                break
        return max(bound, self.slow_floor_s)

    # -- the keep decision ----------------------------------------------------

    def decide(self, ctx, err: Optional[BaseException] = None,
               status: int = 200,
               partial: bool = False) -> Optional[str]:
        """The keep reason for this finished query, or None. Pure
        decision — persistence is ``keep()``."""
        if isinstance(err, QueryDeadlineError) or status == 504:
            return "deadline"
        if isinstance(err, QueryCancelledError) or status == 409:
            return "cancelled"
        if status == 429:
            return "shed"
        if err is not None or status >= 500:
            return "error"
        flags = getattr(ctx, "flags", None) or ()
        if partial or "partial" in flags:
            return "partial"
        if "corruption" in flags:
            # The query detected on-disk corruption or failed over a
            # quarantined fragment (storage integrity subsystem).
            return "corruption"
        if "breaker" in flags or "failover" in flags:
            return "breaker"
        if "failpoint" in flags:
            return "failpoint"
        if (self.admission is not None
                and self.admission.recent_rejection(
                    getattr(ctx, "lane", ""), self.shed_window_s)):
            return "shed"
        if ctx is not None and ctx.elapsed() >= self.slow_threshold_s():
            return "slow"
        if self.head_n:
            with self._mu:
                self._seen += 1
                # First query, then every head_n-th — exact at every
                # head_n including 1 (keep all healthy queries).
                if (self._seen - 1) % self.head_n == 0:
                    return "head"
        return None

    # -- persistence -----------------------------------------------------------

    def persist(self, trace: Trace, reason: str, ctx=None) -> None:
        """One kept trace to the disk ring (no-op without one)."""
        if self.disk is None:
            return
        record = trace_record(trace, reason, ctx=ctx)
        ok = self.disk.append(record)
        obs_metrics.TRACE_DISK_RECORDS.labels(
            "written" if ok else "dropped").inc()


def trace_record(trace: Trace, reason: str, ctx=None) -> dict:
    """The disk form of one kept trace: the summary plus the full
    compact span rows, the cost roll-up, and the stage timings."""
    out = trace.summary()
    out["reason"] = reason
    out["keptAt"] = time.time()
    out["spans"] = [s.to_json() for s in trace.spans()]
    if ctx is not None:
        cost = getattr(ctx, "cost", None)
        if cost is not None:
            try:
                out["cost"] = cost.summary()
            except Exception:  # noqa: BLE001 - advisory
                pass
        stages = getattr(ctx, "stages", None)
        if stages:
            out["stages"] = {k: round(v, 6) for k, v in
                             dict(stages).items()}
        out["index"] = getattr(ctx, "index", "")
        out["lane"] = getattr(ctx, "lane", "")
    return out


def record_to_trace(record: dict) -> Trace:
    """Rebuild a Trace from its disk record (for the Chrome/spans
    export paths of ``/debug/traces/{id}?source=disk``)."""
    t = Trace(str(record.get("id", "")),
              node=str(record.get("node", "")),
              pql=str(record.get("pql", "")))
    t.started = float(record.get("startedAt", t.started))
    t.keep_reason = str(record.get("reason", ""))
    for row in record.get("spans") or []:
        try:
            t._spans.append(Span.from_json(row))
        except (IndexError, TypeError, ValueError):
            continue
    return t


def record_summary(record: dict) -> dict:
    """The listing form (everything but the span rows)."""
    return {k: v for k, v in record.items() if k != "spans"}
