"""Workload capture: the recorded-traffic plane (docs/OBSERVABILITY.md).

Every handler-served query (and import ack) can append one compact
record — arrival timestamps, PQL, index/tenant/lane, effective request
options, query id, plan fingerprint, status, latency, and a canonical
64-bit digest of the normalized result JSON — into a crc-framed on-disk
segment ring under ``<data>/capture/`` (the obs.diskring discipline:
bounded bytes, torn tails skipped on reopen, diagnostics never raise).
A captured stream is replayable: ``benchmarks/replay.py`` re-issues it
against any cluster preserving inter-arrival gaps, and the shadow-diff
mode compares digests between a baseline and a candidate endpoint.

Record wire format (compact keys; one JSON object per ring line)::

    seq    per-node capture id (monotonic int; the ?since= cursor)
    t      arrival wall-clock (time.time, float seconds)
    mono   arrival monotonic stamp (gap reconstruction within a node)
    kind   "query" | "import"
    pql    the query text (possibly redacted), "" for imports
    index  index name         tenant  scheduling principal
    lane   read|write|admin   qid     the X-Pilosa-Query-Id
    plan   plan fingerprint ("" when unplanned)
    status HTTP status        latS    service latency (seconds)
    digest canonical result digest ("" on errors / non-200)
    opts   effective request options ({"timeout": s, "partial": true})
    node   host that served it (merged multi-node exports disambiguate)
    bits/slice  (imports only) accepted bit count and target slice

Digest canonicalization contract: the digest is a 64-bit BLAKE2b over
the *normalized* result JSON (server.codec.query_response_json shapes)
serialized with sorted keys and no whitespace. Normalization sorts
TopN pair lists by (count desc, id asc) — ties in count are broken by
ascending id — so two servers that order equal-count pairs differently
still agree. Floats are round-tripped through repr via json; bools,
ints, and bitmap JSON pass through structurally.

Sampling modes (``[capture] mode``): ``off`` is a nop-cost path (one
attribute read per request, proven by the overhead guard in
benchmarks/suite.py config_replay); ``sampled`` (the default) records
EVERY write and import — replay must reproduce state — plus 1-in-N
reads; ``full`` records everything. Redaction (``redact``): for the
listed tenants ("*" = all), PQL string/numeric literals are replaced
with ``?`` before the record is written, so a captured ring can leave
the trust boundary without leaking row ids or attribute strings (the
plan-fingerprint normalization rule, applied to the raw text).
"""

from __future__ import annotations

import hashlib
import json
import re
import threading
import time
from typing import Optional

from . import metrics as obs_metrics
from .diskring import SegmentRing

MODES = ("off", "sampled", "full")

DIGEST_HEADER = "X-Pilosa-Result-Digest"

DEFAULT_SAMPLE_N = 16
DEFAULT_SEGMENT_BYTES = 1 << 20
DEFAULT_SEGMENTS = 8


# -- canonical result digest --------------------------------------------------


def _is_pair_list(v) -> bool:
    return (isinstance(v, list) and bool(v)
            and all(isinstance(e, dict) and "id" in e and "count" in e
                    for e in v))


def normalize_result(v):
    """The canonical form the digest hashes: TopN pair lists sorted by
    (count desc, id asc), containers recursed, scalars unchanged."""
    if _is_pair_list(v):
        return [{"id": e["id"], "count": e["count"]}
                for e in sorted(v, key=lambda e: (-e["count"], e["id"]))]
    if isinstance(v, dict):
        return {k: normalize_result(x) for k, x in v.items()}
    if isinstance(v, list):
        return [normalize_result(x) for x in v]
    return v


def result_digest(results_json) -> str:
    """Stable 64-bit digest (16 hex chars) over normalized result
    JSON — the value of ``X-Pilosa-Result-Digest`` and the shadow-diff
    comparison key. Input is the ``results`` list of
    codec.query_response_json (already plain JSON values)."""
    body = json.dumps(normalize_result(results_json), sort_keys=True,
                      separators=(",", ":"), default=str)
    return hashlib.blake2b(body.encode(), digest_size=8).hexdigest()


# -- PQL redaction ------------------------------------------------------------

# String literals first (so digits inside them vanish with the
# string), then bare numeric literals. Frame/view/field *names* are
# argument values too ("frame=f" / frame="f") — the capture contract
# redacts quoted strings wholesale: a redacted record stays
# fingerprintable (the plan fingerprint rides alongside) but carries
# no tenant data.
_STR_RE = re.compile(r'"(?:[^"\\]|\\.)*"')
_NUM_RE = re.compile(r"(?<![\w?])\d+(?:\.\d+)?\b")


def redact_pql(pql: str) -> str:
    return _NUM_RE.sub("?", _STR_RE.sub('"?"', pql))


# -- the store ----------------------------------------------------------------


class CaptureStore:
    """Per-node capture ring + sampling/redaction policy. Thread-safe;
    append failures count (metrics + ring.dropped), never raise."""

    def __init__(self, dir: str, mode: str = "sampled",
                 sample_n: int = DEFAULT_SAMPLE_N,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 max_segments: int = DEFAULT_SEGMENTS,
                 redact_tenants: Optional[set] = None,
                 node: str = ""):
        if mode not in MODES:
            raise ValueError(f"capture mode {mode!r} not in {MODES}")
        self.mode = mode
        self.sample_n = max(1, int(sample_n))
        self.redact_tenants = frozenset(redact_tenants or ())
        self.node = node
        self.ring = SegmentRing(dir, segment_bytes=segment_bytes,
                                max_segments=max_segments)
        self._mu = threading.Lock()
        self._reads_seen = 0
        # Resume the per-node cursor past what survives on disk, so
        # ?since= cursors from before a restart stay monotonic.
        seq = 0
        for rec in self.ring.scan(newest_first=True):
            seq = int(rec.get("seq", 0))
            break
        self._seq = seq

    # The one check the handler pays per request when capture is off:
    # a bool attribute read (the nop-cost disabled path the overhead
    # guard proves).
    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    def should_capture(self, lane: str) -> bool:
        """Sampling decision: writes/imports always (replay must
        reproduce state), reads 1-in-``sample_n`` when sampled."""
        if self.mode == "off":
            return False
        if self.mode == "full" or lane != "read":
            return True
        with self._mu:
            self._reads_seen += 1
            return self._reads_seen % self.sample_n == 1 \
                or self.sample_n == 1

    def redacts(self, tenant: str) -> bool:
        return ("*" in self.redact_tenants
                or tenant in self.redact_tenants)

    def add(self, kind: str, pql: str, index: str, tenant: str,
            lane: str, qid: str, status: int, latency_s: float,
            digest: str = "", plan: str = "",
            opts: Optional[dict] = None, wall: Optional[float] = None,
            mono: Optional[float] = None, **extra) -> int:
        """Append one record; returns its capture id (seq), or 0 when
        the append was dropped."""
        if self.redacts(tenant) and pql:
            pql = redact_pql(pql)
        with self._mu:
            self._seq += 1
            seq = self._seq
        rec = {"seq": seq,
               "t": time.time() if wall is None else wall,
               "mono": time.monotonic() if mono is None else mono,
               "kind": kind, "pql": pql, "index": index,
               "tenant": tenant, "lane": lane, "qid": qid,
               "plan": plan, "status": int(status),
               "latS": round(latency_s, 6), "digest": digest,
               "node": self.node}
        if opts:
            rec["opts"] = opts
        rec.update(extra)
        if self.ring.append(rec):
            obs_metrics.CAPTURE_RECORDS.labels(kind).inc()
            obs_metrics.CAPTURE_BYTES.labels(kind).inc(
                len(json.dumps(rec, separators=(",", ":"),
                               default=str)))
            return seq
        obs_metrics.CAPTURE_DROPPED.labels("io").inc()
        return 0

    # -- export ---------------------------------------------------------------

    def export(self, since: int = 0, limit: int = 500) -> list[dict]:
        """Records with seq > ``since``, oldest first, at most
        ``limit`` — the /debug/capture/records page. The cursor for
        the next page is the last record's seq."""
        limit = max(1, min(int(limit), 10000))
        out = []
        for rec in self.ring.scan(newest_first=False):
            if int(rec.get("seq", 0)) > since:
                out.append(rec)
                if len(out) >= limit:
                    break
        return out

    def status(self) -> dict:
        s = self.ring.stats()
        return {"mode": self.mode, "sampleN": self.sample_n,
                "redactTenants": sorted(self.redact_tenants),
                "seq": self._seq, "node": self.node,
                "budgetBytes": s["segmentBytes"] * s["maxSegments"],
                "ring": s}

    def close(self) -> None:
        self.ring.close()


# -- replay-side helpers (benchmarks/replay.py, tests) ------------------------


def merge_streams(streams: list[list[dict]]) -> list[dict]:
    """Merge per-node exports into one replayable stream ordered by
    arrival wall-clock (cross-node ``mono`` stamps are not comparable;
    ``t`` is the only shared axis). Stable on ties: (t, node, seq)."""
    merged = [r for s in streams for r in s]
    merged.sort(key=lambda r: (r.get("t", 0.0), r.get("node", ""),
                               r.get("seq", 0)))
    return merged


def arrival_offsets(records: list[dict]) -> list[float]:
    """Seconds offset of each record from the first, preserving the
    recorded inter-arrival gaps. Single-node streams use the monotonic
    stamps (immune to wall-clock steps); merged streams fall back to
    wall time."""
    if not records:
        return []
    nodes = {r.get("node", "") for r in records}
    key = "mono" if len(nodes) == 1 and all(
        "mono" in r for r in records) else "t"
    base = records[0].get(key, 0.0)
    return [max(0.0, r.get(key, base) - base) for r in records]
