"""Observability subsystem: metrics, distributed tracing, and the
runtime collector.

- ``obs.metrics`` — a Prometheus-style registry (labeled counters,
  gauges, log-bucketed histograms) rendered at ``GET /metrics``; every
  metric family the server emits is declared there at import, and a
  ``RegistryStatsClient`` bridge feeds legacy ``StatsClient`` call
  sites into the same registry so no call site changes twice.
- ``obs.trace`` — per-query distributed traces: spans opened at parse,
  admission, executor fan-out, per-leg RPCs, mesh dispatch, and XLA
  compile; remote legs return their spans piggybacked on the internal
  query response and the coordinator stitches them under one trace id
  (the query id riding ``X-Pilosa-Query-Id``). A bounded per-node ring
  serves ``GET /debug/traces`` and Chrome trace-event export.
- ``obs.accounting`` — per-query cost ledgers (EXPLAIN ANALYZE for
  PQL): container ops by operand-kind pair, words scanned, bits
  written, device programs/bytes, compile ms, RPC bytes per peer;
  remote legs piggyback their ledger on ``X-Pilosa-Cost`` and the
  coordinator stitches a per-node cost tree (``?profile=1``,
  ``X-Pilosa-Stats``, /debug/queries, the slow log, span args).
- ``obs.profile`` — the always-on low-Hz continuous wall profiler:
  query-id-tagged folded stacks in a bounded ring, served as
  speedscope-loadable collapsed-stack text at ``/debug/pprof/flame``.
- ``obs.slo`` — rolling latency-objective burn rates over the query
  histograms, OpenMetrics exemplars carrying trace ids, and the
  ``GET /health`` readiness checks.
- ``obs.runtime`` — a background collector sampling holder/cache/
  residency sizes, thread activity, and the XLA compile-cache
  counters (parallel.mesh.compile_stats) into gauges and ``/status``.
- ``obs.sampler`` — always-on tail-sampled tracing: every query gets
  the span buffer, the keep decision runs at query end (slow/errored/
  deadline/cancelled/partial/shed/breaker/failpoint/head), and kept
  traces persist to a crash-safe on-disk segment ring
  (``obs.diskring``) that survives restarts.
- ``obs.blackbox`` — the flight recorder: periodic whole-system
  snapshots into a bounded disk ring, dumped in full on SIGTERM,
  fatal thread death, a watchdog trip, or the API.
- ``obs.watchdog`` — the stall watchdog: wedged WAL flusher, legs
  stuck past deadline grace, gossip silence, non-draining admission
  queue → ``pilosa_watchdog_trips_total{cause}``, force-kept
  in-flight traces, a blackbox dump.
- ``obs.history`` — the on-disk metric history: every registry
  family sampled on the collector cadence into bounded
  multi-resolution rings (counters as rates, histograms as
  p50/p99/rate series) persisted crash-safe under the data dir;
  served at ``GET /debug/metrics/history``.
- ``obs.federate`` — cluster-wide aggregation at query time:
  ``GET /metrics/cluster`` (counters sum, histograms merge, gauges
  per-node) and the ``GET /debug/cluster`` fleet rollup, over a
  bounded breaker-aware parallel scrape with the ``?partial=1``
  degradation contract.
- ``obs.sentinel`` — the regression sentinel: robust-z rules over
  the live history plus committed-envelope rules against
  benchmarks/MANIFEST.json; a finding raises
  ``pilosa_sentinel_findings_total{metric,direction}``, force-keeps
  in-flight traces (reason ``anomaly``), and lands a blackbox
  snapshot naming the regressed metric.

See docs/OBSERVABILITY.md for the metric name reference, the trace
and cost wire contracts, and the perfetto/speedscope how-tos.
"""

from .metrics import (RegistryStatsClient, Registry,  # noqa: F401
                      default_registry)
from .trace import Tracer, get_tracer, span_current  # noqa: F401
