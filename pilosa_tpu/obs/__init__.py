"""Observability subsystem: metrics, distributed tracing, and the
runtime collector.

- ``obs.metrics`` — a Prometheus-style registry (labeled counters,
  gauges, log-bucketed histograms) rendered at ``GET /metrics``; every
  metric family the server emits is declared there at import, and a
  ``RegistryStatsClient`` bridge feeds legacy ``StatsClient`` call
  sites into the same registry so no call site changes twice.
- ``obs.trace`` — per-query distributed traces: spans opened at parse,
  admission, executor fan-out, per-leg RPCs, mesh dispatch, and XLA
  compile; remote legs return their spans piggybacked on the internal
  query response and the coordinator stitches them under one trace id
  (the query id riding ``X-Pilosa-Query-Id``). A bounded per-node ring
  serves ``GET /debug/traces`` and Chrome trace-event export.
- ``obs.runtime`` — a background collector sampling holder/cache/
  residency sizes, thread activity, and the XLA compile-cache
  counters (parallel.mesh.compile_stats) into gauges and ``/status``.

See docs/OBSERVABILITY.md for the metric name reference, the trace
header contract, and the perfetto how-to.
"""

from .metrics import (RegistryStatsClient, Registry,  # noqa: F401
                      default_registry)
from .trace import Tracer, get_tracer, span_current  # noqa: F401
