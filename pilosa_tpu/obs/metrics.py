"""Prometheus-style metrics: registry, typed families, text exposition.

The reference exposes only expvar counters (stats.go + handler.go's
/debug/vars); production serving needs real types — monotonic counters,
point-in-time gauges, and log-bucketed latency histograms, all with
bounded label sets — rendered in the Prometheus text exposition format
at ``GET /metrics``.

Design rules:

- **One registry, declared at import.** Every metric family the server
  emits is a module-level constant in THIS file, created against
  ``default_registry()`` — so the naming-convention sweep test can walk
  the full emitted-name set by importing the module, and a grep for a
  metric name has exactly one place to land.
- **Naming convention** (enforced at registration):
  ``pilosa_<subsystem>_<noun>_<unit>`` — lowercase snake case, at least
  three segments after ``pilosa``; counters end in ``_total``.
- **The legacy StatsClient feeds the same registry.**
  ``RegistryStatsClient`` adapts the ``StatsClient`` interface
  (utils/stats.py) onto registry metrics under the ``pilosa_stats_*``
  namespace, so existing call sites (holder gauges, fragment setN,
  slow-query counters) surface at /metrics without changing twice —
  the server composes it into a MultiStatsClient next to the expvar
  and statsd clients.
- **Cheap hot path.** A labeled child lookup is one dict get under a
  lock; histogram observe is a bisect into a static bucket list. No
  allocation after the first observation of a label set.
"""

from __future__ import annotations

import math
import re
import threading
import time
from bisect import bisect_left
from typing import Iterable, Optional

from ..utils.stats import StatsClient

# pilosa_<subsystem>_<noun>_<unit>: at least three snake segments after
# the pilosa prefix (subsystem, noun, unit); plain lowercase/digits.
# The one sanctioned exception is the OpenMetrics *info* idiom —
# ``pilosa_build_info``-style constant-1 gauges whose labels carry the
# values — which keeps the ecosystem-conventional name.
NAME_RE = re.compile(r"^pilosa(_[a-z][a-z0-9]*){3,}$"
                     r"|^pilosa(_[a-z][a-z0-9]*)+_info$")


def validate_name(name: str, type_: str) -> None:
    if not NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} outside the"
            f" pilosa_<subsystem>_<noun>_<unit> convention")
    if type_ == "counter" and not name.endswith("_total"):
        raise ValueError(f"counter {name!r} must end in _total")


def log_buckets(lo: float = 0.001, hi: float = 64.0
                ) -> tuple[float, ...]:
    """Power-of-two log-spaced bucket bounds [lo, hi] — 1 ms to 64 s
    by default, which covers the tunnel sync floor (~65 ms), warm
    queries (<10 ms), and the multi-second cold-compile tail that
    VERDICT weak #2 asks us to see."""
    out = []
    b = lo
    while b < hi * 1.0001:
        out.append(round(b, 9))
        b *= 2.0
    return tuple(out)


# Per-family bound on distinct label sets: per-peer families
# (pilosa_cluster_rpc_seconds{peer}, pilosa_cluster_peer_health{peer})
# otherwise grow without bound as the cluster scales, and an unbounded
# registry is both a memory leak and a scrape-size incident. Past the
# cap, NEW label sets collapse into one ``_overflow_`` bucket and
# pilosa_metrics_label_overflow_total{family} counts the collapses.
DEFAULT_MAX_LABEL_SETS = 256
_OVERFLOW_LABEL = "_overflow_"
_OVERFLOW_COUNTER_NAME = "pilosa_metrics_label_overflow_total"


class _Family:
    """Shared base: a named family with optional label names and a
    dict of label-tuple → child state."""

    type = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: Iterable[str] = (),
                 max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        validate_name(name, self.type)
        self.name = name
        self.help = help
        self.labelnames = tuple(labels)
        self.max_label_sets = max(1, int(max_label_sets))
        self._mu = threading.Lock()
        self._children: dict[tuple, object] = {}

    def _child(self, labelvalues: tuple):
        overflowed = False
        with self._mu:
            child = self._children.get(labelvalues)
            if child is None:
                if (self.labelnames
                        and len(self._children) >= self.max_label_sets
                        and self.name != _OVERFLOW_COUNTER_NAME):
                    # Cardinality guard: the cap is on NEW label sets;
                    # existing children (and the overflow bucket
                    # itself) keep resolving normally.
                    overflowed = True
                    labelvalues = ((_OVERFLOW_LABEL,)
                                   * len(self.labelnames))
                    child = self._children.get(labelvalues)
                if child is None:
                    child = self._children[labelvalues] = \
                        self._new_child()
        if overflowed:
            LABEL_OVERFLOW.labels(self.name).inc()
        return child

    def labels(self, *values, **kv):
        if kv:
            values = tuple(str(kv.get(ln, "")) for ln in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: got {len(values)} label values for"
                f" {self.labelnames}")
        return self._child(values)

    def _default(self):
        if self.labelnames:
            raise ValueError(f"{self.name}: labels required")
        return self._child(())

    def samples(self) -> list[tuple[str, dict, float]]:
        """(suffix, labels, value) triples for rendering."""
        raise NotImplementedError

    def samples_ex(self):
        """(suffix, labels, value, exemplar) — the OpenMetrics form;
        only histograms attach exemplars (they override this)."""
        return [(s, l, v, None) for s, l, v in self.samples()]

    def _label_dicts(self) -> list[tuple[dict, object]]:
        with self._mu:
            items = list(self._children.items())
        return [(dict(zip(self.labelnames, lv)), ch) for lv, ch in items]


class _CounterChild:
    __slots__ = ("_v", "_mu")

    def __init__(self):
        self._v = 0.0
        self._mu = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._mu:
            self._v += n

    def set_total(self, total: float) -> None:
        """Sync from an external monotonic source (e.g. the XLA
        compile-cache counters, which live in parallel.mesh and are
        mirrored here by the runtime collector)."""
        with self._mu:
            if total > self._v:
                self._v = total

    @property
    def value(self) -> float:
        return self._v


class Counter(_Family):
    type = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    def set_total(self, total: float) -> None:
        self._default().set_total(total)

    @property
    def value(self) -> float:
        return self._default().value

    def samples(self):
        return [("", labels, ch.value)
                for labels, ch in self._label_dicts()]


class _GaugeChild:
    __slots__ = ("_v",)

    def __init__(self):
        self._v = 0.0

    def set(self, v: float) -> None:
        self._v = v

    def inc(self, n: float = 1.0) -> None:
        self._v += n

    @property
    def value(self) -> float:
        return self._v


class Gauge(_Family):
    type = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, v: float) -> None:
        self._default().set(v)

    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    @property
    def value(self) -> float:
        return self._default().value

    def samples(self):
        return [("", labels, ch.value)
                for labels, ch in self._label_dicts()]


class _HistogramChild:
    __slots__ = ("_bounds", "_counts", "_sum", "_count", "_mu",
                 "_exemplars")

    def __init__(self, bounds: tuple[float, ...]):
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # + the +Inf bucket
        self._sum = 0.0
        self._count = 0
        self._mu = threading.Lock()
        # Per-bucket last exemplar: (labels, value, unix_ts) — the
        # OpenMetrics hook carrying a trace/query id next to the
        # latency observation that landed in that bucket.
        self._exemplars: dict[int, tuple[dict, float, float]] = {}

    def observe(self, v: float,
                exemplar: Optional[dict] = None) -> None:
        i = bisect_left(self._bounds, v)
        with self._mu:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if exemplar:
                self._exemplars[i] = (exemplar, v, time.time())

    def snapshot(self) -> tuple[list[int], float, int]:
        with self._mu:
            return list(self._counts), self._sum, self._count

    def exemplars(self) -> dict[int, tuple[dict, float, float]]:
        with self._mu:
            return dict(self._exemplars)


class Histogram(_Family):
    type = "histogram"

    def __init__(self, name: str, help: str = "",
                 labels: Iterable[str] = (),
                 buckets: Optional[tuple[float, ...]] = None,
                 max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        self.buckets = tuple(buckets) if buckets else log_buckets()
        super().__init__(name, help, labels,
                         max_label_sets=max_label_sets)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float, exemplar: Optional[dict] = None) -> None:
        self._default().observe(v, exemplar=exemplar)

    def samples(self):
        return [s[:3] for s in self.samples_ex()]

    def samples_ex(self):
        """(suffix, labels, value, exemplar-or-None) — exemplars ride
        bucket samples only (the OpenMetrics rule)."""
        out = []
        for labels, ch in self._label_dicts():
            counts, total, n = ch.snapshot()
            exemplars = ch.exemplars()
            cum = 0
            for i, (bound, c) in enumerate(zip(self.buckets, counts)):
                cum += c
                out.append(("_bucket", {**labels, "le": _fmt(bound)},
                            cum, exemplars.get(i)))
            out.append(("_bucket", {**labels, "le": "+Inf"}, n,
                        exemplars.get(len(self.buckets))))
            out.append(("_sum", labels, total, None))
            out.append(("_count", labels, n, None))
        return out


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == int(v):
        return str(int(v))
    return repr(v)


def _escape(v: str) -> str:
    """Label-VALUE escaping per the exposition spec: backslash, double
    quote, and line feed (in that order — escaping the backslash last
    would corrupt the other two escapes)."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n",
                                                               "\\n")


def _escape_help(v: str) -> str:
    """HELP-text escaping: ONLY backslash and line feed. ``\\"`` is
    not a valid escape sequence in help text — emitting it (the old
    shared escaper did) renders a spec-invalid line that strict
    OpenMetrics parsers reject."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


# Public faces for the federation renderer (obs.federate) and tests:
# one escaping implementation, every exposition writer.
escape_label_value = _escape
escape_help = _escape_help


class Registry:
    """Named metric families + the text-exposition renderer."""

    def __init__(self):
        self._mu = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _register(self, fam: _Family) -> _Family:
        with self._mu:
            existing = self._families.get(fam.name)
            if existing is not None:
                if (type(existing) is not type(fam)
                        or existing.labelnames != fam.labelnames):
                    raise ValueError(
                        f"metric {fam.name} re-registered with a"
                        f" different shape")
                return existing
            self._families[fam.name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: Iterable[str] = (),
                max_label_sets: int = DEFAULT_MAX_LABEL_SETS
                ) -> Counter:
        return self._register(Counter(
            name, help, labels, max_label_sets=max_label_sets))

    def gauge(self, name: str, help: str = "",
              labels: Iterable[str] = (),
              max_label_sets: int = DEFAULT_MAX_LABEL_SETS) -> Gauge:
        return self._register(Gauge(
            name, help, labels, max_label_sets=max_label_sets))

    def histogram(self, name: str, help: str = "",
                  labels: Iterable[str] = (),
                  buckets: Optional[tuple[float, ...]] = None,
                  max_label_sets: int = DEFAULT_MAX_LABEL_SETS
                  ) -> Histogram:
        return self._register(Histogram(
            name, help, labels, buckets,
            max_label_sets=max_label_sets))

    def families(self) -> dict[str, _Family]:
        with self._mu:
            return dict(self._families)

    def render(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition format 0.0.4, or (with
        ``openmetrics=True``) OpenMetrics 1.0: counter families are
        declared under their ``_total``-stripped name, histogram bucket
        samples carry their exemplar (``# {trace_id="..."} v ts``), and
        the body terminates with ``# EOF``."""
        lines = []
        for name in sorted(self.families()):
            fam = self._families[name]
            om_name = name
            if (openmetrics and fam.type == "counter"
                    and name.endswith("_total")):
                om_name = name[: -len("_total")]
            if fam.help:
                lines.append(
                    f"# HELP {om_name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {om_name} {fam.type}")
            for suffix, labels, value, exemplar in fam.samples_ex():
                if labels:
                    lab = ",".join(
                        f'{k}="{_escape(str(v))}"'
                        for k, v in labels.items())
                    line = f"{name}{suffix}{{{lab}}} {_fnum(value)}"
                else:
                    line = f"{name}{suffix} {_fnum(value)}"
                if openmetrics and exemplar is not None:
                    ex_labels, ex_v, ex_ts = exemplar
                    exl = ",".join(
                        f'{k}="{_escape(str(v))}"'
                        for k, v in ex_labels.items())
                    line += (f" # {{{exl}}} {_fnum_om(ex_v)}"
                             f" {_fnum_om(ex_ts)}")
                lines.append(line)
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"


def _fnum(v: float) -> str:
    if isinstance(v, int) or v == int(v):
        return str(int(v))
    return repr(v)


format_value = _fnum  # the federation renderer's sample formatting


def _fnum_om(v: float) -> str:
    """Exemplar value/timestamp: keep floats readable (OpenMetrics
    allows either form; repr of a perf_counter float is noise)."""
    if v == int(v):
        return str(int(v))
    return f"{v:.6f}".rstrip("0").rstrip(".")


_DEFAULT = Registry()


def default_registry() -> Registry:
    return _DEFAULT


# -- the emitted metric set ---------------------------------------------------
# Declared here, at import, against the default registry: the naming
# sweep test walks this set, and every instrumented layer imports its
# family from here.

QUERY_SECONDS = _DEFAULT.histogram(
    "pilosa_query_duration_seconds",
    "End-to-end /query latency on this node",
    labels=("call", "lane", "status"))
QUERIES_TOTAL = _DEFAULT.counter(
    "pilosa_query_requests_total",
    "Queries served, by outcome",
    labels=("call", "lane", "status"))
IMPORT_BITS = _DEFAULT.counter(
    "pilosa_import_bits_total",
    "Bits (or field values) accepted by /import endpoints",
    labels=("kind",))
ADMISSION_REJECTED = _DEFAULT.counter(
    "pilosa_admission_rejections_total",
    "Requests answered 429 by the admission controller",
    labels=("lane",))
ADMISSION_QUEUE_DEPTH = _DEFAULT.gauge(
    "pilosa_admission_queue_depth",
    "Queries waiting in the admission queue",
    labels=("lane",))
ADMISSION_IN_FLIGHT = _DEFAULT.gauge(
    "pilosa_admission_inflight_queries",
    "Queries currently holding an execution slot")
RPC_SECONDS = _DEFAULT.histogram(
    "pilosa_cluster_rpc_seconds",
    "Cluster fan-out RPC latency, by peer host",
    labels=("peer", "kind"))
ROARING_OPS = _DEFAULT.counter(
    "pilosa_roaring_container_ops_total",
    "Roaring container set-algebra operations, by op and operand"
    " container kinds",
    labels=("op", "kind"))
ROARING_CONTAINERS = _DEFAULT.gauge(
    "pilosa_roaring_containers_live",
    "Live roaring containers across open fragments, by kind"
    " (array/bitmap/run) — the container-mix shift to runs as a gauge",
    labels=("kind",))
ROARING_CONTAINER_BYTES = _DEFAULT.gauge(
    "pilosa_roaring_container_bytes",
    "Resident bytes held by live roaring containers, by kind — run"
    " containers shrinking this is the HBM-headroom payoff ramp",
    labels=("kind",))
COMPILE_HITS = _DEFAULT.counter(
    "pilosa_compile_cache_hits_total",
    "XLA program-cache lookups served without building a program")
COMPILE_MISSES = _DEFAULT.counter(
    "pilosa_compile_cache_misses_total",
    "XLA program-cache misses (a program was built)")
COMPILE_SECONDS = _DEFAULT.counter(
    "pilosa_compile_cache_build_seconds_total",
    "Wall seconds spent in first-call XLA trace+compile")
COMPILE_PROGRAMS = _DEFAULT.gauge(
    "pilosa_compile_cache_programs_live",
    "Compiled XLA programs held live by the in-process builder caches"
    " (the shape-stable catalogue keeps this bucket-bound as slice"
    " count grows)")
SLOW_QUERIES = _DEFAULT.counter(
    "pilosa_query_slow_total",
    "Queries slower than the configured slow-query threshold")
RUNTIME_THREADS = _DEFAULT.gauge(
    "pilosa_runtime_threads_live",
    "Live interpreter threads", labels=("state",))
HOLDER_FRAGMENTS = _DEFAULT.gauge(
    "pilosa_holder_fragments_open",
    "Open fragments across all indexes")
HOLDER_CACHE_ENTRIES = _DEFAULT.gauge(
    "pilosa_holder_cache_entries",
    "Row-cache entries across all open fragments")
RESIDENCY_BYTES = _DEFAULT.gauge(
    "pilosa_residency_hbm_bytes",
    "Device residency cache HBM", labels=("kind",))
TRACES_KEPT = _DEFAULT.counter(
    "pilosa_trace_kept_total",
    "Traces retained by the tail sampler, by keep reason (slow/error/"
    "deadline/cancelled/partial/corruption/shed/breaker/failpoint/"
    "head/requested/watchdog/anomaly — docs/OBSERVABILITY.md"
    " keep-reason catalogue)",
    labels=("reason",))
TRACE_DISK_RECORDS = _DEFAULT.counter(
    "pilosa_trace_disk_records_total",
    "Kept traces persisted to the on-disk segment ring, by outcome"
    " (written / dropped)",
    labels=("outcome",))
LABEL_OVERFLOW = _DEFAULT.counter(
    "pilosa_metrics_label_overflow_total",
    "New label sets collapsed into a family's _overflow_ bucket by the"
    " per-family cardinality cap, by family",
    labels=("family",))
BUILD_INFO = _DEFAULT.gauge(
    "pilosa_build_info",
    "Constant 1; the labels carry the build identity (version, python,"
    " jax, backend) — the OpenMetrics info idiom",
    labels=("version", "python", "jax", "backend"))
WATCHDOG_TRIPS = _DEFAULT.counter(
    "pilosa_watchdog_trips_total",
    "Stall-watchdog trips, by cause (wal_flusher / stuck_query /"
    " gossip_silence / admission_stall)",
    labels=("cause",))
BLACKBOX_SNAPSHOTS = _DEFAULT.counter(
    "pilosa_blackbox_snapshots_total",
    "Flight-recorder whole-system snapshots taken, by trigger",
    labels=("trigger",))
BLACKBOX_DUMPS = _DEFAULT.counter(
    "pilosa_blackbox_dumps_total",
    "Flight-recorder full dumps written, by cause (sigterm / fatal /"
    " watchdog / api)",
    labels=("cause",))
IMPORT_STAGE_SECONDS = _DEFAULT.histogram(
    "pilosa_import_stage_seconds",
    "Wire-import handler stage timings: decode (wire to arrays),"
    " apply (fragment mutation), snapshot (storage rewrite) — the"
    " decode-vs-apply serialization recorded as a metric",
    labels=("stage",))
SLO_BURN_RATE = _DEFAULT.gauge(
    "pilosa_slo_burn_rate_ratio",
    "Latency-objective error-budget burn rate over a rolling window"
    " (1.0 = budget burns exactly at the sustainable rate)",
    labels=("window",))
SLO_OBJECTIVE = _DEFAULT.gauge(
    "pilosa_slo_latency_objective_seconds",
    "The configured latency objective the burn rate is computed"
    " against")
PROFILE_SAMPLES = _DEFAULT.counter(
    "pilosa_profile_samples_total",
    "Continuous-profiler sampling ticks taken")
PEER_HEALTH = _DEFAULT.gauge(
    "pilosa_cluster_peer_health",
    "Blended per-peer health score in [0, 1]: EWMA of RPC outcomes"
    " scaled by gossip liveness (fault subsystem)",
    labels=("peer",))
BREAKER_STATE = _DEFAULT.gauge(
    "pilosa_fault_breaker_state",
    "Per-peer circuit-breaker state: 0=closed, 1=half-open, 2=open",
    labels=("peer",))
BREAKER_TRANSITIONS = _DEFAULT.counter(
    "pilosa_fault_breaker_transitions_total",
    "Circuit-breaker state transitions, by peer and target state",
    labels=("peer", "state"))
FAILPOINT_TRIGGERS = _DEFAULT.counter(
    "pilosa_fault_failpoint_triggers_total",
    "Armed failpoint injections fired, by site",
    labels=("site",))
FAILOVER_SLICES = _DEFAULT.counter(
    "pilosa_cluster_failover_slices_total",
    "Slices re-mapped onto surviving replicas after a node leg"
    " failed mid-query, by failed peer",
    labels=("peer",))

# -- storage integrity (storage.integrity / storage.scrub;
#    docs/FAULT_TOLERANCE.md) ------------------------------------------------
STORAGE_SCRUB_BLOCKS = _DEFAULT.counter(
    "pilosa_storage_scrub_blocks_total",
    "Container blocks whose crc32 was re-verified against the snapshot"
    " footer, by source (scrub = the background pass, read = the lazy"
    " first-read check after an open)",
    labels=("source",))
STORAGE_CORRUPTION = _DEFAULT.counter(
    "pilosa_storage_corruption_detected_total",
    "On-disk corruption detections (checksum mismatch or unparseable"
    " snapshot), by detection site (open / read / scrub)",
    labels=("site",))
STORAGE_QUARANTINED = _DEFAULT.counter(
    "pilosa_storage_quarantined_fragments_total",
    "Fragments newly quarantined after a corruption detection (reads"
    " fail over to a replica; writes keep WAL-buffering)")
STORAGE_QUARANTINED_LIVE = _DEFAULT.gauge(
    "pilosa_storage_quarantined_fragments_live",
    "Fragments currently quarantined on this node (awaiting replica"
    " repair, or unrepairable with no healthy replica)")
STORAGE_REPAIRS = _DEFAULT.counter(
    "pilosa_storage_repairs_total",
    "Automatic replica re-stream repairs of quarantined fragments, by"
    " outcome (repaired / failed / no_replica)",
    labels=("outcome",))

# -- tiered storage (tier working-set manager; docs/STORAGE.md) ---------------
TIER_FRAGMENTS = _DEFAULT.gauge(
    "pilosa_tier_fragments_resident",
    "Fragments per residency tier on this node (hot = fully mmap-"
    "resident with caches, cold = metadata-only with unfaulted"
    " container blocks, blob = bytes live only in the blob store)",
    labels=("tier",))
TIER_BYTES = _DEFAULT.gauge(
    "pilosa_tier_bytes_resident",
    "Data bytes per residency tier on this node — resident counts"
    " hot fragments plus the faulted blocks of cold ones; the"
    " watermark eviction loop works against this gauge's resident"
    " label",
    labels=("tier",))
TIER_FAULTS = _DEFAULT.counter(
    "pilosa_tier_block_faults_total",
    "Container blocks faulted into residency on first read of a cold"
    " fragment, by outcome (ok / corrupt — a corrupt fault"
    " quarantines exactly like a failed lazy read verify)",
    labels=("outcome",))
TIER_DEMOTIONS = _DEFAULT.counter(
    "pilosa_tier_demotions_total",
    "Fragment demotions out of the resident set, by reason"
    " (watermark = eviction pressure, idle = idle-age sweep,"
    " blob = pushed to the blob tier)",
    labels=("reason",))
TIER_PROMOTIONS = _DEFAULT.counter(
    "pilosa_tier_promotions_total",
    "Fragment promotions back toward residency, by trigger (read ="
    " a query faulted it, prefetch = the history-driven prefetcher,"
    " write = a mutation landed on a cold fragment)",
    labels=("trigger",))
TIER_PREFETCH = _DEFAULT.counter(
    "pilosa_tier_prefetch_total",
    "History-driven prefetch decisions, by outcome (promoted /"
    " skipped_busy / skipped_budget / error)",
    labels=("outcome",))
TIER_FETCHES = _DEFAULT.counter(
    "pilosa_tier_blob_transfers_total",
    "Blob-tier transfers, by direction (push / fetch) and outcome"
    " (ok / error / corrupt — corrupt means the fetched bytes failed"
    " footer verification at admission and were discarded)",
    labels=("direction", "outcome"))
TIER_FAULT_SECONDS = _DEFAULT.histogram(
    "pilosa_tier_fault_wait_seconds",
    "Latency of faulting the blocks one read touched on a cold"
    " fragment (crc verification included; blob fetch included when"
    " the fragment had left local disk)")
TIER_TOUCH = _DEFAULT.counter(
    "pilosa_tier_fragment_touches_total",
    "Read-path touches per (tenant, index, slice) — sampled into the"
    " on-disk metric history, where yesterday's rates drive the"
    " prefetcher's prediction of tomorrow's hot set",
    labels=("tenant", "index", "slice"), max_label_sets=512)

# -- multi-tenant QoS (sched.tenants; docs/SCHEDULING.md) ---------------------
# Tenant-labeled families ride an explicit per-family cardinality cap:
# past _TENANT_LABEL_SETS distinct tenants, new ones collapse into the
# shared ``_overflow_`` bucket (the PR-10 overflow machinery) — a
# tenant-per-customer deployment cannot blow up the exposition.
_TENANT_LABEL_SETS = 64
TENANT_QUERY_SECONDS = _DEFAULT.histogram(
    "pilosa_tenant_query_duration_seconds",
    "End-to-end /query latency on this node, by tenant — the"
    " per-tenant SLO burn rates are computed over this family",
    labels=("tenant",), max_label_sets=_TENANT_LABEL_SETS)
TENANT_QUERIES = _DEFAULT.counter(
    "pilosa_tenant_query_requests_total",
    "Queries served, by tenant and status — 429s and cost-policy"
    " 402s included, so shed/kill rates are derivable per tenant",
    labels=("tenant", "status"), max_label_sets=4 * _TENANT_LABEL_SETS)
TENANT_COST_UNITS = _DEFAULT.counter(
    "pilosa_tenant_cost_units_total",
    "Chargeback roll-up of the per-query cost ledgers, by tenant and"
    " resource (container_ops / words_scanned / bits_written /"
    " device_bytes / rpc_bytes / queue_wait_ms / wall_us)",
    labels=("tenant", "resource"),
    max_label_sets=8 * _TENANT_LABEL_SETS)
TENANT_SHED = _DEFAULT.counter(
    "pilosa_tenant_admission_rejections_total",
    "Per-tenant 429s: arrivals past the tenant's own queue quota"
    " (lane-scoped) — only the offending tenant sheds",
    labels=("tenant", "lane"), max_label_sets=4 * _TENANT_LABEL_SETS)
TENANT_KILLS = _DEFAULT.counter(
    "pilosa_tenant_cost_kills_total",
    "Queries killed cluster-wide by the per-tenant cost policy"
    " (ceiling breach at a stage boundary), by tenant",
    labels=("tenant",), max_label_sets=_TENANT_LABEL_SETS)
TENANT_INFLIGHT = _DEFAULT.gauge(
    "pilosa_tenant_inflight_queries",
    "Execution slots currently held, by tenant (scrape-time refresh"
    " from the admission controller)",
    labels=("tenant",), max_label_sets=_TENANT_LABEL_SETS)
TENANT_PENALTY = _DEFAULT.gauge(
    "pilosa_tenant_penalty_score",
    "Decaying penalty-box score, by tenant: each cost-policy kill"
    " adds 1, halving every penalty half-life; the effective stride"
    " weight is demoted by 2^-score until the score decays away",
    labels=("tenant",), max_label_sets=_TENANT_LABEL_SETS)
TENANT_CACHE_BYTES = _DEFAULT.gauge(
    "pilosa_tenant_cache_bytes",
    "Result-cache residency held per tenant (result-residency bits/8"
    " + coordinator cluster-cache entries) under the per-tenant"
    " cache quota",
    labels=("tenant",), max_label_sets=_TENANT_LABEL_SETS)
TENANT_SLO_BURN = _DEFAULT.gauge(
    "pilosa_tenant_slo_burn_rate_ratio",
    "Per-tenant latency-objective error-budget burn rate over a"
    " rolling window (1.0 = sustainable) — the quiet tenant's"
    " isolation guarantee is stated against this",
    labels=("tenant", "window"),
    max_label_sets=4 * _TENANT_LABEL_SETS)

# -- disk-full graceful degradation (fault.diskfull) --------------------------
STORAGE_ENOSPC = _DEFAULT.counter(
    "pilosa_storage_enospc_events_total",
    "ENOSPC hits at durable-write sites (wal.append /"
    " snapshot.write), by site — each flips the node write-unready"
    " until a probe write succeeds",
    labels=("site",))
STORAGE_WRITE_READY = _DEFAULT.gauge(
    "pilosa_storage_write_ready",
    "1 while durable writes are accepted; 0 while the node is"
    " write-unready after ENOSPC (writes answer 507, reads keep"
    " serving, auto-recovers on a successful probe write)")
HEDGED_REQUESTS = _DEFAULT.counter(
    "pilosa_cluster_hedged_requests_total",
    "Hedged-read outcomes: fired (second leg launched), primary_won,"
    " hedge_won",
    labels=("outcome",))
PARTIAL_RESULTS = _DEFAULT.counter(
    "pilosa_query_partial_results_total",
    "Queries answered degraded (?partial=1) with at least one"
    " unreachable slice skipped")
WAL_GROUP_BATCH_SIZE = _DEFAULT.histogram(
    "pilosa_wal_group_commit_batch_size",
    "Op records covered by one WAL group-commit leader flush — the"
    " syscall/fsync amortization factor of the write path",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096,
             16384, 65536))
WAL_GROUP_FLUSH_SECONDS = _DEFAULT.histogram(
    "pilosa_wal_group_commit_flush_seconds",
    "Wall seconds one WAL group-commit leader flush took (write +"
    " fsync per policy)")
WAL_FSYNCS = _DEFAULT.counter(
    "pilosa_wal_fsync_calls_total",
    "fsync() calls issued by WAL group-commit leader flushes — the"
    " denominator the group-commit amortization is measured against")
IMPORT_PIPELINE_DEPTH = _DEFAULT.gauge(
    "pilosa_import_pipeline_depth",
    "Wire-import blocks currently in their apply stage across all"
    " fragments — >1 means decode of later blocks is overlapping"
    " earlier applies (the pipelined import path)")
GENERATION_UPDATES = _DEFAULT.counter(
    "pilosa_cluster_generation_updates_total",
    "Per-slice generation-token entries applied to the coordinator"
    " generation map, by source peer (X-Pilosa-Generations headers"
    " and /generations probes)",
    labels=("peer",))
RESULT_CACHE_HITS = _DEFAULT.counter(
    "pilosa_executor_result_cache_hits_total",
    "Materialized-bitmap result-residency cache hits (a repeated"
    " Union/Intersect/Difference chain served without a re-fold)")
RESULT_CACHE_MISSES = _DEFAULT.counter(
    "pilosa_executor_result_cache_misses_total",
    "Result-residency lookups that had to fold (cacheable key, no"
    " live entry)")
RESULT_CACHE_EVICTIONS = _DEFAULT.counter(
    "pilosa_executor_result_cache_evictions_total",
    "Result-residency entries evicted by the entry/bit bounds")
CLUSTER_CACHE_REQUESTS = _DEFAULT.counter(
    "pilosa_executor_cluster_cache_requests_total",
    "Coordinator hot-query result-cache lookups, by outcome: hit"
    " (every generation token validated), miss (no entry or"
    " unvalidatable), invalidated (a token mismatched — a replica"
    " took a write since the entry was cached)",
    labels=("outcome",))
TOPN_PUSHDOWN = _DEFAULT.counter(
    "pilosa_executor_topn_pushdown_total",
    "Distributed TopN pushdown outcomes: merged (per-node partials"
    " merged per the two-phase semantics) or fallback (pushdown"
    " failed; the fan-out path answered)",
    labels=("outcome",))
RESIZE_STATE = _DEFAULT.gauge(
    "pilosa_cluster_resize_state",
    "Elastic-resize state on this node: 1 on the current phase label"
    " (idle / preparing / streaming / migrating / flipping / draining /"
    " finalizing / done / aborted), 0 elsewhere — the cluster_ prefix"
    " carries the naming convention's subsystem segment"
    " (docs/CLUSTER_RESIZE.md)",
    labels=("phase",))
RESIZE_SLICES_MOVED = _DEFAULT.counter(
    "pilosa_resize_slices_moved_total",
    "Moving (index, slice) groups whose fragments finished streaming"
    " to their new owner during an elastic resize")
RESIZE_STREAM_BYTES = _DEFAULT.counter(
    "pilosa_resize_stream_bytes_total",
    "Position bytes pushed source→target by the resize fragment"
    " streamer (the migration wire cost — run-shaped fragments ride"
    " their compact container form)")
RESIZE_DOUBLE_READS = _DEFAULT.counter(
    "pilosa_cluster_resize_double_reads_total",
    "Moving-slice double-read legs during a resize, by winner: source"
    " (old owner answered — the authoritative pre-flip copy) or"
    " target (old side failed; the new owner's post-flip answer won"
    " with the newest generation tokens)",
    labels=("winner",))
HISTORY_SAMPLES = _DEFAULT.counter(
    "pilosa_history_samples_total",
    "Metric-history sampling passes over the registry (obs.history —"
    " one pass per runtime-collector tick)")
HISTORY_SERIES_LIVE = _DEFAULT.gauge(
    "pilosa_history_series_live",
    "Series held in the on-disk metric history's in-memory rings"
    " (bounded by the per-process series cap)")
HISTORY_SERIES_DROPPED = _DEFAULT.counter(
    "pilosa_history_series_dropped_total",
    "New series the metric history refused past its series cap — a"
    " nonzero value means some families' label growth outran the"
    " retention budget")
HISTORY_DISK_RECORDS = _DEFAULT.counter(
    "pilosa_history_disk_records_total",
    "Metric-history tick records persisted to the per-resolution"
    " segment rings, by outcome (written / dropped)",
    labels=("outcome",))
FEDERATION_SCRAPES = _DEFAULT.counter(
    "pilosa_federation_scrapes_total",
    "Cluster-federation fan-out legs (/metrics/cluster,"
    " /debug/cluster, history scope=cluster), by peer and outcome —"
    " error legs are the partial-result denominator",
    labels=("peer", "outcome"))
SENTINEL_FINDINGS = _DEFAULT.counter(
    "pilosa_sentinel_findings_total",
    "Regression-sentinel findings raised, by watched metric and"
    " direction (up = regressed slower/hotter, down = cliff): a"
    " robust-z anomaly against the trailing baseline or a breach of"
    " the committed MANIFEST envelope (obs.sentinel;"
    " docs/OBSERVABILITY.md rule catalogue)",
    labels=("metric", "direction"))
SENTINEL_ACTIVE = _DEFAULT.gauge(
    "pilosa_sentinel_findings_active",
    "1 while a sentinel finding's condition still holds on the most"
    " recent evaluation, 0 once it recovers, by watched metric and"
    " direction",
    labels=("metric", "direction"))
SENTINEL_CHECKS = _DEFAULT.counter(
    "pilosa_sentinel_checks_total",
    "Regression-sentinel evaluation passes (every rule, every pass)")

# -- query planner (pilosa_tpu/plan; docs/OBSERVABILITY.md EXPLAIN) -----------
PLANNER_DECISIONS = _DEFAULT.counter(
    "pilosa_planner_decisions_total",
    "Planner decisions taken, by outcome (planned / reordered /"
    " short_circuit / cse / placement) — every read query lands at"
    " least one 'planned'",
    labels=("outcome",))
PLANNER_MISESTIMATE = _DEFAULT.histogram(
    "pilosa_planner_misestimation_ratio",
    "Actual/estimated cardinality ratio per measured plan node"
    " ((actual+1)/(est+1)): 1.0 = perfect, the sentinel's"
    " planner_misestimate rule fires on a sustained p99 drift",
    buckets=(0.015625, 0.03125, 0.0625, 0.125, 0.25, 0.5, 1.0, 2.0,
             4.0, 8.0, 16.0, 32.0, 64.0))
PLANNER_SUBRESULT_EVENTS = _DEFAULT.counter(
    "pilosa_planner_subresult_cache_events_total",
    "Generation-token-keyed interior-node subresult cache events"
    " (hit / miss / store / evict) — the cross-query CSE plane",
    labels=("event",))
PLANNER_PLAN_SECONDS = _DEFAULT.histogram(
    "pilosa_planner_plan_seconds",
    "Wall seconds spent planning one read query (estimation +"
    " rewrite) — the overhead-guard numerator, before execution",
    buckets=(0.00001, 0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1,
             0.5, 1.0))

# -- workload capture (obs.capture; docs/OBSERVABILITY.md) --------------------
CAPTURE_RECORDS = _DEFAULT.counter(
    "pilosa_capture_records_total",
    "Workload-capture records appended to the on-disk capture ring,"
    " by kind (query / import)",
    labels=("kind",))
CAPTURE_DROPPED = _DEFAULT.counter(
    "pilosa_capture_dropped_total",
    "Capture records lost, by reason (io = the ring append failed)",
    labels=("reason",))
CAPTURE_BYTES = _DEFAULT.counter(
    "pilosa_capture_bytes_total",
    "Framed record bytes appended to the capture ring, by kind",
    labels=("kind",))

# -- disaster recovery (pilosa_tpu.backup; docs/DISASTER_RECOVERY.md) ---------
BACKUP_STATE = _DEFAULT.gauge(
    "pilosa_backup_state_info",
    "One-hot backup coordinator phase (idle / scan / push / manifest /"
    " done / aborted / failed) on the coordinating node",
    labels=("phase",))
BACKUP_OBJECTS = _DEFAULT.counter(
    "pilosa_backup_objects_total",
    "Archive objects handled by backups, by outcome (pushed = written,"
    " skipped = block-diff dedupe hit an existing object)",
    labels=("outcome",))
BACKUP_BYTES = _DEFAULT.counter(
    "pilosa_backup_bytes_total",
    "Archive bytes moved, by direction (push = backup, fetch ="
    " restore/verify)",
    labels=("direction",))
BACKUP_FRAGMENTS = _DEFAULT.counter(
    "pilosa_backup_fragments_total",
    "Fragments processed by backup/restore, by outcome (backed_up /"
    " restored / corrupt / error)",
    labels=("outcome",))
BACKUP_WAL_RECORDS = _DEFAULT.counter(
    "pilosa_backup_wal_records_total",
    "Committed WAL op records handed to the continuous archiver")
BACKUP_WAL_SEGMENTS = _DEFAULT.counter(
    "pilosa_backup_wal_segments_total",
    "WAL segments flushed to the archive store")
BACKUP_ERRORS = _DEFAULT.counter(
    "pilosa_backup_errors_total",
    "Backup-plane failures, by site (push / wal / restore / gc)",
    labels=("site",))


# -- legacy StatsClient bridge ------------------------------------------------

_CAMEL_RE = re.compile(r"(?<=[a-z0-9])(?=[A-Z])")
_SAN_RE = re.compile(r"[^a-z0-9_]")


def _snake(name: str) -> str:
    s = _SAN_RE.sub("_", _CAMEL_RE.sub("_", name).lower()).strip("_")
    return re.sub(r"__+", "_", s) or "unnamed"


class RegistryStatsClient(StatsClient):
    """StatsClient adapter onto a metrics Registry: legacy call sites
    (``stats.count("setN")``, holder gauges, slow-query counters) land
    in the ``pilosa_stats_*`` namespace so /metrics sees them without a
    second instrumentation pass. Tag-scoped children carry the joined
    tag string as one ``tags`` label (bounded: tags are per-index /
    per-frame scopes, not per-query values)."""

    def __init__(self, registry: Optional[Registry] = None,
                 _tags: str = ""):
        self.registry = registry or default_registry()
        self._tags = _tags
        self._cache: dict[tuple[str, str], object] = {}

    def with_tags(self, *tags: str) -> "RegistryStatsClient":
        joined = ",".join(filter(None, [self._tags, *sorted(tags)]))
        child = RegistryStatsClient(self.registry, joined)
        return child

    def _metric(self, kind: str, name: str):
        key = (kind, name)
        m = self._cache.get(key)
        if m is not None:
            return m
        snake = _snake(name)
        if kind == "count":
            fam = self.registry.counter(
                f"pilosa_stats_{snake}_total", labels=("tags",))
        elif kind == "gauge":
            fam = self.registry.gauge(
                f"pilosa_stats_{snake}_value", labels=("tags",))
        else:  # histogram / timing: seconds
            if snake.endswith("_ns"):
                snake = snake[:-3]
            if not snake.endswith("_seconds"):
                snake += "_seconds"
            fam = self.registry.histogram(
                f"pilosa_stats_{snake}", labels=("tags",))
        m = fam.labels(self._tags)
        self._cache[key] = m
        return m

    def count(self, name: str, value: int = 1) -> None:
        self._metric("count", name).inc(value)

    def gauge(self, name: str, value: float) -> None:
        self._metric("gauge", name).set(value)

    def histogram(self, name: str, value: float) -> None:
        self._metric("histogram", name).observe(value)

    def timing(self, name: str, value_ns: float) -> None:
        self._metric("timing", name).observe(value_ns / 1e9)
