"""Per-query resource accounting: the QueryCost ledger + cost tree.

PR 3 made the cluster visible (traces, /metrics, runtime gauges) but
nothing said *what a query cost* — and the Roaring papers
(arXiv:1709.07821, 1402.6407) show cost is dominated by the
*container-kind mix* of the operand pairs, so the ledger attributes
work at container granularity, not just wall-clock:

- **container ops** by ``(op, operand-kind pair)`` — the same keying as
  the global ``pilosa_roaring_container_ops_total`` counters, but
  per-query (storage/roaring.py increments both at one site);
- **word-equivalents scanned** (1024 words per bitmap container
  operand, ``ceil(len/64)`` per array operand);
- **bits written** (fragment mutate/import paths);
- **device programs dispatched + device bytes** (parallel/mesh entry
  points) and **XLA compile seconds** attributed to the query whose
  first call paid the trace+compile;
- **RPC bytes in/out per peer** (cluster/client fan-out legs);
- **queue wait** rides the context's existing ``admission`` stage.

A ledger is attached to ``sched.QueryContext.cost`` by the serving
layers (the same pattern as ``ctx.trace``); ``None`` is the
no-allocation fast path — every ``note_*`` helper is two attribute
reads and out. Remote legs piggyback their ledger on the internal
response header ``X-Pilosa-Cost`` (same stitching pattern as
``X-Pilosa-Trace-Spans``) so the coordinator merges a per-node,
per-stage **cost tree**, returned inline with results under
``?profile=1`` (EXPLAIN ANALYZE for PQL), summarized in the
``X-Pilosa-Stats`` response header, and visible in ``/debug/queries``
+ the slow log + trace-span args.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

# Wire header: a remote leg's serialized ledger rides the internal
# query response; the coordinator's cluster client stitches it in as a
# child of its own ledger.
COST_HEADER = "X-Pilosa-Cost"
# Compact per-response summary (every /query response carries it).
STATS_HEADER = "X-Pilosa-Stats"

# Hard cap on stitched children so a pathological fan-out cannot
# balloon the tree (mirrors trace.MAX_SPANS's role).
MAX_CHILDREN = 64

# Module switch: accounting is ON by default (the ledger is plain int
# increments). This is the process-wide kill switch the overhead-guard
# test flips; operators use the per-server gate instead
# ([metrics] accounting / --metrics.accounting /
# PILOSA_METRICS_ACCOUNTING, threaded into the handler).
_enabled = True


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


class QueryCost:
    """One query's resource ledger on one node.

    Increments are GIL-coarse plain-int bumps (a rare lost count is
    acceptable for accounting, same contract as roaring._OP_COUNTS);
    the lock guards only the merge/serialize paths.
    """

    __slots__ = ("node", "container_ops", "words_scanned",
                 "bits_written", "device_programs", "device_bytes",
                 "compile_s", "wal_wait_s", "result_cache_hits", "rpc",
                 "children", "_mu")

    def __init__(self, node: str = ""):
        self.node = node
        self.container_ops: dict[str, int] = {}
        self.words_scanned = 0
        self.bits_written = 0
        self.device_programs = 0
        self.device_bytes = 0
        self.compile_s = 0.0
        # Seconds this query's threads spent blocked in WAL group
        # commit (waiting for a leader's flush to cover their records)
        # — the write-side queue wait, alongside the admission stage's
        # read-side one.
        self.wal_wait_s = 0.0
        # Results this query served from a generation-validated cache
        # (result residency or the coordinator cluster cache) instead
        # of re-folding — the ledger's "why was this query cheap".
        self.result_cache_hits = 0
        # peer host -> {"bytesOut": n, "bytesIn": n, "calls": n}
        self.rpc: dict[str, dict] = {}
        self.children: list[dict] = []
        self._mu = threading.Lock()

    # -- increment sites -----------------------------------------------------

    def note_container_op(self, op: str, kind: str, words: int = 0) -> None:
        key = f"{op}:{kind}"
        self.container_ops[key] = self.container_ops.get(key, 0) + 1
        if words:
            self.words_scanned += words

    def note_bits_written(self, n: int) -> None:
        self.bits_written += n

    def note_device_dispatch(self, nbytes: int = 0) -> None:
        self.device_programs += 1
        self.device_bytes += nbytes

    def note_compile(self, seconds: float) -> None:
        self.compile_s += seconds

    def note_wal_wait(self, seconds: float) -> None:
        self.wal_wait_s += seconds

    def note_result_cache_hit(self, n: int = 1) -> None:
        self.result_cache_hits += n

    def note_rpc(self, peer: str, bytes_out: int, bytes_in: int) -> None:
        with self._mu:
            entry = self.rpc.setdefault(
                peer, {"bytesOut": 0, "bytesIn": 0, "calls": 0})
            entry["bytesOut"] += bytes_out
            entry["bytesIn"] += bytes_in
            entry["calls"] += 1

    # -- stitching -----------------------------------------------------------

    def add_remote_json(self, payload: str) -> None:
        """Stitch a peer's piggybacked ledger (COST_HEADER value) as a
        child of this tree."""
        try:
            tree = json.loads(payload)
        except ValueError:
            return
        if not isinstance(tree, dict):
            return
        with self._mu:
            if len(self.children) < MAX_CHILDREN:
                self.children.append(tree)

    # -- export --------------------------------------------------------------

    def to_tree(self, stages: Optional[dict] = None) -> dict:
        """The per-node cost tree: this ledger plus stitched children.
        ``stages`` (the QueryContext's per-stage seconds) makes it
        per-stage as well as per-node."""
        with self._mu:
            rpc = {p: dict(v) for p, v in self.rpc.items()}
            children = list(self.children)
        out: dict = {
            "node": self.node,
            "containerOps": dict(self.container_ops),
            "wordsScanned": self.words_scanned,
            "bitsWritten": self.bits_written,
            "devicePrograms": self.device_programs,
            "deviceBytes": self.device_bytes,
            "compileMs": round(self.compile_s * 1e3, 3),
        }
        if self.wal_wait_s:
            out["walWaitMs"] = round(self.wal_wait_s * 1e3, 3)
        if self.result_cache_hits:
            out["resultCacheHit"] = self.result_cache_hits
        if stages:
            out["stages"] = {k: round(v, 6) for k, v in stages.items()}
            if "admission" in stages:
                out["queueWaitMs"] = round(stages["admission"] * 1e3, 3)
        if rpc:
            out["rpc"] = rpc
        if children:
            out["children"] = children
        return out

    def summary(self) -> dict:
        """Compact roll-up for headers, span tags, and slow-log rows —
        totals only, bounded size whatever the query did."""
        with self._mu:
            rpc_out = sum(v["bytesOut"] for v in self.rpc.values())
            rpc_in = sum(v["bytesIn"] for v in self.rpc.values())
            n_children = len(self.children)
        out = {
            "containerOps": sum(self.container_ops.values()),
            "wordsScanned": self.words_scanned,
            "bitsWritten": self.bits_written,
            "devicePrograms": self.device_programs,
            "deviceBytes": self.device_bytes,
            "compileMs": round(self.compile_s * 1e3, 3),
        }
        if self.wal_wait_s:
            out["walWaitMs"] = round(self.wal_wait_s * 1e3, 3)
        if self.result_cache_hits:
            out["resultCacheHit"] = self.result_cache_hits
        if rpc_out or rpc_in:
            out["rpcBytesOut"] = rpc_out
            out["rpcBytesIn"] = rpc_in
        if n_children:
            out["remoteLegs"] = n_children
        return out

    # Same wire budget rationale as trace.Trace._WIRE_BYTES:
    # http.client rejects header LINES over 64 KiB.
    _WIRE_BYTES = 48 << 10

    def wire_json(self, stages: Optional[dict] = None,
                  max_bytes: int = _WIRE_BYTES) -> str:
        """Compact JSON of the tree for the piggyback header; over
        budget the containerOps detail collapses to its total (the
        mix is the first thing to go — totals must survive)."""
        tree = self.to_tree(stages)
        out = json.dumps(tree, separators=(",", ":"))
        if len(out) > max_bytes:
            tree["containerOps"] = {
                "total": sum(self.container_ops.values())}
            tree.pop("children", None)
            out = json.dumps(tree, separators=(",", ":"))
        return out


# -- current-query helpers ----------------------------------------------------
# The sched package import is deferred to first use: storage.roaring
# imports this module, and an import-time ``from ..sched import ...``
# could re-enter a partially initialized package when the import chain
# starts from sched.warmup -> executor -> storage.

_sched_current = None
_sched_tls = None


def current_cost() -> Optional[QueryCost]:
    """The ledger of this thread's current query, or None (the fast
    path: thread-local read + two attribute reads, no allocation)."""
    global _sched_current
    if _sched_current is None:
        from ..sched.context import current as _c
        _sched_current = _c
    ctx = _sched_current()
    if ctx is None:
        return None
    return getattr(ctx, "cost", None)


def attach(ctx, node: str = "") -> Optional[QueryCost]:
    """Attach a fresh ledger to a QueryContext (respecting the module
    switch); returns it. The serving layers call this where they
    construct the context — mirroring how the tracer binds ctx.trace."""
    if not _enabled:
        return None
    cost = QueryCost(node=node or getattr(ctx, "node", ""))
    ctx.cost = cost
    return cost


def note_bits_written(n: int) -> None:
    # The per-op write hot path: one thread-local read inline instead
    # of the current_cost() call chain (measured at per-op rates).
    global _sched_tls
    tls = _sched_tls
    if tls is None:
        from ..sched import context as _sched_ctx
        tls = _sched_tls = _sched_ctx._tls
    ctx = getattr(tls, "ctx", None)
    if ctx is None:
        return
    cost = getattr(ctx, "cost", None)
    if cost is not None:
        cost.note_bits_written(n)


def note_result_cache_hit(ctx=None) -> None:
    """Stamp a generation-validated cache hit on the query's ledger
    (explicit ctx where the caller holds one; thread-bound otherwise)."""
    cost = (getattr(ctx, "cost", None) if ctx is not None
            else current_cost())
    if cost is not None:
        cost.note_result_cache_hit()


def note_device_dispatch(nbytes: int = 0) -> None:
    cost = current_cost()
    if cost is not None:
        cost.note_device_dispatch(nbytes)


def note_compile(seconds: float) -> None:
    cost = current_cost()
    if cost is not None:
        cost.note_compile(seconds)
