"""On-disk metric history: bounded multi-resolution rings over the
whole registry — the Monarch leaf store in miniature.

Every observability surface before this PR was point-in-time:
``/metrics`` is a snapshot and the only retained signals are kept
traces and blackbox snapshots, so "what did p99 look like in the ten
minutes before the watchdog tripped" was unanswerable. This module
keeps recent high-resolution history AT THE LEAF (the Monarch /
Dapper-lineage split: aggregate at query time, don't ship everything
to a central store):

- On the runtime-collector cadence, ``sample()`` walks every family in
  the metrics registry and appends one point per series into bounded
  rings at three resolutions (default 10 s × 1 h, 1 m × 12 h,
  15 m × 7 d). **Counters are stored as per-second rates** (the delta
  between ticks), gauges as values, and histograms as derived quantile
  series — ``<name>:p50`` / ``<name>:p99`` (interpolation-free bucket
  upper bounds over the tick's bucket deltas) plus ``<name>:rate``
  (observations/s).
- Coarser rings aggregate the base ring on the fly (bucket means), so
  a 7-day question costs 672 points, not 60 480.
- Every tick persists crash-safe to ``<data>/history/res<N>/`` through
  the PR-10 ``obs.diskring`` segment/crc discipline: a SIGKILL mid-
  append tears at most the unflushed tail of one segment, reopen
  skips exactly the torn record and serves everything else (the
  ``ring.write`` failpoint tears the history write site too — the
  chaos tests drive it).
- ``GET /debug/metrics/history?family=&label=&window=&step=`` serves
  the rings as JSON series; ``?scope=cluster`` federates the same
  question across the fleet (obs.federate).

Bounded by construction: per-series ring capacity is fixed, the series
count is capped (new series past the cap are dropped and counted), and
disk is the segment rings' budget — whatever the write rate.
"""

from __future__ import annotations

import json
import threading
import time
from array import array
from typing import Optional

from . import metrics as obs_metrics
from .diskring import SegmentRing

# (step_seconds, ring_capacity): 10s x 1h, 1m x 12h, 15m x 7d.
DEFAULT_RESOLUTIONS = ((10.0, 360), (60.0, 720), (900.0, 672))
DEFAULT_MAX_SERIES = 4096
# Disk budget per resolution ring (segment_bytes, max_segments).
DEFAULT_SEGMENT_BYTES = 1 << 20
DEFAULT_MAX_SEGMENTS = 8

_EPS = 1e-12


def series_key(name: str, labels: dict) -> str:
    """Stable series identity: name + compact-JSON sorted label pairs
    (JSON so hostile label values can never collide or split a key)."""
    if not labels:
        return name
    return name + "|" + json.dumps(sorted(labels.items()),
                                   separators=(",", ":"))


def split_key(key: str) -> tuple[str, dict]:
    name, sep, raw = key.partition("|")
    if not sep:
        return name, {}
    try:
        return name, dict(json.loads(raw))
    except ValueError:
        return name, {}


class _Ring:
    """Fixed-capacity circular buffer of (ts, value) as two packed
    float arrays — ~16 bytes per point instead of a tuple's ~100."""

    __slots__ = ("cap", "ts", "v", "head", "count")

    def __init__(self, cap: int):
        self.cap = max(2, int(cap))
        self.ts = array("d", bytes(8 * self.cap))
        self.v = array("d", bytes(8 * self.cap))
        self.head = 0   # next write slot
        self.count = 0

    def append(self, ts: float, v: float) -> None:
        self.ts[self.head] = ts
        self.v[self.head] = v
        self.head = (self.head + 1) % self.cap
        if self.count < self.cap:
            self.count += 1

    def points(self, since: float = 0.0) -> list[tuple[float, float]]:
        """Chronological (ts, value) pairs with ts >= since."""
        out = []
        start = (self.head - self.count) % self.cap
        for i in range(self.count):
            j = (start + i) % self.cap
            t = self.ts[j]
            if t >= since:
                out.append((t, self.v[j]))
        return out

    def last_ts(self) -> float:
        if not self.count:
            return 0.0
        return self.ts[(self.head - 1) % self.cap]


class _Series:
    """One series' rings across every resolution plus the coarse
    aggregation accumulators (bucket mean)."""

    __slots__ = ("rings", "acc")

    def __init__(self, resolutions):
        self.rings = [_Ring(cap) for _step, cap in resolutions]
        # Per coarse resolution: [bucket_start, sum, count].
        self.acc = [[0.0, 0.0, 0] for _ in resolutions[1:]]


class MetricHistory:
    """The embedded RRD-style store (module docstring). Thread-safe;
    every disk error degrades to in-memory-only (diskring contract)."""

    def __init__(self, dir: Optional[str] = None,
                 resolutions=DEFAULT_RESOLUTIONS,
                 max_series: int = DEFAULT_MAX_SERIES,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 max_segments: int = DEFAULT_MAX_SEGMENTS,
                 registry=None):
        self.resolutions = tuple((float(s), int(c))
                                 for s, c in resolutions)
        self.max_series = max(16, int(max_series))
        self.registry = registry or obs_metrics.default_registry()
        self._mu = threading.Lock()
        self._series: dict[str, _Series] = {}
        self._prev: dict[str, tuple] = {}   # counter/histogram deltas
        self._last_sample = 0.0
        self.samples = 0
        self.dropped_series = 0
        self.disk: list[Optional[SegmentRing]] = [None] * len(
            self.resolutions)
        if dir:
            import os
            for i in range(len(self.resolutions)):
                self.disk[i] = SegmentRing(
                    os.path.join(dir, f"res{i}"),
                    segment_bytes=segment_bytes,
                    max_segments=max_segments)
            self._replay()

    # -- persistence ----------------------------------------------------------

    def _replay(self) -> None:
        """Rebuild the rings from the disk records (oldest first).
        A torn tail costs at most the unflushed records of one
        segment — everything else serves (diskring's scan contract)."""
        with self._mu:
            for i, ring in enumerate(self.disk):
                if ring is None:
                    continue
                for rec in ring.scan(newest_first=False):
                    try:
                        ts = float(rec["t"])
                        samples = rec["s"]
                    except (KeyError, TypeError, ValueError):
                        continue
                    if not isinstance(samples, dict):
                        continue
                    for key, v in samples.items():
                        s = self._series_for_locked(key)
                        if s is None:
                            continue
                        try:
                            if isinstance(v, (list, tuple)):
                                # Coarse form: [bucket_start, value]
                                # — the ring timestamp is the BUCKET,
                                # not the flush tick, so replayed
                                # points line up with live flushes.
                                s.rings[i].append(float(v[0]),
                                                  float(v[1]))
                            else:
                                s.rings[i].append(ts, float(v))
                        except (TypeError, ValueError, IndexError):
                            continue

    def _persist(self, res_idx: int, ts: float,
                 samples: dict) -> None:
        ring = self.disk[res_idx]
        if ring is None or not samples:
            return
        ok = ring.append({"t": round(ts, 3), "s": samples})
        obs_metrics.HISTORY_DISK_RECORDS.labels(
            "written" if ok else "dropped").inc()

    def close(self) -> None:
        for ring in self.disk:
            if ring is not None:
                ring.close()

    # -- sampling -------------------------------------------------------------

    def _series_for_locked(self, key: str) -> Optional[_Series]:
        s = self._series.get(key)
        if s is None:
            if len(self._series) >= self.max_series:
                self.dropped_series += 1
                obs_metrics.HISTORY_SERIES_DROPPED.inc()
                return None
            s = self._series[key] = _Series(self.resolutions)
        return s

    def sample(self, now: Optional[float] = None) -> int:
        """One sampling pass over the whole registry; returns the
        number of points recorded. Re-entrant calls inside half a base
        step are ignored (the collector's on-demand /status path must
        not double-sample a tick)."""
        now = time.time() if now is None else float(now)
        base_step = self.resolutions[0][0]
        with self._mu:
            if now - self._last_sample < 0.45 * base_step:
                return 0
            self._last_sample = now
            points = self._collect_locked(now)
            base: dict[str, float] = {}
            # Coarse flushes persist as [bucket_start, mean] pairs:
            # each series' flushed bucket can differ (series that
            # skip ticks lag), so the record-level tick time cannot
            # stamp them — the bucket start must ride per key.
            coarse: list[dict[str, list]] = [
                {} for _ in self.resolutions[1:]]
            for key, v in points.items():
                s = self._series_for_locked(key)
                if s is None:
                    continue
                s.rings[0].append(now, v)
                base[key] = round(v, 6)
                # Roll into the coarser buckets; flush on boundary.
                for ci, (step, _cap) in enumerate(
                        self.resolutions[1:]):
                    acc = s.acc[ci]
                    bucket = now - (now % step)
                    if acc[2] and acc[0] != bucket:
                        mean = acc[1] / acc[2]
                        s.rings[ci + 1].append(acc[0], mean)
                        coarse[ci][key] = [round(acc[0], 3),
                                           round(mean, 6)]
                        acc[0], acc[1], acc[2] = bucket, 0.0, 0
                    elif not acc[2]:
                        acc[0] = bucket
                    acc[1] += v
                    acc[2] += 1
            self.samples += 1
        obs_metrics.HISTORY_SAMPLES.inc()
        obs_metrics.HISTORY_SERIES_LIVE.set(len(self._series))
        self._persist(0, now, base)
        for ci, flushed in enumerate(coarse):
            self._persist(ci + 1, now, flushed)
        return len(points)

    def _collect_locked(self, now: float) -> dict[str, float]:
        """The registry → {series key: value} for this tick (counters
        as rates, histograms as quantile/rate summaries)."""
        out: dict[str, float] = {}
        for name, fam in self.registry.families().items():
            try:
                if fam.type == "counter":
                    for labels, child in fam._label_dicts():
                        key = series_key(name, labels)
                        v = float(child.value)
                        pts = self._prev.get(key)
                        self._prev[key] = (now, v)
                        if pts is None:
                            continue
                        pt, pv = pts
                        dt = now - pt
                        if dt <= 0 or v < pv:  # reset → skip the tick
                            continue
                        out[key] = (v - pv) / dt
                elif fam.type == "gauge":
                    for labels, child in fam._label_dicts():
                        out[series_key(name, labels)] = float(
                            child.value)
                elif fam.type == "histogram":
                    for labels, child in fam._label_dicts():
                        key = series_key(name, labels)
                        counts, total, n = child.snapshot()
                        prev = self._prev.get(key)
                        self._prev[key] = (now, counts, total, n)
                        if prev is None:
                            continue
                        pt, pc, ptotal, pn = prev
                        dt = now - pt
                        dn = n - pn
                        if dt <= 0 or dn < 0:
                            continue
                        out[series_key(f"{name}:rate", labels)] = \
                            dn / dt
                        if dn == 0:
                            continue
                        deltas = [c - p for c, p in zip(counts, pc)]
                        bounds = fam.buckets
                        for q, suffix in ((0.5, ":p50"),
                                          (0.99, ":p99")):
                            want = dn * q
                            cum = 0
                            est = bounds[-1]
                            for i, d in enumerate(deltas[:-1]):
                                cum += d
                                if cum >= want:
                                    est = bounds[i]
                                    break
                            out[series_key(name + suffix,
                                           labels)] = est
            except Exception:  # noqa: BLE001 - sampling must not raise
                continue
        return out

    # -- querying -------------------------------------------------------------

    def _pick_resolution(self, window_s: float, step_s: float) -> int:
        """Finest resolution whose step honors the caller's step hint
        and whose ring span covers the window."""
        idx = 0
        for i, (step, _cap) in enumerate(self.resolutions):
            if step_s >= step:
                idx = i
        while idx < len(self.resolutions) - 1:
            step, cap = self.resolutions[idx]
            if window_s <= step * cap:
                break
            idx += 1
        return idx

    def series(self, family: str = "", label_filter: Optional[dict]
               = None, window_s: float = 3600.0,
               step_s: float = 0.0,
               now: Optional[float] = None) -> dict:
        """The query face of the store: every series whose name is
        ``family`` or a derived ``family:<q>`` form, label-filtered,
        over the trailing window at the chosen resolution."""
        now = time.time() if now is None else float(now)
        window_s = max(float(window_s), self.resolutions[0][0])
        idx = self._pick_resolution(window_s, float(step_s))
        since = now - window_s
        out = []
        with self._mu:
            for key, s in self._series.items():
                name, labels = split_key(key)
                if family and not (name == family or name.startswith(
                        family + ":")):
                    continue
                if label_filter and any(
                        labels.get(k) != v
                        for k, v in label_filter.items()):
                    continue
                pts = s.rings[idx].points(since)
                if not pts:
                    continue
                out.append({"name": name, "labels": labels,
                            "points": [[round(t, 3), round(v, 6)]
                                       for t, v in pts]})
        out.sort(key=lambda e: (e["name"], sorted(e["labels"].items())))
        return {"family": family, "windowS": window_s,
                "stepS": self.resolutions[idx][0],
                "resolution": idx, "series": out}

    def latest(self, name: str, labels: Optional[dict] = None
               ) -> Optional[float]:
        """Newest point of one exact series (the sentinel's cheap
        probe); None when the series doesn't exist or is empty."""
        key = series_key(name, labels or {})
        with self._mu:
            s = self._series.get(key)
            if s is None or not s.rings[0].count:
                return None
            return s.rings[0].v[(s.rings[0].head - 1) % s.rings[0].cap]

    def window_values(self, key: str, start: float, end: float
                      ) -> list[float]:
        """Base-ring values of one series key in [start, end) — the
        sentinel's window extraction."""
        with self._mu:
            s = self._series.get(key)
            if s is None:
                return []
            return [v for t, v in s.rings[0].points(start) if t < end]

    def keys(self, family: str = "") -> list[str]:
        with self._mu:
            return [k for k in self._series
                    if not family or split_key(k)[0] == family
                    or split_key(k)[0].startswith(family + ":")]

    def stats(self) -> dict:
        with self._mu:
            n = len(self._series)
        return {"series": n, "samples": self.samples,
                "droppedSeries": self.dropped_series,
                "resolutions": [{"stepS": s, "points": c}
                                for s, c in self.resolutions],
                "disk": [r.stats() for r in self.disk
                         if r is not None]}
