"""Multi-host TPU execution: one SPMD mesh across a pod of hosts.

The reference scales across machines with HTTP remote legs + gossip
(executor.go:1001-1083, gossip/gossip.go); pilosa-tpu keeps that DCN
path for *cross-cluster* queries, and adds this layer for the case the
reference cannot express: a single TPU pod spanning several hosts (e.g.
v5e-16 = 2 hosts × 8 chips), where the slice axis shards over EVERY
chip in the pod and Count/TopN reductions ride ICI end-to-end instead
of merging per-host results over HTTP.

Design (scaling-book recipe):
- each host in the pod is one jax.distributed process; together they
  own one global ``Mesh`` over all chips (slices axis, optional rows
  axis);
- each host feeds ONLY its local shard of the leaf/candidate blocks
  (``jax.make_array_from_process_local_data``) — slice placement is
  aligned so the slices a host serves are the slices its chips hold;
- the jitted programs are the SAME ones the single-host executor uses
  (parallel.mesh.count_expr_fn / topn_exact_fn): under SPMD every
  process runs the identical program and the psum spans the pod.

The coordinator/membership control plane stays host-side HTTP/gossip —
metadata is not bandwidth-bound (SURVEY.md §5).

Deployment contract: a pod is ONE logical cluster node (only the pod
coordinator appears in ``cluster.hosts``; a cluster of pods lists one
coordinator per pod). Every process of the pod must enter each
collective together with identically-shaped shards — the pod-internal
query broadcast in ``parallel.pod`` drives this layer from the
Server/Executor stack: the coordinator replays each device-batched
Count/TopN as a work item to every process's ``/pod/exec`` route and
all processes enter the collective together (NOT the executor's
per-node map-reduce, which would double-count the pod-global psum if
pod hosts were also cluster nodes).

Environment contract (set by the pod launcher):
  PILOSA_TPU_DIST_COORDINATOR  host:port of process 0
  PILOSA_TPU_DIST_NUM_PROCS    total process count
  PILOSA_TPU_DIST_PROC_ID      this process's id (0-based)
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from . import mesh as mesh_mod

_initialized = False


def initialize_from_env() -> bool:
    """Join the pod's jax.distributed job if the env contract is set.

    Idempotent; returns True when running as part of a multi-process
    job (including a degenerate 1-process one, which is how tests
    exercise this path without pod hardware).
    """
    global _initialized
    if _initialized:
        return True
    coord = os.environ.get("PILOSA_TPU_DIST_COORDINATOR")
    if not coord:
        return False
    # CPU-pod support (tests and TPU-less staging): give each process N
    # virtual CPU devices and gloo cross-process collectives. Must be
    # configured before the first backend touch.
    cpu_devs = os.environ.get("PILOSA_TPU_DIST_CPU_DEVICES")
    if cpu_devs:
        try:
            jax.config.update("jax_num_cpu_devices", int(cpu_devs))
        except AttributeError:
            # Pre-0.5 jax has no jax_num_cpu_devices option; the
            # XLA_FLAGS env equivalent works as long as the backend is
            # untouched, which this env-contract path guarantees.
            flag = (f"--xla_force_host_platform_device_count="
                    f"{int(cpu_devs)}")
            prior = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in prior:
                os.environ["XLA_FLAGS"] = f"{prior} {flag}".strip()
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(os.environ.get("PILOSA_TPU_DIST_NUM_PROCS", "1")),
        process_id=int(os.environ.get("PILOSA_TPU_DIST_PROC_ID", "0")))
    _initialized = True
    return True


def pod_mesh(rows: int = 1) -> Mesh:
    """A (rows × slices) mesh over every chip in the pod (all processes)."""
    return mesh_mod.make_mesh(len(jax.devices()), rows=rows)


def process_slice_range(n_slices: int) -> tuple[int, int]:
    """[lo, hi) rows of the global slice axis this process must feed.

    The global block is sharded evenly over the slice axis; with
    process-local device order matching mesh order (the default
    make_mesh layout), each process feeds a contiguous range. Slice
    placement in the cluster layer should assign these slices to this
    host so packing is local (no cross-host reads).
    """
    n_procs = jax.process_count()
    if n_slices % n_procs:
        raise ValueError(f"{n_slices} slices not divisible by"
                         f" {n_procs} processes (pad first)")
    per = n_slices // n_procs
    pid = jax.process_index()
    return pid * per, (pid + 1) * per


# Local slice-axis chunk size: every process uses the same bound, so
# chunk boundaries agree pod-wide; the global per-chunk slice count
# (chunk × n_procs, plus per-device padding ≤ n_devices) stays within
# the int32 hi/lo split (mesh.slice_chunk_bound).
def _local_chunk() -> int:
    return max(1, ((1 << 15) - len(jax.devices()))
               // jax.process_count())


def _assert_uniform_shards(*dims: int) -> None:
    """Every process must enter the chunk loops with identically-sized
    local shards — unequal shards execute different numbers of
    collectives and deadlock the pod. One tiny allgather per call
    (entered by all processes together) catches the mismatch up front.
    """
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    mine = np.asarray(dims, dtype=np.int64)
    everyone = np.asarray(multihost_utils.process_allgather(mine))
    if not (everyone == mine[None, :]).all():
        raise ValueError(
            "pod shard shapes differ across processes:"
            f" {everyone.tolist()} — every process must pass the same"
            " local slice/row counts (pad with zero slices)")


def _pad_local(local: np.ndarray, axis: int) -> np.ndarray:
    """Pad this process's shard to its canonical slice BUCKET
    (parallel.programs.slice_bucket over the per-process device count),
    so every process contributes the same number of slice rows per
    device AND the assembled global array has a bucket-stable shape —
    the pod reuses one compiled program as the index grows within a
    bucket, exactly like the single-host path. Zero slices are the
    identity for every count/TopN reduction, so the result is exact
    even though the zeros interleave between process ranges in the
    global order. Deterministic from the shard length alone, so every
    process picks the same bucket (the shard-uniformity allgather has
    already pinned the lengths equal)."""
    from . import programs
    per_dev = len(jax.devices()) // jax.process_count()
    target = programs.slice_bucket(local.shape[axis], per_dev)
    # The GLOBAL row count (target × n_procs) must stay within the
    # int32 hi/lo split; past the cap fall back to plain device-
    # multiple padding (the chunk loops bound the shard anyway).
    if target * jax.process_count() > (1 << 15):
        n = local.shape[axis]
        target = (n + (-n % per_dev)) or per_dev
    if local.shape[axis] == target:
        return local
    pad = [(0, 0)] * local.ndim
    pad[axis] = (0, target - local.shape[axis])
    return np.pad(local, pad)


def _global_from_local(mesh: Mesh, local: np.ndarray,
                       axis: int) -> jax.Array:
    """Assemble the pod-global sharded array from this process's shard."""
    spec = [None] * local.ndim
    spec[axis] = mesh_mod.AXIS_SLICES
    sharding = NamedSharding(mesh, P(*spec))
    global_shape = list(local.shape)
    global_shape[axis] = local.shape[axis] * jax.process_count()
    return jax.make_array_from_process_local_data(
        sharding, local, tuple(global_shape))


def count_expr(mesh: Mesh, expr: tuple, local_leaves: np.ndarray) -> int:
    """Pod-wide Count: each process passes its local [L, S_local, W]
    leaf shard; the psum spans every chip on every host. Chunks the
    slice axis identically on every process (int32 hi/lo bound).
    The K=1 form of count_exprs."""
    return count_exprs(mesh, (expr,), local_leaves)[0]


def count_exprs(mesh: Mesh, exprs: tuple,
                local_leaves: np.ndarray) -> list[int]:
    """Pod-wide batched Counts: K expressions over one shared local
    leaf shard, one collective program per chunk (the pod form of
    mesh.count_exprs_sharded — K counts, one dispatch)."""
    _assert_uniform_shards(*local_leaves.shape, len(exprs))
    fn = mesh_mod.count_exprs_fn(mesh, tuple(exprs))
    totals = [0] * len(exprs)
    step = _local_chunk()
    for off in range(0, max(local_leaves.shape[1], 1), step):
        chunk = _pad_local(local_leaves[:, off:off + step], 1)
        arr = _global_from_local(mesh, chunk, 1)
        counts = mesh_mod.hilo_combine(fn(arr))  # [2, K]: one fetch
        for k in range(len(exprs)):
            totals[k] += counts[k]
    return totals


def topn_exact(mesh: Mesh, expr, local_rows: np.ndarray,
               local_leaves: Optional[np.ndarray], threshold: int = 1,
               tanimoto: int = 0) -> list[int]:
    """Pod-wide TopN exact counts: local shards in, global counts out.
    threshold>1 / tanimoto engage the per-slice pruning program
    (mesh.topn_filtered_fn) — masks are per-slice, so shard-local
    evaluation composes exactly.

    Chunks slices (int32 bound) and candidate rows (device-block byte
    budget, mirroring mesh.topn_exact) with pod-wide identical bounds.
    """
    import functools

    import jax.numpy as jnp
    n_local, n_rows, n_words = local_rows.shape
    _assert_uniform_shards(n_local, n_rows, n_words, threshold, tanimoto)
    if local_leaves is None:
        local_leaves = np.zeros((0, n_local, 1), dtype=np.uint32)
    filtered = threshold > 1 or tanimoto > 0
    if filtered:
        threshold = min(threshold, 2**31 - 1)  # counts never exceed 2^31
        fn = functools.partial(mesh_mod.topn_filtered_fn(mesh, expr),
                               jnp.int32(threshold), jnp.int32(tanimoto))
    else:
        fn = mesh_mod.topn_exact_fn(mesh, expr)
    s_step = _local_chunk()
    r_step = max(1, mesh_mod.TOPN_BLOCK_BYTES
                 // (max(s_step, 1) * n_words * 4))
    totals = [0] * n_rows
    for s_off in range(0, max(n_local, 1), s_step):
        for r_off in range(0, n_rows, r_step):
            rc = _pad_local(
                local_rows[s_off:s_off + s_step, r_off:r_off + r_step], 0)
            lc = _pad_local(local_leaves[:, s_off:s_off + s_step], 1)
            rows = _global_from_local(mesh, rc, 0)
            leaves = _global_from_local(mesh, lc, 1)
            counts = mesh_mod.hilo_combine(fn(rows, leaves))  # 1 fetch
            for r in range(rc.shape[1]):
                totals[r_off + r] += counts[r]
    return totals
