"""Pod-internal query broadcast: a multi-host TPU pod as ONE cluster node.

The reference scales only by adding cluster nodes that merge results
over HTTP (executor.go:1103-1236); a TPU pod instead spans hosts with a
single device mesh whose collectives ride ICI. This module makes such a
pod serve PQL through the ordinary Server/Executor stack:

- Only the pod *coordinator* (jax process 0) appears in the cluster's
  host list. Clients (and other cluster nodes' remote legs) talk to it.
- Slice ownership inside the pod is round-robin by process:
  ``owner_pid(slice) = slice % n_procs``. Stable as the index grows, so
  writes and reads agree on placement without any rebalancing.
- Device-batched Count/TopN: the coordinator broadcasts a *work item*
  (expression tree + leaf descriptors + the global slice list) to every
  worker process over HTTP, then all processes pack their owned slices
  and enter the SAME SPMD collective together
  (parallel.multihost.count_expr / topn_exact) — the in-program
  reduction spans every chip in the pod. Workers run the item from the
  ``/pod/exec`` route. The programs are the single-host catalogue's
  (parallel.programs): multihost pads each process's shard to its
  canonical slice bucket, so the pod compiles once per bucket and the
  identical jitted computation lowers unchanged from one host to the
  whole pod.
- Host-path reads (Bitmap/Range materialization, TopN candidate phase)
  and writes route within the pod over HTTP as ``podLocal`` query legs:
  the executor partitions slices by owner process and the owning
  process runs its plain local path (executor._pod_host_mapper).

Failure semantics match TPU pods, not the reference's replica retry: a
pod process that dies mid-collective stalls the pod until the
collective layer times out — the pod is one failure domain, and
cluster-level replication (whole pods as ReplicaN nodes) provides the
redundancy.

Environment contract (in addition to parallel.multihost's):
  PILOSA_TPU_POD_PEERS   comma list of every pod process's HTTP host,
                         in process order (index 0 = coordinator)
  PILOSA_TPU_POD_TIMEOUT seconds to wait for worker legs (default 300)
"""

from __future__ import annotations

import http.client
import json
import os
import threading
from typing import Optional

import numpy as np

from ..errors import PilosaError
from . import multihost

ENV_PEERS = "PILOSA_TPU_POD_PEERS"


class PodError(PilosaError):
    pass


def _expr_from_json(v):
    """JSON arrays back to the hashable tuple trees mesh kernels key on."""
    if isinstance(v, list):
        return tuple(_expr_from_json(x) for x in v)
    return v


class Pod:
    """Pod membership + the work-item protocol. One per Server process."""

    def __init__(self, holder, peers: list[str]):
        import jax
        self._init_state(holder, jax.process_index(),
                         jax.process_count(), peers)

    def _init_state(self, holder, pid: int, n_procs: int,
                    peers: list[str]) -> None:
        """All non-jax state — shared by __init__ and unit tests that
        build Pods without a jax.distributed job."""
        self.holder = holder
        self.pid = pid
        self.n_procs = n_procs
        if len(peers) != self.n_procs:
            raise PodError(
                f"{ENV_PEERS} lists {len(peers)} hosts for"
                f" {self.n_procs} pod processes")
        self.peers = peers
        self.timeout = float(os.environ.get("PILOSA_TPU_POD_TIMEOUT",
                                            "300"))
        self._run_mu = threading.Lock()       # one collective at a time
        self._dispatch_mu = threading.Lock()  # one item in flight pod-wide
        # Set when a dispatch failed AFTER some worker received the item:
        # that worker may be parked inside the orphaned collective, and a
        # new collective would cross-match with it. Once poisoned, the
        # device path stays off (the podLocal host fan-out remains
        # correct) until the pod is restarted — a pod is one failure
        # domain, like a real TPU pod job.
        self._poisoned = False
        # Per-kind successful dispatch counts (observability + tests
        # pinning that the collective path actually engaged).
        self.dispatch_counts: dict[str, int] = {}
        # Per-peer keep-alive connections for pod-internal requests
        # (serialized per peer; reconnect on any error).
        self._conns: dict[int, http.client.HTTPConnection] = {}
        self._conn_mus = {pid: threading.Lock()
                          for pid in range(self.n_procs)}

    @property
    def is_coordinator(self) -> bool:
        return self.pid == 0

    # -- slice placement -----------------------------------------------------

    def owner_pid(self, slice: int) -> int:
        return slice % self.n_procs

    def owned(self, slices, pid: Optional[int] = None) -> list[int]:
        pid = self.pid if pid is None else pid
        return sorted(s for s in slices if s % self.n_procs == pid)

    def max_shard_slices(self, slices) -> int:
        """Per-process shard length for an item's slice list: the max
        owned count over processes, so arbitrary (non-round-robin-
        balanced) lists still give every process an equal shard."""
        counts = [0] * self.n_procs
        for s in slices:
            counts[s % self.n_procs] += 1
        return max(counts) if counts else 0

    def _local_slices(self, slices: list[int]) -> list[int]:
        """This process's shard of the item's slice list, padded with -1
        (absent → zero slices, the identity for every reduction) so all
        processes feed identically-shaped shards to the collective —
        deterministic from the item alone, so every process agrees.
        (multihost._pad_local then pads the packed shard to its slice
        BUCKET, so the collective program shape — and hence the compile
        count — is stable as the index grows within a bucket.)"""
        per = self.max_shard_slices(slices)
        mine = self.owned(slices)
        return mine + [-1] * (per - len(mine))

    # -- packing (zeros for absent fragments / pad slices) -------------------

    def _pack_leaves(self, index: str, leaves: list[tuple],
                     local_slices: list[int]) -> np.ndarray:
        from ..ops.packed import WORDS_PER_SLICE
        block = np.zeros(
            (len(leaves), len(local_slices), WORDS_PER_SLICE),
            dtype=np.uint32)
        for li, (frame, view, row_id) in enumerate(leaves):
            for si, s in enumerate(local_slices):
                if s < 0:
                    continue
                frag = self.holder.fragment(index, frame, view, s)
                if frag is not None:
                    frag.pack_row(row_id, out=block[li, si])
        return block

    def _pack_rows(self, index: str, frame: str, row_ids: list[int],
                   local_slices: list[int]) -> np.ndarray:
        from ..models.view import VIEW_STANDARD
        from ..ops.packed import WORDS_PER_SLICE
        rows = np.zeros(
            (len(local_slices), len(row_ids), WORDS_PER_SLICE),
            dtype=np.uint32)
        for si, s in enumerate(local_slices):
            if s < 0:
                continue
            frag = self.holder.fragment(index, frame, VIEW_STANDARD, s)
            if frag is None:
                continue
            cached = len(row_ids) <= frag.device.max_rows
            for ri, rid in enumerate(row_ids):
                frag.pack_row(rid, out=rows[si, ri], cached=cached)
        return rows

    # -- the collective leg (every process runs this) ------------------------

    def run_item(self, item: dict) -> dict:
        """Pack this process's shard and enter the pod-wide collective.

        Called inline by the coordinator and from the ``/pod/exec``
        route by workers. All processes compute the same shard layout
        from the item, so the SPMD programs line up.
        """
        with self._run_mu:
            kind = item["kind"]
            index = item["index"]
            slices = [int(s) for s in item["slices"]]
            leaves = [tuple(leaf) for leaf in item["leaves"]]
            local = self._local_slices(slices)
            mesh = multihost.pod_mesh()
            if kind == "count_expr":
                block = self._pack_leaves(index, leaves, local)
                return {"total": multihost.count_expr(
                    mesh, _expr_from_json(item["expr"]), block)}
            if kind == "count_exprs":
                exprs = tuple(_expr_from_json(e) for e in item["exprs"])
                block = self._pack_leaves(index, leaves, local)
                return {"totals": multihost.count_exprs(mesh, exprs,
                                                        block)}
            if kind == "topn_exact":
                rows = self._pack_rows(index, item["frame"],
                                       item["row_ids"], local)
                lblock = self._pack_leaves(index, leaves, local)
                return {"counts": multihost.topn_exact(
                    mesh, _expr_from_json(item["expr"]), rows, lblock,
                    threshold=int(item.get("threshold", 1)),
                    tanimoto=int(item.get("tanimoto", 0)))}
            raise PodError(f"unknown pod work item kind: {kind}")

    # -- coordinator dispatch ------------------------------------------------

    def _request(self, pid: int, method: str, path: str, body: bytes,
                 content_type: str,
                 sent: Optional[threading.Event] = None) -> bytes:
        """One pod-internal request on the peer's keep-alive connection
        (serialized per peer; reconnect once on a stale socket)."""
        with self._conn_mus[pid]:
            for attempt in range(2):
                conn = self._conns.pop(pid, None)
                fresh = conn is None
                if conn is None:
                    conn = http.client.HTTPConnection(
                        self.peers[pid], timeout=self.timeout)
                else:
                    # Apply the CURRENT pod timeout to the pooled
                    # socket: a connection created during a tight phase
                    # (schema replication, kill detection) must not pin
                    # its old deadline onto a phase that legitimately
                    # allows longer legs (8-way cold-compile warm-up),
                    # nor the reverse.
                    conn.timeout = self.timeout
                    if conn.sock is not None:
                        conn.sock.settimeout(self.timeout)
                try:
                    # Accept mirrors Content-Type: the /import route
                    # negotiates strictly on both (handler 406/415).
                    conn.request(method, path, body=body,
                                 headers={"Content-Type": content_type,
                                          "Accept": content_type})
                except (http.client.HTTPException, OSError):
                    conn.close()
                    if fresh:
                        raise
                    continue  # stale keep-alive socket — retry fresh
                if sent is not None:
                    sent.set()  # delivered — worker enters the collective
                try:
                    resp = conn.getresponse()
                    data = resp.read()
                except (http.client.HTTPException, OSError):
                    conn.close()
                    raise
                if resp.will_close:
                    conn.close()
                else:
                    self._conns[pid] = conn
                if resp.status != 200:
                    raise PodError(f"pod process {pid} {method} {path}:"
                                   f" {data.decode(errors='replace')}")
                return data

    def _post_item(self, pid: int, body: bytes, sent: threading.Event,
                   out: list, errs: list) -> None:
        try:
            out[pid] = json.loads(self._request(
                pid, "POST", "/pod/exec", body, "application/json",
                sent=sent))
        except Exception as e:  # noqa: BLE001 - collected by dispatcher
            errs.append((pid, e))

    def _dispatch(self, item: dict) -> dict:
        """Broadcast the item to every worker, run our own leg, verify
        all legs agree (they all hold the same psum result)."""
        if self._poisoned:
            raise PodError("pod collective path disabled after an earlier"
                           " partial dispatch failure (restart the pod)")
        body = json.dumps(item).encode()
        out: list = [None] * self.n_procs
        errs: list = []
        sent_events = []
        threads = []
        with self._dispatch_mu:
            for pid in range(1, self.n_procs):
                sent = threading.Event()
                t = threading.Thread(
                    target=self._post_item, args=(pid, body, sent, out,
                                                  errs), daemon=True)
                t.start()
                sent_events.append((pid, sent))
                threads.append(t)
            # Only enter the collective once every worker has the item —
            # entering with a worker unreachable would stall the pod
            # until the collective layer times out.
            delivered = []
            undelivered = []
            for pid, sent in sent_events:
                (delivered if sent.wait(min(self.timeout, 15.0))
                 else undelivered).append(pid)
            if undelivered:
                if delivered:
                    # Some workers are already entering the orphaned
                    # collective; a new one would cross-match with it.
                    self._poisoned = True
                raise PodError(
                    f"pod processes {undelivered} not reachable for"
                    " work-item broadcast"
                    + (" — pod collective path disabled" if delivered
                       else ""))
            # Run our own leg BOUNDED by the pod timeout: a worker
            # that dies after receiving the item leaves the collective
            # stalled, and gloo would park this thread indefinitely —
            # the timeout converts the stall into a poisoned pod with
            # the host fan-out still serving (the reference's analogue
            # is a TPU pod job failing as one unit).
            box: dict = {}

            def run_leg():
                try:
                    box["out"] = self.run_item(item)
                except BaseException as e:  # noqa: BLE001 - re-raised below
                    box["err"] = e

            leg = threading.Thread(target=run_leg, daemon=True)
            leg.start()
            leg.join(self.timeout)
            if leg.is_alive():
                self._poisoned = True
                raise PodError(
                    f"pod collective stalled past {self.timeout:.0f}s "
                    "(worker died mid-collective?) — pod collective "
                    "path disabled")
            if "err" in box:
                # The collective itself failed (e.g. a worker died after
                # receiving the item) — remaining processes may be parked
                # in it; nothing further can safely pair up.
                self._poisoned = True
                raise box["err"]
            mine = box["out"]
            for t in threads:
                t.join()
        if errs:
            pid, e = errs[0]
            raise PodError(f"pod process {pid} failed: {e}") from e
        for pid in range(1, self.n_procs):
            if out[pid] != mine:
                raise PodError(
                    f"pod divergence: process {pid} returned {out[pid]},"
                    f" coordinator computed {mine}")
        kind = item["kind"]
        self.dispatch_counts[kind] = self.dispatch_counts.get(kind, 0) + 1
        return mine

    def count_expr(self, index: str, expr: tuple, leaves: list[tuple],
                   slices: list[int]) -> int:
        if not slices:
            return 0
        return self._dispatch({
            "kind": "count_expr", "index": index, "expr": expr,
            "leaves": [list(leaf) for leaf in leaves],
            "slices": sorted(slices)})["total"]

    def count_exprs(self, index: str, exprs: list[tuple],
                    leaves: list[tuple], slices: list[int]) -> list[int]:
        """K batched Counts in one pod collective (one work item, one
        dispatch) — the pod form of executor._device_batch_run's
        counts-only lane."""
        if not slices:
            return [0] * len(exprs)
        return self._dispatch({
            "kind": "count_exprs", "index": index,
            "exprs": list(exprs),
            "leaves": [list(leaf) for leaf in leaves],
            "slices": sorted(slices)})["totals"]

    def topn_exact(self, index: str, frame: str, expr, leaves: list[tuple],
                   row_ids: list[int], slices: list[int],
                   threshold: int = 1, tanimoto: int = 0) -> list[int]:
        if not slices or not row_ids:
            return [0] * len(row_ids)
        return self._dispatch({
            "kind": "topn_exact", "index": index, "frame": frame,
            "expr": expr, "leaves": [list(leaf) for leaf in leaves],
            "row_ids": [int(r) for r in row_ids],
            "threshold": int(threshold), "tanimoto": int(tanimoto),
            "slices": sorted(slices)})["counts"]

    # -- pod-internal forwarding helpers -------------------------------------

    def forward_raw(self, pid: int, method: str, path: str, body: bytes,
                    content_type: str) -> bytes:
        """One pod-internal HTTP request (import forwarding, schema
        replication) on the peer's keep-alive connection."""
        return self._request(pid, method, path, body, content_type)


class PodBroadcaster:
    """Wraps the coordinator's cluster broadcaster so schema mutations
    also reach every pod worker (their ``/messages`` route) — workers
    are not cluster nodes, but must hold the same schema to serve
    pod-internal legs."""

    def __init__(self, base, pod: Pod):
        self.base = base
        self.pod = pod

    def _pod_send(self, m) -> None:
        from ..cluster.broadcast import marshal_message
        body = marshal_message(m)
        errs = []
        threads = []

        def post(pid):
            try:
                self.pod.forward_raw(pid, "POST", "/messages", body,
                                     "application/x-protobuf")
            except Exception as e:  # noqa: BLE001 - collected below
                errs.append(e)

        for pid in range(1, self.pod.n_procs):
            t = threading.Thread(target=post, args=(pid,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if errs:
            raise errs[0]

    def send_sync(self, m) -> None:
        self.base.send_sync(m)
        self._pod_send(m)

    def send_async(self, m) -> None:
        self.base.send_async(m)
        threading.Thread(target=lambda: self._quiet_pod_send(m),
                         daemon=True).start()

    def _quiet_pod_send(self, m) -> None:
        try:
            self._pod_send(m)
        except Exception:  # noqa: BLE001 - async sends are best-effort
            pass


def maybe_pod(holder) -> Optional[Pod]:
    """A Pod when the multihost env contract is active with >1 process;
    None in the ordinary single-process server."""
    if not multihost.initialize_from_env():
        return None
    import jax
    if jax.process_count() <= 1:
        return None
    peers = [p.strip()
             for p in os.environ.get(ENV_PEERS, "").split(",") if p.strip()]
    return Pod(holder, peers)
