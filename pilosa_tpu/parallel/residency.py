"""HBM working-set manager: device residency for hot fragment rows.

The reference mutates mmap'd bitmaps in place and relies on the OS page
cache plus its own row cache for hot-row reuse (fragment.go:338-367);
device arrays are immutable and HBM is smaller than the on-disk index,
so device state is an explicit, *budgeted* cache:

- ``DeviceBlockCache`` — a process-wide LRU over device-resident packed
  blocks with an HBM byte budget (PILOSA_TPU_HBM_BUDGET_MB). Entries
  are the executor's mesh leaf blocks (one [slices, words] slab per
  PQL leaf row), the mesh TopN candidate blocks, and each fragment's
  single-device candidate blocks. The hot entries are exactly the rank
  cache's top rows — LRU over query use keeps that working set pinned
  while bounded eviction stops 50k-rows × many-fragments from
  exceeding HBM (SURVEY §7 hard part 2).
- ``DeviceRowCache`` — per-fragment host-side LRU of packed row words
  (feeds block builds and mesh uploads; invalidated per row by writes).

Staleness is handled by keys, not callbacks: every cached block's key
embeds the owning fragments' ``(uid, generation)`` pairs — writes bump
the generation, fragment reopen mints a fresh uid — so stale entries
simply stop being referenced and age out of the LRU.

Upload layout: the globally-sharded slab builders (``leaf_slab``,
``candidate_block``) pad the slice axis to its canonical bucket
(parallel.programs.slice_bucket) before the device_put, so every
resident array already has the bucket-stable shape the program
catalogue compiles for — growing an index within a bucket re-uses both
the compiled programs AND the upload path's shapes.

Host container kinds are invisible past this layer: the extraction
feeding both the sparse and dense upload legs (ops.packed
sparse_row_words / pack_bitmap) decodes array, bitmap, AND run
containers to the same word form, so run-compressed fragments (the
memory win that lets more of the matrix fit in HBM) ride the existing
bucket-padded path with no residency-side special case.
"""

from __future__ import annotations

import itertools
import os
import threading
from collections import OrderedDict
from typing import Callable, Optional

import jax
import numpy as np

from ..ops import packed

# Default packed-row budget per fragment (256 rows × 128 KB = 32 MB
# host-side).
DEFAULT_MAX_ROWS = 256

# Process-wide HBM budget for device-resident blocks. v5e chips have
# ~16 GB HBM; leave headroom for the programs' own activations.
DEFAULT_HBM_BUDGET_MB = 1024

_uid_counter = itertools.count(1)


class DeviceBlockCache:
    """Budgeted process-wide LRU of device-resident arrays.

    Thread-safe. An entry larger than the whole budget is returned
    uncached (one-shot upload) rather than evicting everything else.
    """

    def __init__(self, budget_bytes: Optional[int] = None):
        if budget_bytes is None:
            budget_bytes = int(os.environ.get(
                "PILOSA_TPU_HBM_BUDGET_MB", str(DEFAULT_HBM_BUDGET_MB))
            ) << 20
        self.budget_bytes = budget_bytes
        self._mu = threading.Lock()
        self._lru: OrderedDict[tuple, jax.Array] = OrderedDict()
        self.used_bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @staticmethod
    def _nbytes(arr) -> int:
        return int(np.prod(arr.shape)) * arr.dtype.itemsize

    def get_or_build(self, key: tuple,
                     build: Callable[[], jax.Array]) -> jax.Array:
        with self._mu:
            arr = self._lru.get(key)
            if arr is not None:
                self._lru.move_to_end(key)
                self.hits += 1
                return arr
            self.misses += 1
        # Build outside the lock: packing + device_put can take long and
        # must not serialize unrelated queries. Concurrent builders of
        # the same key race benignly (last insert wins).
        arr = build()
        nbytes = self._nbytes(arr)
        if nbytes > self.budget_bytes:
            return arr  # one-shot: bigger than the whole working set
        with self._mu:
            if key not in self._lru:
                self._lru[key] = arr
                self.used_bytes += nbytes
            self._lru.move_to_end(key)
            # len > 1 keeps the just-built entry (now most-recent) alive.
            while self.used_bytes > self.budget_bytes and len(self._lru) > 1:
                _, old = self._lru.popitem(last=False)
                self.used_bytes -= self._nbytes(old)
                self.evictions += 1
        return arr

    def contains(self, key: tuple) -> bool:
        """Residency probe WITHOUT touching LRU order — the routing
        cost model asks whether an upload would be needed."""
        with self._mu:
            return key in self._lru

    def clear(self) -> None:
        with self._mu:
            self._lru.clear()
            self.used_bytes = 0

    def snapshot(self) -> dict:
        with self._mu:
            return {"entries": len(self._lru),
                    "usedBytes": self.used_bytes,
                    "budgetBytes": self.budget_bytes,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}


_device_cache: Optional[DeviceBlockCache] = None
_device_cache_mu = threading.Lock()


def device_cache() -> DeviceBlockCache:
    """The process-wide device block cache (lazy singleton)."""
    global _device_cache
    with _device_cache_mu:
        if _device_cache is None:
            _device_cache = DeviceBlockCache()
        return _device_cache


def _bucketed_slices(mesh, n_slices: int) -> int:
    """The bucket-padded slice count an upload for ``n_slices`` uses
    (zero slices are the identity for every count/TopN reduction)."""
    from . import mesh as mesh_mod
    from . import programs
    return programs.slice_bucket(n_slices,
                                 mesh.shape[mesh_mod.AXIS_SLICES])


def leaf_slab(mesh, key: tuple, frags: list, row_id: int) -> jax.Array:
    """Device-resident ``[bucket(n_slices), words]`` slab of one PQL
    leaf row across ``frags`` (one fragment per slice, None = absent =
    zero words), globally sharded over the slice axis and held in the
    budgeted HBM cache under ``key``.

    The caller owns the key contract (executor embeds every backing
    fragment's (uid, generation), so writes/reopens age entries out of
    the LRU); this builder owns the transfer: sparse-gate → bucketed
    sparse upload + on-device densify when it wins, dense host pack
    otherwise — always at the bucket-padded, program-stable shape."""
    from . import mesh as mesh_mod

    def build():
        from ..ops import packed
        n = _bucketed_slices(mesh, len(frags))
        mode = mesh_mod.densify_mode()
        pairs = [frag.sparse_row_pairs(row_id)
                 if frag is not None else None for frag in frags]
        pairs += [None] * (n - len(pairs))
        if mode is not None:
            use_sparse, plan = packed.sparse_gate(
                pairs, packed.WORDS_PER_SLICE)
            if use_sparse:
                subs = packed.WORDS_PER_SLICE // 128
                lanes, vals = packed.bucket_prepared(pairs, subs,
                                                     plan=plan)
                return mesh_mod.densify_sharded(
                    mesh, lanes, vals, interpret=(mode == "interpret"))
        block = packed.densify_host(pairs, packed.WORDS_PER_SLICE)
        return mesh_mod.shard_slices(mesh, block)

    return device_cache().get_or_build(key, build)


def candidate_block(mesh, key: tuple, frags: list,
                    row_ids: tuple) -> jax.Array:
    """Device-resident ``[bucket(n_slices), n_rows, words]`` TopN
    candidate block (same key/staleness contract as ``leaf_slab``),
    bucket-padded and slice-sharded — repeat TopN queries skip the
    per-query pack + upload entirely."""
    from . import mesh as mesh_mod

    def build():
        from ..ops import packed
        n = _bucketed_slices(mesh, len(frags))
        # Extract once as sparse (word idx, value) pairs; the gate
        # then picks the transfer representation — bucketed sparse +
        # device densify (3-6x cold-upload win at sparse shapes,
        # benchmarks/DENSIFY.json) or host dense scatter.
        mode = mesh_mod.densify_mode()
        pairs: list = []
        for si in range(n):
            frag = frags[si] if si < len(frags) else None
            for rid in row_ids:
                pairs.append(None if frag is None
                             else frag.sparse_row_pairs(rid))
        if mode is not None:
            use_sparse, plan = packed.sparse_gate(
                pairs, packed.WORDS_PER_SLICE)
            if use_sparse:
                subs = packed.WORDS_PER_SLICE // 128
                lanes, vals = packed.bucket_prepared(pairs, subs,
                                                     plan=plan)
                shp = (n, len(row_ids)) + lanes.shape[1:]
                return mesh_mod.densify_sharded(
                    mesh, lanes.reshape(shp), vals.reshape(shp),
                    interpret=(mode == "interpret"))
        rows = packed.densify_host(
            pairs, packed.WORDS_PER_SLICE).reshape(
                n, len(row_ids), packed.WORDS_PER_SLICE)
        return mesh_mod.shard_slices(mesh, rows)

    return device_cache().get_or_build(key, build)


class DeviceRowCache:
    """Per-fragment residency state: host packed-row LRU + the device
    block handle into the shared ``DeviceBlockCache``."""

    def __init__(self, max_rows: int = DEFAULT_MAX_ROWS):
        self.max_rows = max_rows
        # Host-side packed words, feeding the device row blocks and the
        # executor's mesh block builds (which stack rows across
        # fragments host-side before one sharded device_put).
        self._host_rows: OrderedDict[int, np.ndarray] = OrderedDict()
        # (uid, generation) is this fragment's staleness key fragment:
        # generation bumps on every write-invalidation; uid is unique
        # per DeviceRowCache instance so a reopened fragment at
        # generation 0 can never alias a prior instance's entries.
        self.uid = next(_uid_counter)
        self.generation = 0

    # -- single rows

    def host_row_words(self, storage, row_id: int) -> np.ndarray:
        """Packed host words for one row (read-only view); caches on miss.

        ``storage`` is the fragment-local roaring bitmap
        (pos = row*SLICE_WIDTH + col).
        """
        words = self._host_rows.get(row_id)
        if words is not None:
            self._host_rows.move_to_end(row_id)
            return words
        words = np.zeros(packed.WORDS_PER_SLICE, dtype=np.uint32)
        packed.pack_storage_row(storage, row_id, words)
        words.flags.writeable = False  # callers copy, never mutate
        self._host_rows[row_id] = words
        while len(self._host_rows) > self.max_rows:
            self._host_rows.popitem(last=False)
        return words

    def invalidate_row(self, row_id: int) -> None:
        self._host_rows.pop(row_id, None)
        self.generation += 1

    def invalidate_rows(self, row_ids) -> None:
        """Batch invalidation: one generation bump for the whole write
        batch (the key embeds the generation, so one bump suffices)."""
        pop = self._host_rows.pop
        for rid in row_ids:
            pop(rid, None)
        self.generation += 1

    def invalidate_all(self) -> None:
        self._host_rows.clear()
        self.generation += 1

    # -- row blocks (TopN candidates), budgeted in the shared cache

    def block(self, storage, row_ids: tuple[int, ...]) -> jax.Array:
        """Stacked u32[n, 32768] device matrix for the given rows, held
        in the process-wide budgeted cache keyed by this fragment's
        (uid, generation) + the id tuple."""
        key = ("fragblock", self.uid, self.generation, row_ids)
        return device_cache().get_or_build(
            key, lambda: jax.device_put(packed.pack_rows(storage, row_ids)))
