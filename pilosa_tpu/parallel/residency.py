"""HBM working-set manager: device residency for hot fragment rows.

The reference mutates mmap'd bitmaps in place; device arrays are immutable
and HBM is smaller than the on-disk index, so device state is an explicit
cache with two layers: a host-side LRU of packed row words (feeding the
executor's mesh block builds and device uploads; invalidated per row by
writes, bounded by ``max_rows``), and the TopN candidate row *block* — a
stacked u32 matrix pinned in HBM as a unit, keyed by (row ids, write
generation) since the rank cache already identifies the hot rows.

One manager exists per fragment (pilosa_tpu.storage.fragment.Fragment).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import jax
import numpy as np

from .. import SLICE_WIDTH
from ..ops import packed

# Default packed-row budget per fragment (256 rows × 128 KB = 32 MB
# host-side; the device holds only the TopN block).
DEFAULT_MAX_ROWS = 256


class DeviceRowCache:
    def __init__(self, max_rows: int = DEFAULT_MAX_ROWS):
        self.max_rows = max_rows
        # Host-side packed words, feeding the device row blocks and the
        # executor's mesh block builds (which stack rows across
        # fragments host-side before one sharded device_put).
        self._host_rows: OrderedDict[int, np.ndarray] = OrderedDict()
        # Write generation: bumped on every invalidation so cached row
        # blocks (keyed by ids+generation) go stale automatically.
        self.generation = 0
        self._block_key: Optional[tuple] = None
        self._block: Optional[jax.Array] = None

    # -- single rows

    def host_row_words(self, storage, row_id: int) -> np.ndarray:
        """Packed host words for one row (read-only view); caches on miss.

        ``storage`` is the fragment-local roaring bitmap
        (pos = row*SLICE_WIDTH + col).
        """
        words = self._host_rows.get(row_id)
        if words is not None:
            self._host_rows.move_to_end(row_id)
            return words
        words = np.zeros(packed.WORDS_PER_SLICE, dtype=np.uint32)
        packed.pack_storage_row(storage, row_id, words)
        words.flags.writeable = False  # callers copy, never mutate
        self._host_rows[row_id] = words
        while len(self._host_rows) > self.max_rows:
            self._host_rows.popitem(last=False)
        return words

    def invalidate_row(self, row_id: int) -> None:
        self._host_rows.pop(row_id, None)
        self.generation += 1

    def invalidate_all(self) -> None:
        self._host_rows.clear()
        self._block_key = None
        self._block = None
        self.generation += 1

    # -- row blocks (TopN candidates)

    def block(self, storage, row_ids: tuple[int, ...]) -> jax.Array:
        """Stacked u32[n, 32768] device matrix for the given rows, cached by
        (ids, generation)."""
        key = (row_ids, self.generation)
        if self._block_key == key:
            return self._block
        matrix = packed.pack_rows(storage, row_ids)
        self._block = jax.device_put(matrix)
        self._block_key = key
        return self._block

