"""The shape-stable device program catalogue: global-view pjit programs
over globally-sharded bit-plane arrays.

The original mesh layer built one ``shard_map`` program per (expr,
n_leaves, slice-count) — 13 separately-cached per-shape builders whose
compile count scaled with the slice counts a deployment happened to
serve, paying a measured multi-second cold-compile tax on the first
device query after restart (VERDICT r5 weak #2). This module replaces
the per-shard form with the modern global-view idiom for exactly our
shape — one logical (rows × columns) bit matrix partitioned by column
across the mesh:

- programs are plain ``jax.jit`` over *global* arrays with explicit
  ``NamedSharding``/``with_sharding_constraint`` placement (the GSPMD
  partitioner inserts the cross-device reductions, so the final
  Count/TopN merge is an in-program all-reduce, not a host-side fold);
- the slice axis is padded to a few canonical **buckets**
  (``slice_bucket``: the smallest ``n_devices × 2^k`` covering the
  slice count), so the compile count is bounded by the bucket count —
  O(log max_slices) — instead of scaling with every distinct slice
  count (zero slices are the identity for every count/TopN reduction,
  so bucket padding is exact);
- multi-op PQL trees (several Counts, TopN exact-count blocks, BSI
  compare-select circuits) fuse into ONE XLA computation returning one
  stacked (hi, lo) output — one dispatch, one host fetch per tree
  (``fused_program``);
- streaming operands (blocks re-packed per query, never reused) are
  **donated** on real accelerators so XLA reuses their HBM instead of
  copying (donation is gated off host backends, where it only warns).

The same programs lower unchanged to the multi-host pod path: under
SPMD every process runs the identical jitted computation over the
global array assembled from its local shard
(``jax.make_array_from_process_local_data``), and the in-program
reduction spans the pod.

Pallas-bodied variants (the TPU fused kernels) keep their ``shard_map``
form in ``parallel.mesh`` — ``pallas_call`` is a per-shard primitive —
and the dispatch layer picks per backend; this catalogue is the XLA
serving path (the recorded A/B winner) and the one tests exercise on
the virtual CPU mesh.

Every builder is ``lru_cache``'d and finalized through
``mesh._finalize_program`` so the compile-cache counters
(hits/misses/first-call seconds) keep answering "is the cache hitting,
and does anything warm it" for the new program set too.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from . import mesh as mesh_mod

# The program catalogue: every shape-stable program kind this module
# can build, in warmup order. sched.warmup compiles these against the
# holder's actual max-slice bucket at startup/fragment load, and
# /status reports coverage against this list.
CATALOGUE = (
    "count_fold",          # K=1 count over resident leaf slabs
    "count_batch",         # K-expression fused count batch
    "topn_exact",          # TopN exact-count block, psum'd in-program
    "topn_filtered",       # per-slice threshold/Tanimoto pruning form
    "topn_topk",           # sourceless TopN: top-k selected IN-PROGRAM
    "materialize",         # dense expression words, sharded output
    "bsi_compare_select",  # BSI comparison circuit over bit-planes
    "fused_tree",          # Counts + TopN blocks in ONE computation
)


def slice_bucket(n_slices: int, n_dev: int) -> int:
    """The canonical padded slice count for ``n_slices`` on an
    ``n_dev``-device mesh: the smallest ``n_dev * 2^k`` that covers it,
    capped at the int32 hi/lo chunk bound. Callers pad the slice axis
    to the bucket (zero slices are the reduction identity), so every
    slice count in (bucket/2, bucket] reuses ONE compiled program —
    compile count stops scaling with slice count. Counts above the
    largest bucket fall back to plain device-multiple padding (the
    chunking layers bound them anyway)."""
    if n_slices <= 0:
        return n_dev
    bound = mesh_mod.slice_chunk_bound(n_dev)
    b = n_dev
    while b < n_slices and b * 2 <= bound:
        b *= 2
    if b >= n_slices:
        return b
    return n_slices + (-n_slices % n_dev)


def bucket_pad(arr: np.ndarray, axis: int, n_dev: int) -> np.ndarray:
    """Pad ``axis`` (the slice axis) with zero slices up to its bucket."""
    target = slice_bucket(arr.shape[axis], n_dev)
    if arr.shape[axis] == target:
        return arr
    pad = [(0, 0)] * arr.ndim
    pad[axis] = (0, target - arr.shape[axis])
    return np.pad(arr, pad)


def _slice_sharding(mesh) -> NamedSharding:
    return NamedSharding(mesh, P(mesh_mod.AXIS_SLICES))


def _donate_kw(mesh, n_args: int, skip: int = 0) -> dict:
    """donate_argnums for streaming operands — real accelerators only:
    on host backends donation is ignored with a per-call warning, and
    there is no HBM copy to save."""
    if mesh.devices.flat[0].platform == "cpu":
        return {}
    return {"donate_argnums": tuple(range(skip, skip + n_args))}


def _hi_lo_rows(per_slice):
    """[S, R] per-(slice, row) counts → [2, R] (hi, lo) 16-bit halves,
    summed over the global slice axis. The sum over the sharded axis is
    the in-program reduction: GSPMD lowers it to per-shard partial sums
    plus one all-reduce riding the interconnect — the collective form
    of the reference's cross-node merge. Same int32-safety split as the
    per-shard form (counts ≤ 2^20 per row, ≤ 2^15 slice rows)."""
    hi = jnp.sum(per_slice >> 16, axis=0)
    lo = jnp.sum(per_slice & 0xFFFF, axis=0)
    return jnp.stack([hi, lo])


@functools.lru_cache(maxsize=256)
def count_exprs_program(mesh, exprs: tuple, n_leaves: int):
    """K expression counts over ``n_leaves`` separate [S_b, W] leaf
    slabs (each globally sharded over the slice axis — the residency
    cache's native layout) → one [2, K] (hi, lo) output. The whole
    expression set evaluates elementwise over every slice at once; the
    final reduction is in-program."""
    sh = _slice_sharding(mesh)

    def fn(*leaf_shards):
        leaves = jnp.stack([
            jax.lax.with_sharding_constraint(a, sh)
            for a in leaf_shards])
        his, los = mesh_mod._exprs_hi_lo(exprs, leaves, None)
        return jnp.stack([his, los])

    return mesh_mod._finalize_program(jax.jit(fn))


@functools.lru_cache(maxsize=256)
def count_exprs_block_program(mesh, exprs: tuple):
    """The streaming-block form: one [L, S_b, W] stacked leaf block
    (freshly packed per query — the operand is DONATED on accelerators)
    → [2, K]. Public shape contract of mesh.count_expr_fn, reused by
    the multi-host pod path with process-local shards."""
    sh = NamedSharding(mesh, P(None, mesh_mod.AXIS_SLICES))

    def fn(leaves):
        leaves = jax.lax.with_sharding_constraint(leaves, sh)
        his, los = mesh_mod._exprs_hi_lo(exprs, leaves, None)
        return jnp.stack([his, los])

    return mesh_mod._finalize_program(
        jax.jit(fn, **_donate_kw(mesh, 1)))


@functools.lru_cache(maxsize=256)
def topn_program(mesh, expr, n_leaves: int, filtered: bool):
    """TopN exact-count block: rows [S_b, R, W] + ``n_leaves`` leaf
    slabs → [2, R] per-candidate (hi, lo), reduced in-program.
    ``filtered`` engages the per-slice threshold/Tanimoto pruning
    (runtime scalars — one program per (expr, shape))."""
    sh = _slice_sharding(mesh)

    def stack_leaves(rows, leaf_shards):
        if leaf_shards:
            return jnp.stack([
                jax.lax.with_sharding_constraint(a, sh)
                for a in leaf_shards])
        return jnp.zeros((0,) + rows.shape[::2], dtype=rows.dtype)

    if filtered:
        def fn(threshold, tanimoto, rows, *leaf_shards):
            rows = jax.lax.with_sharding_constraint(rows, sh)
            return _hi_lo_rows(mesh_mod._filtered_counts(
                expr, rows, stack_leaves(rows, leaf_shards),
                threshold, tanimoto, None))
    else:
        def fn(rows, *leaf_shards):
            rows = jax.lax.with_sharding_constraint(rows, sh)
            return _hi_lo_rows(mesh_mod._shard_topn_inter(
                expr, rows, stack_leaves(rows, leaf_shards), None))

    return mesh_mod._finalize_program(jax.jit(fn))


@functools.lru_cache(maxsize=256)
def topn_block_program(mesh, expr, filtered: bool):
    """Streaming TopN form: rows [S_b, R, W] + one [L, S_b, W] leaf
    block, both freshly packed per query (donated on accelerators).
    The pod path's shape contract (mesh.topn_exact_fn)."""
    sh = _slice_sharding(mesh)
    lsh = NamedSharding(mesh, P(None, mesh_mod.AXIS_SLICES))

    if filtered:
        def fn(threshold, tanimoto, rows, leaves):
            rows = jax.lax.with_sharding_constraint(rows, sh)
            leaves = jax.lax.with_sharding_constraint(leaves, lsh)
            return _hi_lo_rows(mesh_mod._filtered_counts(
                expr, rows, leaves, threshold, tanimoto, None))
        donate = _donate_kw(mesh, 2, skip=2)
    else:
        def fn(rows, leaves):
            rows = jax.lax.with_sharding_constraint(rows, sh)
            leaves = jax.lax.with_sharding_constraint(leaves, lsh)
            return _hi_lo_rows(mesh_mod._shard_topn_inter(
                expr, rows, leaves, None))
        donate = _donate_kw(mesh, 2)

    return mesh_mod._finalize_program(jax.jit(fn, **donate))


@functools.lru_cache(maxsize=128)
def topn_topk_program(mesh, expr, n_leaves: int, k: int):
    """In-program top-k for the sourceless TopN forms (the ROADMAP
    item-1 leftover): rows [S_b, R, W] (+ optional leaf slabs) →
    [3, k] int32 (hi, lo, row index). The per-candidate (hi, lo)
    16-bit halves reduce in-program as usual, then ONE lexicographic
    ``lax.sort`` over (hi, lo, -index) selects the winners on device —
    exact even though counts exceed int32 as a single key, and the
    host fetch shrinks from O(R) to O(k). Tie-break is ascending row
    index, matching the host pairs_sort order bit-for-bit. One program
    per (expr, shape, k); k values in the wild are the handful of
    TopN(n=...) sizes a deployment serves."""
    sh = _slice_sharding(mesh)

    def fn(rows, *leaf_shards):
        rows = jax.lax.with_sharding_constraint(rows, sh)
        if leaf_shards:
            leaves = jnp.stack([
                jax.lax.with_sharding_constraint(a, sh)
                for a in leaf_shards])
        else:
            leaves = jnp.zeros((0,) + rows.shape[::2], dtype=rows.dtype)
        per_slice = mesh_mod._shard_topn_inter(expr, rows, leaves, None)
        hi = jnp.sum(per_slice >> 16, axis=0).astype(jnp.int32)
        lo = jnp.sum(per_slice & 0xFFFF, axis=0).astype(jnp.int32)
        # Normalize the halves before the sort: the lo-sum reaches
        # n_slices * 0xFFFF, so without carrying its overflow into hi
        # the lexicographic order diverges from true count order
        # (e.g. (hi=1, lo=0) would outrank (hi=0, lo=131070)). The
        # host decode (hi<<16)+lo is invariant under this shift.
        hi = hi + (lo >> 16)
        lo = lo & 0xFFFF
        idx = jax.lax.iota(jnp.int32, hi.shape[0])
        shi, slo, sneg = jax.lax.sort((hi, lo, -idx), num_keys=3)
        return jnp.stack([shi[::-1][:k], slo[::-1][:k],
                          -sneg[::-1][:k]])

    return mesh_mod._finalize_program(jax.jit(fn))


@functools.lru_cache(maxsize=256)
def materialize_program(mesh, expr, n_leaves: int):
    """Dense [S_b, W] words of the expression bitmap over resident leaf
    slabs; the output keeps the slice sharding (the host fetches it
    once for roaring repack)."""
    sh = _slice_sharding(mesh)

    def fn(*leaf_shards):
        leaves = jnp.stack([
            jax.lax.with_sharding_constraint(a, sh)
            for a in leaf_shards])
        return jax.lax.with_sharding_constraint(
            mesh_mod._eval_expr(expr, leaves), sh)

    return mesh_mod._finalize_program(jax.jit(fn))


@functools.lru_cache(maxsize=256)
def bsi_range_program(mesh, op: str, n_planes: int):
    """The whole BSI comparison circuit (existence row + value planes)
    as one computation over ``n_planes`` resident plane slabs → dense
    [S_b, W] matched words, sharded output. The predicate travels as a
    traced LSB-first bit vector, so every range query at one depth
    reuses the compilation."""
    from ..ops import kernels
    sh = _slice_sharding(mesh)

    def fn(pbits, pbits2, *plane_shards):
        planes = jnp.stack([
            jax.lax.with_sharding_constraint(a, sh)
            for a in plane_shards])
        if op == "><":
            ge = kernels.bsi_compare_select(">=", pbits, planes)
            le = kernels.bsi_compare_select("<=", pbits2, planes)
            out = jnp.bitwise_and(ge, le)
        else:
            out = kernels.bsi_compare_select(op, pbits, planes)
        return jax.lax.with_sharding_constraint(out, sh)

    return mesh_mod._finalize_program(jax.jit(fn))


@functools.lru_cache(maxsize=128)
def fused_program(mesh, count_exprs: tuple, topn_exprs: tuple,
                  n_leaves: int):
    """A whole multi-op PQL tree as ONE XLA computation: K expression
    counts plus M TopN exact-count blocks (``topn_exprs`` =
    ((expr, n_rows), ...)) over one shared deduplicated leaf-slab set
    → a single [2, K + Σ n_rows] (hi, lo) output. One dispatch, one
    in-program reduction, one host fetch for the whole tree — the
    device form of the reference's strictly sequential per-call
    execution (the calls are independent reads, so fusing them is
    observationally identical). Decode with ``hilo_combine`` and split
    at the K/candidate offsets."""
    sh = _slice_sharding(mesh)

    def fn(*args):
        leaf_shards = args[:n_leaves]
        rows_blocks = args[n_leaves:]
        if leaf_shards:
            leaves = jnp.stack([
                jax.lax.with_sharding_constraint(a, sh)
                for a in leaf_shards])
        else:
            leaves = jnp.zeros((0,) + rows_blocks[0].shape[::2],
                               dtype=rows_blocks[0].dtype)
        parts_hi, parts_lo = [], []
        if count_exprs:
            his, los = mesh_mod._exprs_hi_lo(count_exprs, leaves, None)
            parts_hi.append(his)
            parts_lo.append(los)
        for (expr_t, _n_rows), rows in zip(topn_exprs, rows_blocks):
            rows = jax.lax.with_sharding_constraint(rows, sh)
            per_slice = mesh_mod._shard_topn_inter(expr_t, rows,
                                                   leaves, None)
            parts_hi.append(jnp.sum(per_slice >> 16, axis=0))
            parts_lo.append(jnp.sum(per_slice & 0xFFFF, axis=0))
        return jnp.stack([jnp.concatenate(parts_hi),
                          jnp.concatenate(parts_lo)])

    return mesh_mod._finalize_program(jax.jit(fn))


# Builder caches, appended to mesh._PROGRAM_CACHES so compile_stats()
# aggregates hits/misses over the catalogue too.
PROGRAM_CACHES = (
    count_exprs_program, count_exprs_block_program, topn_program,
    topn_block_program, topn_topk_program, materialize_program,
    bsi_range_program, fused_program,
)
